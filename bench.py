"""Benchmark: FFAT sliding-window sum throughput on one chip (the north-star
metric, BASELINE.json: "tuples/sec/chip on FFAT sliding-window sum; p99
window latency").

Runs the flagship per-batch program (see ``__graft_entry__.entry``): staged
batches of ``CAP`` tuples over ``K`` keys, count-based sliding window
``WIN``/``SLIDE`` decomposed into panes, all fired windows of all keys
computed in one fused XLA program per batch.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The reference publishes no in-repo numbers (BASELINE.md — `published: {}`),
so ``vs_baseline`` is measured against our own previous round's number for
the same platform, persisted in ``bench_history.json``.

Robustness (the round-1 bench died to a hung TPU backend init and left no
artifact): the TPU backend is probed in a *subprocess* with a bounded
timeout and one retry; on failure the bench falls back to the CPU backend so
a number (clearly labelled with its platform + the TPU failure diagnosis) is
always recorded.  Exit code is 0 whenever a value was measured.
"""

import json
import math
import os
import subprocess
import sys
import time
from typing import Optional

TPU_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT", "150"))
TPU_PROBE_RETRIES = int(os.environ.get("BENCH_TPU_RETRIES", "1"))
HISTORY_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_history.json")

#: per-platform workload configs (kept stable across rounds so
#: round-over-round vs_baseline is meaningful per platform)
CONFIGS = {
    # sweet spot on v5e: the sliding-reduce kernel is dispatch-bound
    # below ~128k tuples per staged batch
    "tpu": dict(cap=262144, keys=1024, win=1024, slide=128,
                warmup=6, steps=40, lat_steps=20,
                e2e_tuples=16 * 262144, e2e_warm_tuples=2 * 262144),
    # CPU fallback: smaller so a diagnostic number lands in minutes
    "cpu": dict(cap=65536, keys=256, win=1024, slide=128,
                warmup=2, steps=10, lat_steps=5,
                e2e_tuples=16 * 65536, e2e_warm_tuples=2 * 65536),
}


def probe_tpu() -> tuple:
    """Check, in a subprocess with a hard timeout, that the default (axon
    TPU) backend can initialize and run one op.  Returns
    (ok, diagnosis, attempts) — ``attempts`` records every probe's outcome
    so a fallback artifact shows exactly what was tried and when."""
    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "x = (jnp.ones(8) * 2).block_until_ready();"
            "print('PROBE_OK', d[0].platform, d[0])")
    last = ""
    attempts = []
    for attempt in range(1 + TPU_PROBE_RETRIES):
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=TPU_PROBE_TIMEOUT_S)
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                dev = r.stdout.strip().split("PROBE_OK", 1)[1].strip()
                attempts.append({"at": stamp, "ok": True, "device": dev})
                return True, dev, attempts
            tail = (r.stderr or r.stdout).strip().splitlines()
            last = tail[-1][:300] if tail else f"rc={r.returncode}"
        except subprocess.TimeoutExpired:
            last = (f"backend init hung > {TPU_PROBE_TIMEOUT_S}s "
                    "(axon tunnel unresponsive)")
        attempts.append({"at": stamp, "ok": False, "error": last})
    return False, last, attempts


def run_bench(platform: str, cfg: dict, jax) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)

    CAP, K = cfg["cap"], cfg["keys"]
    Pn = math.gcd(cfg["win"], cfg["slide"])
    R, D = cfg["win"] // Pn, cfg["slide"] // Pn

    lift = lambda x: x["v"]
    comb = lambda a, b: a + b
    key_fn = lambda x: x["k"]

    step = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lift, comb, key_fn),
                   donate_argnums=(0,))

    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    # A few pre-staged batches cycled round-robin, so host staging cost is
    # off the timed path (the driver loop overlaps staging with compute in
    # production; here we isolate device throughput).
    batches = []
    for i in range(4):
        payload = {
            "k": jax.device_put(
                jnp.asarray(rng.integers(0, K, CAP), jnp.int32), dev),
            "v": jax.device_put(
                jnp.asarray(rng.random(CAP, dtype=np.float32)), dev),
        }
        ts = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), dev)
        valid = jax.device_put(jnp.ones(CAP, bool), dev)
        batches.append((payload, ts, valid))

    state = make_ffat_state(jnp.zeros((), jnp.float32), K, R)
    state = jax.device_put(state, dev)

    def time_steps(stp, st):
        """Warm up, then MEDIAN of 5 timing windows with the dispersion
        reported (VERDICT r3: best-of-3 swung vs_baseline ±40% on a link
        whose scheduling jitter can halve any single window).  One
        methodology for every kernel variant so the numbers stay
        comparable."""
        for i in range(cfg["warmup"]):
            p, t, v = batches[i % len(batches)]
            st, out, fired, _ = stp(st, p, t, v)
        jax.block_until_ready(st)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(cfg["steps"]):
                p, t, v = batches[i % len(batches)]
                st, out, fired, _ = stp(st, p, t, v)
            jax.block_until_ready(st)
            rates.append(cfg["steps"] * CAP / (time.perf_counter() - t0))
        rates.sort()
        med = rates[len(rates) // 2]
        disp = {"windows": len(rates), "min": round(rates[0], 1),
                "max": round(rates[-1], 1),
                "rel_spread": round((rates[-1] - rates[0]) / med, 4)}
        return med, disp, st

    tuples_per_sec, dispersion, state = time_steps(step, state)

    # the same workload with the combiner DECLARED sum-like (flagless
    # sliding fold, windows/ffat_kernels._sliding_reduce_plain): reported
    # alongside — `value` stays the default-path number so round-over-round
    # vs_baseline compares like with like
    step_sum = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lift, comb, key_fn,
                                      sum_like=True), donate_argnums=(0,))
    state_sum = jax.device_put(
        make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
    sum_tps, _, _ = time_steps(step_sum, state_sum)

    # p99 per-batch latency: timed with a sync per step (dispatch pipeline
    # drained), so it is an upper bound on steady-state window latency.
    lats = []
    for i in range(cfg["lat_steps"]):
        p, t, v = batches[i % len(batches)]
        t1 = time.perf_counter()
        state, out, fired, _ = step(state, p, t, v)
        jax.block_until_ready(out)
        lats.append(time.perf_counter() - t1)
    p99_ms = float(np.percentile(np.array(lats) * 1e3, 99))

    # Roofline anchor (the vs_baseline field only compares our own prior
    # rounds): irreducible per-tuple payload traffic is ~16 B (i32 key +
    # f32 value read + i64 ts read), so achieved payload bandwidth is a
    # LOWER bound on HBM traffic — the step is argsort-dominated, whose
    # multi-pass traffic multiplies it several-fold.  v5e peak HBM is
    # ~819 GB/s; the fraction below is therefore a floor on utilization.
    roofline = None
    if platform == "tpu":
        payload_gb_s = tuples_per_sec * 16 / 1e9
        roofline = {
            "payload_bytes_per_tuple": 16,
            "payload_gb_s": round(payload_gb_s, 1),
            "hbm_peak_gb_s": 819,
            "hbm_fraction_floor": round(payload_gb_s / 819, 4),
            "note": "argsort-dominated; sort passes multiply true traffic",
        }
    return {
        "value": round(tuples_per_sec, 1),
        "methodology": "median_of_5_windows",
        "dispersion": dispersion,
        "sum_decl_value": round(sum_tps, 1),
        "p99_batch_latency_ms": round(p99_ms, 3),
        "roofline": roofline,
        "config": {"cap": CAP, "keys": K, "win": cfg["win"],
                   "slide": cfg["slide"], "platform": platform,
                   "device": str(dev)},
    }


def _e2e_graph(cfg: dict, n_tuples: int, chunks, lat_sink):
    """Build the whole-framework pipeline (VERDICT r2 item 3: benchmark what
    ``PipeGraph.run()`` sustains, not the raw kernel): columnar byte ingest →
    staging → MapTPU → FilterTPU → FfatWindowsTPU → columnar Sink.  Matches
    the reference's measurement harnesses, which time whole pipelines
    (BASELINE.md: Source→Map_GPU→Filter_GPU→Sink, ``tests/graph_tests_gpu``)."""
    import windflow_tpu as wf
    from windflow_tpu.io import FrameSource

    CAP, K = cfg["cap"], cfg["keys"]
    src = FrameSource(chunks, nv=1, fmt="frames", output_batch_size=CAP)
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
    f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withCBWindows(cfg["win"], cfg["slide"])
         .withKeyBy(lambda t: t["key"]).withMaxKeys(K).build())
    snk = wf.Sink_Builder(lat_sink).withColumnarSink(defer=4).build()
    g = wf.PipeGraph("bench_e2e", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS)
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)        # Map+Filter fuse into ONE XLA program (chaining)
    pipe.add(w).add_sink(snk)
    return g


def run_bench_e2e(platform: str, cfg: dict, jax,
                  kernel_tps: float = 0.0) -> dict:
    """End-to-end framework throughput + p99 window latency.

    Tuples enter as binary frame bytes (columnar native ingest) and leave
    through a columnar sink; INGRESS time stamps each tuple's arrival in
    wall microseconds, so ``sink receipt − row timestamp`` is the event
    arrival → window result latency through staging, emitters, the driver
    loop, device programs, and egress.  XLA's persistent compilation cache
    is enabled and a small warmup graph (same shapes) is run first so the
    timed run measures the framework, not the compiler."""
    import numpy as np

    _setup_compile_cache(jax)

    CAP, K = cfg["cap"], cfg["keys"]
    n_tuples = int(os.environ.get("BENCH_E2E_TUPLES", cfg["e2e_tuples"]))
    rng = np.random.default_rng(1)

    def make_blob(n):
        rec = np.empty(n, dtype=[("k", "<i8"), ("t", "<i8"), ("v", "<f8")])
        rec["k"] = rng.integers(0, K, n)
        rec["t"] = np.arange(n)          # overwritten by INGRESS stamping
        rec["v"] = rng.random(n)
        return rec.tobytes()

    def chunker(blob, chunk_bytes=1 << 20):
        def chunks():
            for lo in range(0, len(blob), chunk_bytes):
                yield blob[lo:lo + chunk_bytes]
        return chunks

    # warmup: compile every program shape (staging CAP, ffat state, sink)
    warm = _e2e_graph(cfg, cfg["e2e_warm_tuples"],
                      chunker(make_blob(cfg["e2e_warm_tuples"])),
                      lambda c: None)
    warm.run()

    lats = []
    rows = [0]
    first_out = [None]

    def lat_sink(c):
        if c is None:
            return
        if first_out[0] is None:
            # first result: every program of the pipeline is now compiled
            first_out[0] = time.perf_counter()
        rows[0] += len(c)
        now = time.time() * 1e6
        tss = np.asarray(c.tss, np.float64)
        tss = tss[tss > 0]      # EOS-flush rows carry ts=0: not steady-state
        if len(tss):
            lats.append(now - tss)

    blob = make_blob(n_tuples)
    g = _e2e_graph(cfg, n_tuples, chunker(blob), lat_sink)
    t0 = time.perf_counter()
    g.run()
    t_end = time.perf_counter()
    elapsed = t_end - t0
    # steady-state window: from the first sink result (compilation and
    # first-batch warmup done) to the end; the first batch's tuples are out
    # of the window.  The total number is reported alongside.  The steady
    # estimate is only meaningful when the window covers a real share of
    # the run — with few batches the deferred sink emits everything near
    # EOS and the window collapses — otherwise fall back to the full-run
    # number.
    steady_s = (t_end - first_out[0]) if first_out[0] else elapsed
    steady_tuples = max(1, n_tuples - CAP)
    full_rate = n_tuples / elapsed
    if steady_s < 0.2 * elapsed or n_tuples < 6 * CAP:
        steady_rate, estimator = full_rate, "full_run_fallback"
    else:
        steady_rate, estimator = steady_tuples / steady_s, "steady"
    # Sanity guard (VERDICT r3: a collapsed steady window once produced
    # 4.96e8 tup/s on CPU — 140x the kernel rate, physically impossible):
    # the pipeline can never beat its own kernel, and a steady estimate
    # far above the full-run rate means the window didn't cover the run.
    # Reject such readings rather than record garbage.
    implausible = (steady_rate > 3 * full_rate
                   or (kernel_tps and steady_rate > 2 * kernel_tps))
    if estimator == "steady" and implausible:
        estimator = (f"full_run_rejected_outlier"
                     f"(steady={steady_rate:.3g})")
        steady_rate = full_rate
    lat_all = (np.concatenate(lats) if lats else np.array([0.0])) / 1e3
    return {
        "tuples_per_sec": round(steady_rate, 1),
        "steady_estimator": estimator,
        "tuples_per_sec_incl_compile": round(n_tuples / elapsed, 1),
        "p99_window_latency_ms": round(float(np.percentile(lat_all, 99)), 3),
        "p50_window_latency_ms": round(float(np.percentile(lat_all, 50)), 3),
        "window_rows": rows[0],
        "tuples": n_tuples,
        "elapsed_s": round(elapsed, 3),
    }


def scaling_step(jax, n: int, K: int, per_chip: int, seed: int = 2):
    """Build one width-``n`` rung of the weak-scaling sweep: the key-sharded
    mesh, the compiled keyed reduce, and its staged inputs.  Shared with the
    test suite so the composition the harness runs on real hardware is the
    composition CI exercises (tests/test_mesh.py)."""
    import jax.numpy as jnp
    import numpy as np

    from windflow_tpu.parallel import mesh as meshmod

    mesh = meshmod.make_mesh(n_devices=n, data=1)
    cap = per_chip * n
    fn = meshmod.make_sharded_keyed_reduce(
        mesh, cap, K,
        lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]},
        key_fn=lambda t: t["k"], use_psum=True)
    rng = np.random.default_rng(seed)
    sh = meshmod.batch_sharding(mesh)
    payload = {
        "k": jax.device_put(
            jnp.asarray(rng.integers(0, K, cap), jnp.int32), sh),
        "v": jax.device_put(
            jnp.asarray(rng.random(cap, dtype=np.float32)), sh),
    }
    valid = jax.device_put(jnp.ones(cap, bool), sh)
    return fn, payload, valid, cap


def _setup_compile_cache(jax) -> None:
    """Persistent XLA compilation cache: fresh operator objects (each graph
    build) re-jit, so cross-run reuse needs the disk cache."""
    os.makedirs("/tmp/wf_jax_cache", exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/wf_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # older jax: first graph still warms per-process caches


def run_bench_ysb(platform: str, cfg: dict, jax) -> dict:
    """Yahoo-Streaming-Benchmark-shaped pipeline throughput (BASELINE.md
    harness list: "YahooStreamingBench ad-analytics DAG"): columnar binary
    ingest → FilterTPU(view events) ⊕ MapTPU(ad→campaign device-table
    join), fused → per-campaign tumbling TB count windows → columnar sink,
    all through ``PipeGraph.run()``."""
    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu.io import FrameSource

    _setup_compile_cache(jax)
    CAP = cfg["cap"]
    n_ads, n_campaigns = 1000, 100
    n_tuples = int(os.environ.get("BENCH_YSB_TUPLES", cfg["e2e_tuples"]))
    rng = np.random.default_rng(3)
    table_np = rng.integers(0, n_campaigns, n_ads).astype(np.int32)

    rec = np.empty(n_tuples, dtype=[("k", "<i8"), ("t", "<i8"),
                                    ("v", "<f8")])
    rec["k"] = rng.integers(0, n_ads, n_tuples)          # ad_id
    # event time spans ~64 tumbling windows so the firing path runs in
    # steady state (not just the EOS flush)
    gap_usec = max(1, 64 * 10_000_000 // n_tuples)
    rec["t"] = np.arange(n_tuples, dtype=np.int64) * gap_usec
    rec["v"] = rng.integers(0, 3, n_tuples).astype(np.float64)  # etype
    blob = rec.tobytes()

    def chunks():
        for lo in range(0, len(blob), 1 << 20):
            yield blob[lo:lo + (1 << 20)]

    import jax.numpy as jnp
    table = jnp.asarray(table_np)
    rows = [0]

    def build():
        src = FrameSource(chunks, nv=1, fmt="frames",
                          output_batch_size=CAP)
        flt = wf.FilterTPU_Builder(lambda e: e["v0"] == 1.0).build()
        prj = wf.MapTPU_Builder(
            lambda e: {"campaign": table[e["key"]], "one": 1}).build()
        win = (wf.Ffat_WindowsTPU_Builder(lambda e: e["one"],
                                          lambda a, b: a + b)
               .withTBWindows(10_000_000, 10_000_000)
               .withKeyBy(lambda e: e["campaign"])
               .withMaxKeys(n_campaigns).build())
        snk = (wf.Sink_Builder(
                lambda c: rows.__setitem__(0, rows[0] + len(c))
                if c is not None else None)
               .withColumnarSink().build())
        g = wf.PipeGraph("bench_ysb", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        pipe = g.add_source(src)
        pipe.add(flt)
        pipe.chain(prj)       # Filter+Map fuse into one XLA program
        pipe.add(win).add_sink(snk)
        return g

    build().run()             # warmup: compile all program shapes
    rows[0] = 0
    t0 = time.perf_counter()
    build().run()
    elapsed = time.perf_counter() - t0
    return {
        "tuples_per_sec": round(n_tuples / elapsed, 1),
        "tuples": n_tuples,
        "window_rows": rows[0],
        "elapsed_s": round(elapsed, 3),
        "shape": "FrameSource->FilterTPU+MapTPU(join)->FfatTB->colSink",
    }


def run_bench_scaling(jax, max_devices: Optional[int] = None) -> dict:
    """Keyed-Reduce weak scaling over a ``(1, n)`` key-sharded mesh
    (BASELINE.json north star: "linear scaling to 8 chips on keyed
    Reduce").  Requires > 1 REAL device: per-chip work is held constant
    (weak scaling) while the mesh widens 1 → N, so ideal efficiency is a
    flat tuples/sec/chip line.  Opt-in (``--scaling`` /
    ``BENCH_SCALING=1``) and refused on virtual/forced-CPU meshes —
    host-core-sharing virtual devices would fabricate the numbers."""
    devs = jax.devices()
    if len(devs) < 2:
        return {"skipped": f"needs >1 real device, have {len(devs)}"}
    if devs[0].platform == "cpu":
        return {"skipped": "virtual CPU mesh: scaling numbers would be "
                           "host-core-sharing artifacts"}
    n_max = min(len(devs), max_devices or len(devs))
    K = 4096
    per_chip = 1 << 20
    series = []
    n = 1
    while n <= n_max:
        fn, payload, valid, cap = scaling_step(jax, n, K, per_chip)
        for _ in range(3):
            table, has = fn(payload, valid)
        jax.block_until_ready(table)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                table, has = fn(payload, valid)
            jax.block_until_ready(table)
            best = max(best, 10 * cap / (time.perf_counter() - t0))
        series.append({"devices": n,
                       "tuples_per_sec": round(best, 1),
                       "tuples_per_sec_per_chip": round(best / n, 1)})
        n *= 2
    base = series[0]["tuples_per_sec_per_chip"]
    for s in series:
        s["efficiency"] = round(s["tuples_per_sec_per_chip"] / base, 4)
    return {"mode": "weak", "keys": K, "tuples_per_chip": per_chip,
            "series": series}


def load_history() -> dict:
    try:
        with open(HISTORY_PATH) as f:
            h = json.load(f)
        # migrate the old single-entry-per-platform shape to run lists
        for k, v in list(h.items()):
            if isinstance(v, dict):
                h[k] = [v]
        return h
    except (OSError, ValueError):
        return {}


def pick_baseline(runs: list, now: float) -> dict:
    """The previous *round's* number, not a minutes-old rerun: the most
    recent run at least 2 hours old (rounds are ~12 h apart; same-round
    debugging reruns are minutes apart), else the oldest run recorded."""
    old = [r for r in runs if now - r.get("t", 0) >= 2 * 3600]
    if old:
        return old[-1]
    return runs[0] if runs else {}


def save_history(hist: dict) -> None:
    try:
        with open(HISTORY_PATH, "w") as f:
            json.dump(hist, f, indent=2)
            f.write("\n")
    except OSError:
        pass  # read-only checkout: the stdout line is still the artifact


def main() -> None:
    forced = os.environ.get("BENCH_PLATFORM")  # "cpu" forces the fallback
    tpu_error = None
    probe_attempts = None
    if forced == "cpu":
        platform = "cpu"
    else:
        ok, diag, probe_attempts = probe_tpu()
        platform = "tpu" if ok else "cpu"
        if not ok:
            tpu_error = diag

    result = {
        "metric": "ffat_sliding_window_sum_throughput",
        "value": 0.0,
        "unit": "tuples/sec/chip",
        "vs_baseline": 1.0,
    }
    if probe_attempts is not None:
        result["tpu_probe_attempts"] = probe_attempts
    if tpu_error:
        result["tpu_error"] = tpu_error

    if platform == "cpu":
        # The axon sitecustomize overrides JAX_PLATFORMS at interpreter
        # startup, so force CPU through the config API before backend init.
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    try:
        measured = run_bench(platform, CONFIGS[platform], jax)
    except Exception as e:  # backend died mid-run: report, don't traceback
        result["error"] = f"{type(e).__name__}: {e}"[:400]
        print(json.dumps(result))
        sys.exit(1)

    result.update(measured)

    # end-to-end framework path (VERDICT r2 item 3): sustained tuples/sec
    # through PipeGraph.run() + p99 event→window-result latency, alongside
    # the kernel number; the ratio shows what the runtime costs on top of
    # the device program.
    if "--scaling" in sys.argv or \
            os.environ.get("BENCH_SCALING") not in (None, "", "0"):
        try:
            result["scaling"] = run_bench_scaling(jax)
        except Exception as e:
            result["scaling"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    try:
        result["ysb"] = run_bench_ysb(platform, CONFIGS[platform], jax)
    except Exception as e:
        result["ysb_error"] = f"{type(e).__name__}: {e}"[:300]

    try:
        e2e = run_bench_e2e(platform, CONFIGS[platform], jax,
                            kernel_tps=result["value"])
        e2e["ratio_vs_kernel"] = round(
            e2e["tuples_per_sec"] / result["value"], 4) \
            if result["value"] else 0.0
        if e2e["ratio_vs_kernel"] < 0.5:
            # Diagnosis (VERDICT r2 item 3): the kernel number consumes
            # pre-staged device batches; the e2e number pays host→device
            # staging of ~16 B/tuple.  On this environment the chip is
            # remote (tunneled link, ~60-90 MB/s, ~100 ms/transfer RTT), so
            # e2e saturates the LINK, not the chip: staged MB/s below ≈
            # measured link bandwidth.  On host-attached TPU (PCIe/ICI,
            # tens of GB/s) the same path is compute-bound.
            if platform == "tpu":
                e2e["gap_diagnosis"] = (
                    "link-bound: staging "
                    f"{e2e['tuples_per_sec'] * 16 / 1e6:.0f}"
                    " MB/s ~= tunnel bandwidth; kernel reads pre-staged HBM")
            else:
                e2e["gap_diagnosis"] = (
                    "cpu fallback: kernel and pipeline share host cores; "
                    "ingest parsing + driver loop compete with compute")
        result["e2e"] = e2e
    except Exception as e:
        result["e2e_error"] = f"{type(e).__name__}: {e}"[:400]

    now = time.time()
    hist = load_history()
    runs = hist.setdefault(platform, [])
    base = pick_baseline(runs, now)
    if base.get("value"):
        result["vs_baseline"] = round(result["value"] / base["value"], 4)
        result["prev_value"] = base["value"]
    runs.append({"value": result["value"],
                 "methodology": result.get("methodology"),
                 "dispersion": result.get("dispersion"),
                 "sum_decl_value": result.get("sum_decl_value"),
                 "p99_batch_latency_ms": result["p99_batch_latency_ms"],
                 "e2e": result.get("e2e"),
                 "ysb": result.get("ysb"),
                 "t": now,
                 "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S")})
    del runs[:-20]  # keep the last 20 runs per platform
    save_history(hist)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
