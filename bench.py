"""Benchmark: FFAT sliding-window sum throughput on one chip (the north-star
metric, BASELINE.json: "tuples/sec/chip on FFAT sliding-window sum; p99
window latency").

Runs the flagship per-batch program (see ``__graft_entry__.entry``): staged
batches of ``CAP`` tuples over ``K`` keys, count-based sliding window
``WIN``/``SLIDE`` decomposed into panes, all fired windows of all keys
computed in one fused XLA program per batch.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The reference publishes no in-repo numbers (BASELINE.md — `published: {}`),
so ``vs_baseline`` is measured against our own previous round's number for
the same platform, persisted in ``bench_history.json``.

Robustness (the round-1 bench died to a hung TPU backend init and left no
artifact): the TPU backend is probed in a *subprocess* with a bounded
timeout and one retry; on failure the bench falls back to the CPU backend so
a number (clearly labelled with its platform + the TPU failure diagnosis) is
always recorded.  Exit code is 0 whenever a value was measured.
"""

import json
import math
import os
import subprocess
import sys
import time
from typing import Optional

# device-plane observability: the bench opts into the full compiled-HLO
# cost analysis (the compile watcher's default is the cheap "lowered"
# estimate — tier-1 wall budget); must be set before windflow_tpu import
os.environ.setdefault("WF_TPU_COST_ANALYSIS", "compiled")

TPU_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT", "150"))
TPU_PROBE_RETRIES = int(os.environ.get("BENCH_TPU_RETRIES", "1"))
HISTORY_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_history.json")

#: per-platform workload configs (kept stable across rounds so
#: round-over-round vs_baseline is meaningful per platform)
CONFIGS = {
    # sweet spot on v5e: the sliding-reduce kernel is dispatch-bound
    # below ~128k tuples per staged batch
    "tpu": dict(cap=262144, keys=1024, win=1024, slide=128,
                warmup=6, steps=40, lat_steps=20,
                e2e_tuples=16 * 262144, e2e_warm_tuples=2 * 262144),
    # CPU fallback: smaller so a diagnostic number lands in minutes.
    # e2e_tuples sized so per-run graph re-tracing (~0.6 s, memory
    # round4-state) amortizes: at r5's ~4.5e6 tup/s steady the 64-batch
    # run lasts ~1.5 s, putting the steady window at >half the run.
    "cpu": dict(cap=65536, keys=256, win=1024, slide=128,
                warmup=2, steps=10, lat_steps=5,
                e2e_tuples=64 * 65536, e2e_warm_tuples=2 * 65536),
}


def probe_tpu() -> tuple:
    """Check, in a subprocess with a hard timeout, that the default (axon
    TPU) backend can initialize and run one op.  Returns
    (ok, diagnosis, attempts) — ``attempts`` records every probe's outcome
    so a fallback artifact shows exactly what was tried and when."""
    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "x = (jnp.ones(8) * 2).block_until_ready();"
            "print('PROBE_OK', d[0].platform, d[0])")
    last = ""
    attempts = []
    for attempt in range(1 + TPU_PROBE_RETRIES):
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=TPU_PROBE_TIMEOUT_S)
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                dev = r.stdout.strip().split("PROBE_OK", 1)[1].strip()
                attempts.append({"at": stamp, "ok": True, "device": dev})
                return True, dev, attempts
            tail = (r.stderr or r.stdout).strip().splitlines()
            last = tail[-1][:300] if tail else f"rc={r.returncode}"
        except subprocess.TimeoutExpired:
            last = (f"backend init hung > {TPU_PROBE_TIMEOUT_S}s "
                    "(axon tunnel unresponsive)")
        attempts.append({"at": stamp, "ok": False, "error": last})
    return False, last, attempts


def a100_anchor(win: int, slide: int) -> dict:
    """Bandwidth-bound throughput ceiling of the REFERENCE's CUDA kernel
    sequence, on A100-SXM-40GB (1.555e12 B/s HBM2e).  The per-tuple byte
    model depends only on the window spec (capacity and key count cancel
    per tuple to first order).

    Per-tuple HBM byte model of the reference CB keyed path (records
    16 B — batch_item_gpu_t carries tuple + u64 timestamp, win_result_t
    key + gwid + aggregate):
      sort    thrust::sort_by_key radix over (i32 key, i32 seq): 4 passes
              x read+write x 8 B   (ffat_replica_gpu.hpp:751; the keyed
              emitter pays the same sort AGAIN, keyby_emitter_gpu.hpp:548
              — not counted, keeping the ceiling conservative)
      lift    read 16 + write 16   (Lifting_Kernel_CB_Keyed, :741)
      add     leaf copy D2D read+write 16 (flatfat_gpu.hpp add_cb :226)
      update  ~1 tree combine per inserted leaf: 2 reads + 1 write x 16
              (Init/Update_TreeLevel_Kernel, flatfat_gpu.hpp:60-89)
      results per window ~2*log2(win) node reads x 16 + 24 B result write
              (Compute_Results_Kernel canonical-range walk,
              flatfat_gpu.hpp:91-139), amortized over ``slide`` tuples
    The ceiling assumes 100% of peak bandwidth with perfect overlap — a
    real A100 run sits strictly below it."""
    rec = 16
    sort_b = 4 * 2 * 8
    lift_b = 2 * rec
    add_b = 2 * rec
    update_b = 3 * rec
    results_b = (2 * math.log2(win) * rec + 24) / slide
    bytes_per_tuple = sort_b + lift_b + add_b + update_b + results_b
    hbm = 1.555e12
    ceiling = hbm / bytes_per_tuple
    return {
        "bytes_per_tuple": round(bytes_per_tuple, 1),
        "components_bytes": {"sort": sort_b, "lift": lift_b, "add": add_b,
                             "tree_update": update_b,
                             "window_results": round(results_b, 2)},
        "a100_hbm_b_s": hbm,
        "a100_tps_ceiling": round(ceiling, 1),
        "target_a100_tps": round(0.9 * ceiling, 1),
    }


def xla_bytes_accessed(jitted, state, batch) -> float:
    """MEASURED per-step memory traffic from XLA's compiled cost analysis
    (bytes accessed across all memory spaces), replacing the 16-B payload
    floor of earlier rounds.  None when the backend doesn't report it."""
    try:
        comp = jitted.lower(state, *batch).compile()
        ca = comp.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        val = d.get("bytes accessed")
        return float(val) if val else None
    except Exception:
        return None


#: steps per dispatch in the unrolled-chain fallback (see
#: make_unrolled_chain); compile time grows with it (~8 min at 16 on the
#: remote helper), 8 amortizes the ~40 us dispatch floor 8x for ~60 s
UNROLL = max(1, int(os.environ.get("BENCH_CHAIN_UNROLL", "8")))


def _fold_step_outputs(jax, jnp, n, v, out, out_valid):
    """Fold one step's fired-window outputs into the (n, v) accumulators
    that keep every chained step live (no DCE).  SHARED by the scan chain
    and the unrolled chain — the two methodologies must measure the same
    program, so the accumulation must never diverge between them."""
    n = n + jnp.sum(out_valid).astype(jnp.int32)
    leaf = jax.tree.leaves(out["value"])[0]
    v = v + jnp.sum(jnp.where(out_valid, leaf, 0.0)).astype(jnp.float32)
    return n, v


def make_unrolled_chain(jax, step_fn, unroll: int):
    """Python-unrolled ``unroll``-step chain: ONE dispatch runs ``unroll``
    FFAT steps over ``unroll`` DISTINCT pre-staged batches, threading the
    state and folding each step's fired-window outputs into scalar
    accumulators (so no step is dead code).

    Fallback for remote-compile helpers that reject ``lax.scan`` around
    the step (the axon helper 500s on ANY scan-of-step, even length 1 —
    r5 bisect; plain unrolled jit compiles fine).  Dispatch cost still
    amortizes ``unroll``-fold.

    The batches MUST be distinct: with a shared batch XLA CSEs the
    payload-only stages (grouping permutation, histogram, lift gather)
    across steps and the chain measures a several-times-lighter program
    (observed 3x inflation at a 4-batch cycle, r5).

    ``flat`` layout: 4 arrays per step — k, v, ts, valid."""
    import jax.numpy as jnp

    def chain(st, *flat):
        n = jnp.int32(0)
        v = jnp.float32(0.0)
        for j in range(unroll):
            payload = {"k": flat[4 * j], "v": flat[4 * j + 1]}
            st, out, out_valid, _ = step_fn(
                st, payload, flat[4 * j + 2], flat[4 * j + 3])
            n, v = _fold_step_outputs(jax, jnp, n, v, out, out_valid)
        return st, n, v

    return jax.jit(chain, donate_argnums=(0,))


def _median_disp(rates: list) -> tuple:
    """Median of a list of window rates + the shared dispersion dict
    (one definition for the per-dispatch and scan-chained loops so the
    two numbers always carry identical statistics)."""
    rates = sorted(rates)
    med = rates[len(rates) // 2]
    disp = {"windows": len(rates), "min": round(rates[0], 1),
            "max": round(rates[-1], 1),
            "rel_spread": round((rates[-1] - rates[0]) / med, 4)}
    return med, disp


def run_bench(platform: str, cfg: dict, jax) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)

    CAP, K = cfg["cap"], cfg["keys"]
    Pn = math.gcd(cfg["win"], cfg["slide"])
    R, D = cfg["win"] // Pn, cfg["slide"] // Pn

    lift = lambda x: x["v"]
    comb = lambda a, b: a + b
    key_fn = lambda x: x["k"]

    step_fn = make_ffat_step(CAP, K, Pn, R, D, lift, comb, key_fn)
    step = jax.jit(step_fn, donate_argnums=(0,))

    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    # A few pre-staged batches cycled round-robin, so host staging cost is
    # off the timed path (the driver loop overlaps staging with compute in
    # production; here we isolate device throughput).
    batches = []
    for i in range(max(4, UNROLL)):
        payload = {
            "k": jax.device_put(
                jnp.asarray(rng.integers(0, K, CAP), jnp.int32), dev),
            "v": jax.device_put(
                jnp.asarray(rng.random(CAP, dtype=np.float32)), dev),
        }
        ts = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), dev)
        valid = jax.device_put(jnp.ones(CAP, bool), dev)
        batches.append((payload, ts, valid))

    state = make_ffat_state(jnp.zeros((), jnp.float32), K, R)
    state = jax.device_put(state, dev)

    def time_steps(stp, st):
        """Warm up, then MEDIAN of 5 timing windows with the dispersion
        reported (VERDICT r3: best-of-3 swung vs_baseline ±40% on a link
        whose scheduling jitter can halve any single window).  One
        methodology for every kernel variant so the numbers stay
        comparable."""
        for i in range(cfg["warmup"]):
            p, t, v = batches[i % len(batches)]
            st, out, fired, _ = stp(st, p, t, v)
        jax.block_until_ready(st)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(cfg["steps"]):
                p, t, v = batches[i % len(batches)]
                st, out, fired, _ = stp(st, p, t, v)
            jax.block_until_ready(st)
            rates.append(cfg["steps"] * CAP / (time.perf_counter() - t0))
        med, disp = _median_disp(rates)
        return med, disp, st

    dispatch_tps, dispatch_disp, state = time_steps(step, state)

    # Scan-chained chip throughput (round-5): the per-dispatch loop above
    # pays one tunnel round trip PER STEP, and on a remote (axon) chip a
    # single scheduling stall can halve a whole timing window — r5's
    # per-dispatch TPU run showed rel_spread 2.7 with the max window 3x
    # the median.  Chaining `steps` batch-steps under ``lax.scan`` runs
    # the whole window as ONE device program, so the measurement is chip
    # throughput, not tunnel-jitter throughput.  A tiny accumulator over
    # the fired-window outputs is threaded through the carry so XLA
    # cannot dead-code-eliminate the firing/compaction stages.
    from jax import lax

    stacked = {
        "k": jnp.stack([b[0]["k"] for b in batches]),
        "v": jnp.stack([b[0]["v"] for b in batches]),
        "ts": jnp.stack([b[1] for b in batches]),
        "valid": jnp.stack([b[2] for b in batches]),
    }
    idxs = jnp.asarray(np.arange(cfg["steps"]) % len(batches), jnp.int32)

    def make_chained(fn):
        def chained(st, idxs, sb):
            def body(carry, i):
                st, acc_n, acc_v = carry
                p = {"k": lax.dynamic_index_in_dim(sb["k"], i,
                                                   keepdims=False),
                     "v": lax.dynamic_index_in_dim(sb["v"], i,
                                                   keepdims=False)}
                t = lax.dynamic_index_in_dim(sb["ts"], i, keepdims=False)
                v = lax.dynamic_index_in_dim(sb["valid"], i,
                                             keepdims=False)
                st, out, out_valid, _ = fn(st, p, t, v)
                acc_n, acc_v = _fold_step_outputs(jax, jnp, acc_n, acc_v,
                                                  out, out_valid)
                return (st, acc_n, acc_v), None
            carry0 = (st, jnp.int32(0), jnp.float32(0.0))
            (st, n, sv), _ = lax.scan(body, carry0, idxs)
            return st, n, sv
        return jax.jit(chained, donate_argnums=(0,))

    scan_dead = []   # set on first scan-of-step compile failure: the
    # axon helper rejects EVERY scan-of-step, so the sum-variant call
    # skips the known-dead second compile round trip

    def time_chained(fn, st):
        """Dispatch-amortized chip throughput + the methodology that
        produced it: ``lax.scan`` chaining first; where the remote
        compile helper rejects any scan-of-step (axon 500s even at
        length 1), a Python-unrolled ``UNROLL``-step chain over DISTINCT
        batches (make_unrolled_chain).  Raises only if both fail."""
        try:
            if scan_dead:
                raise RuntimeError(f"scan chain skipped: {scan_dead[0]}")
            ch = make_chained(fn)
            st, n, sv = ch(st, idxs, stacked)   # compile + warm
            jax.block_until_ready(sv)
            rates = []
            for _ in range(5):
                t0 = time.perf_counter()
                st, n, sv = ch(st, idxs, stacked)
                jax.block_until_ready(sv)
                rates.append(cfg["steps"] * CAP
                             / (time.perf_counter() - t0))
            med, disp = _median_disp(rates)
            return med, disp, "scan_chained_median_of_5", None
        except Exception as e:
            scan_err = f"{type(e).__name__}: {e}"[:300]
            if not scan_dead:
                scan_dead.append(scan_err)
        # the scan attempt may have DONATED st before dying mid-loop
        # (flaky remote link): always hand the fallback a fresh state
        st = jax.device_put(
            make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
        ch = make_unrolled_chain(jax, fn, UNROLL)
        flat = [x for b in batches[:UNROLL]
                for x in (b[0]["k"], b[0]["v"], b[1], b[2])]
        n_disp = max(1, cfg["steps"] // UNROLL)
        st, n, sv = ch(st, *flat)               # compile + warm
        jax.block_until_ready(sv)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(n_disp):
                st, n, sv = ch(st, *flat)
            jax.block_until_ready(sv)
            rates.append(n_disp * UNROLL * CAP
                         / (time.perf_counter() - t0))
        med, disp = _median_disp(rates)
        return (med, disp, f"unrolled_chain{UNROLL}_median_of_5",
                f"scan chain failed ({scan_err}); unrolled chain used")

    chained_error = None
    try:
        state2 = jax.device_put(
            make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
        (tuples_per_sec, dispersion,
         methodology, chained_error) = time_chained(step_fn, state2)
    except Exception as e:
        # both chain forms failed to compile; the per-dispatch number is
        # a jitter-prone but valid fallback — never zero the artifact
        methodology = "median_of_5_windows(chained_compile_failed)"
        tuples_per_sec, dispersion = dispatch_tps, dispatch_disp
        chained_error = f"{type(e).__name__}: {e}"[:300]

    # the same workload with the combiner DECLARED sum-like (flagless
    # sliding fold, windows/ffat_kernels._sliding_reduce_plain): reported
    # alongside — `value` stays the default-path number so round-over-round
    # vs_baseline compares like with like
    step_sum_fn = make_ffat_step(CAP, K, Pn, R, D, lift, comb, key_fn,
                                 sum_like=True)
    state_sum = jax.device_put(
        make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
    sum_decl_error = None
    try:
        sum_tps, _, sum_methodology, _ = time_chained(step_sum_fn,
                                                      state_sum)
    except Exception as e:
        # mark the methodology switch so a per-dispatch sum number is
        # never read against a chained `value` as a regression, and keep
        # the failure in the artifact (symmetric with chained_error)
        sum_decl_error = f"{type(e).__name__}: {e}"[:300]
        sum_methodology = "median_of_5_windows(chained_compile_failed)"
        step_sum = jax.jit(step_sum_fn, donate_argnums=(0,))
        state_sum = jax.device_put(
            make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
        sum_tps, _, _ = time_steps(step_sum, state_sum)

    # p99 per-batch latency: timed with a sync per step (dispatch pipeline
    # drained), so it is an upper bound on steady-state window latency.
    lats = []
    for i in range(cfg["lat_steps"]):
        p, t, v = batches[i % len(batches)]
        t1 = time.perf_counter()
        state, out, fired, _ = step(state, p, t, v)
        jax.block_until_ready(out)
        lats.append(time.perf_counter() - t1)
    p99_ms = float(np.percentile(np.array(lats) * 1e3, 99))

    # Roofline + A100 anchor (BASELINE.md "Concrete A100 anchor" holds the
    # full derivation).  target_a100_tps makes the ">= 90% of CUDA-A100"
    # north star falsifiable: it is 90% of the bandwidth-bound CEILING of
    # the reference's own kernel sequence at this exact shape — sort,
    # lift, leaf copy, tree update, window walks (flatfat_gpu.hpp:60-139,
    # ffat_replica_gpu.hpp:741-864) — on A100-SXM-40GB HBM (1.555 TB/s).
    # A real A100 run sits below its ceiling, so beating the target beats
    # the reference.  hbm_utilization uses XLA's MEASURED bytes-accessed
    # for our step (not the 16-B payload floor of earlier rounds).
    anchor = a100_anchor(cfg["win"], cfg["slide"])
    step_bytes = xla_bytes_accessed(step, state, batches[0])
    roofline = {
        "target_a100_tps": anchor["target_a100_tps"],
        "a100_ceiling_tps": anchor["a100_tps_ceiling"],
        "a100_bytes_per_tuple_model": anchor["bytes_per_tuple"],
        "vs_a100_target": round(tuples_per_sec
                                / anchor["target_a100_tps"], 4),
        "payload_bytes_per_tuple": 16,
    }
    if step_bytes is not None:
        roofline["measured_bytes_per_step"] = step_bytes
        roofline["measured_bytes_per_tuple"] = round(step_bytes / CAP, 1)
        if platform == "tpu":
            from windflow_tpu.monitoring import calibration
            hbm_bw, hbm_prov = calibration.constant("hbm_bytes_per_sec")
            roofline["hbm_peak_gb_s"] = round(hbm_bw / 1e9)
            roofline["hbm_bw_provenance"] = hbm_prov
            util = (tuples_per_sec / CAP) * step_bytes / hbm_bw
            roofline["hbm_utilization"] = round(util, 4)
            if util > 1.0:
                # cost analysis sums every HLO's operand/result bytes
                # PRE-fusion; fused producers never touch HBM, so the
                # "measured" bytes are an upper bound on real traffic
                roofline["hbm_utilization_note"] = (
                    "xla cost-analysis bytes are a pre-fusion upper "
                    "bound; utilization > 1 means fusion elides most of "
                    "that traffic — treat bytes as bound, not "
                    "measurement")
    out = {
        "value": round(tuples_per_sec, 1),
        "methodology": methodology,
        "dispersion": dispersion,
        "dispatch_value": round(dispatch_tps, 1),
        "dispatch_dispersion": dispatch_disp,
        "sum_decl_value": round(sum_tps, 1),
        "sum_decl_methodology": sum_methodology,
        "p99_batch_latency_ms": round(p99_ms, 3),
        "roofline": roofline,
        "config": {"cap": CAP, "keys": K, "win": cfg["win"],
                   "slide": cfg["slide"], "platform": platform,
                   "device": str(dev)},
    }
    if chained_error:
        out["chained_error"] = chained_error
    if sum_decl_error:
        out["sum_decl_error"] = sum_decl_error
    return out


def run_bench_reduce(platform: str, cfg: dict, jax) -> dict:
    """Keyed per-batch ReduceTPU throughput (BASELINE.md harness list:
    keyed Reduce_GPU, ``tests/merge_tests_gpu`` ``_kb_`` variants), both
    single-chip paths: the sorted segmented reduce (arbitrary combiner)
    and the declared-monoid dense scatter table (withMaxKeys +
    withMonoidCombiner) — kernel-level, pre-staged batches, the FFAT
    methodology (median of 5 windows)."""
    import jax.numpy as jnp
    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu.batch import DeviceBatch

    CAP, K = cfg["cap"], cfg["keys"]
    rng = np.random.default_rng(4)
    dev = jax.devices()[0]
    payload = {
        "key": jax.device_put(
            jnp.asarray(rng.integers(0, K, CAP), jnp.int32), dev),
        "v": jax.device_put(
            jnp.asarray(rng.random(CAP, dtype=np.float32)), dev),
    }
    batch = DeviceBatch(payload,
                        jax.device_put(
                            jnp.arange(CAP, dtype=jnp.int64), dev),
                        jax.device_put(jnp.ones(CAP, bool), dev))
    # ONE combiner for both paths (leafwise max) so the speedup is
    # apples-to-apples: the sorted baseline folds the identical function
    # the declared path replaces with scatter-max
    comb = lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                         "v": jnp.maximum(a["v"], b["v"])}
    out = {}
    for label, declare in (("sorted_tps", False), ("dense_decl_tps", True)):
        b = wf.ReduceTPU_Builder(comb).withKeyBy(lambda t: t["key"])
        if declare:
            b = b.withMaxKeys(K).withMonoidCombiner("max")
        op = b.build()
        for _ in range(cfg["warmup"]):
            o = op._step(batch)
        jax.block_until_ready(o.payload)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(cfg["steps"]):
                o = op._step(batch)
            jax.block_until_ready(o.payload)
            rates.append(cfg["steps"] * CAP / (time.perf_counter() - t0))
        med, disp = _median_disp(rates)
        out[label] = round(med, 1)
        out[label.replace("_tps", "_dispersion")] = disp
    out["dense_speedup"] = round(out["dense_decl_tps"]
                                 / out["sorted_tps"], 2)
    return out


def run_bench_compaction(platform: str, cfg: dict, jax) -> dict:
    """Device-side key compaction A/B (parallel/compaction.py, guarded
    by tools/check_bench_keys.py + check_bench_regress.py): the seeded
    Zipf ARBITRARY-key reduce — keys drawn Zipf(1.5) and scrambled to
    arbitrary int32 values, so no ``withMaxKeys`` declaration is
    possible — run through the same declared-monoid ReduceTPU twice:
    the legacy sorted segmented path vs the compacted remap (dense slot
    table + overflow lane in one program).  Both paths fold the same
    batch back-to-back in one process, so the speedup ratio holds even
    when the box is loaded.  The Zipf tail keeps ~2% of lanes missing
    the warm table every batch, so the measured number pays the FULL
    compacted machinery: lookup, packed scatter, overflow sort, rank
    merge — not just the all-hit fast lane."""
    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu.batch import DeviceBatch
    from windflow_tpu.parallel.compaction import KeyCompactor

    import jax.numpy as jnp

    CAP = cfg["cap"]
    SLOTS = 1024
    rng = np.random.default_rng(7)
    # rank-scramble: hot ranks land on arbitrary int32 values, not the
    # dense small ints a withMaxKeys user would declare
    r = rng.zipf(1.5, CAP).astype(np.uint64)
    keys = ((r * np.uint64(0x9E3779B97F4A7C15) >> np.uint64(31))
            & np.uint64(0x7FFFFFFE)).astype(np.int32)
    dev = jax.devices()[0]
    payload = {"key": jax.device_put(jnp.asarray(keys), dev),
               "v": jax.device_put(
                   jnp.asarray(rng.random(CAP, dtype=np.float32)), dev)}
    batch = DeviceBatch(payload,
                        jax.device_put(
                            jnp.arange(CAP, dtype=jnp.int64), dev),
                        jax.device_put(jnp.ones(CAP, bool), dev))
    comb = lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                         "v": jnp.maximum(a["v"], b["v"])}
    ops = {}
    comp = None
    for label in ("sorted", "compacted"):
        op = (wf.ReduceTPU_Builder(comb).withKeyBy(lambda t: t["key"])
              .withMonoidCombiner("max").build())
        if label == "compacted":
            comp = KeyCompactor(SLOTS, name="bench_compact")
            op.enable_compaction(comp)
            # warm admission, hottest-first — what the emitter/sketch
            # seeding converges to on a steady stream
            u, cnt = np.unique(keys, return_counts=True)
            comp.observe(u[np.argsort(-cnt)][:SLOTS])
        for _ in range(cfg["warmup"]):
            o = op._step(batch)
        jax.block_until_ready(o.payload)
        ops[label] = op

    def window(op) -> float:
        t0 = time.perf_counter()
        for _ in range(cfg["steps"]):
            o = op._step(batch)
        jax.block_until_ready(o.payload)
        return cfg["steps"] * CAP / (time.perf_counter() - t0)

    # paired windows: each round times sorted then compacted under the
    # same instantaneous box load, so the per-round ratio is immune to
    # the slow load drift that skews a sequential leg-then-leg A/B
    # (the ratio IS the guarded scalar — check_bench_regress trips it)
    rates = {"sorted": [], "compacted": []}
    ratios = []
    for _ in range(5):
        s, c = window(ops["sorted"]), window(ops["compacted"])
        rates["sorted"].append(s)
        rates["compacted"].append(c)
        ratios.append(c / s)
    out = {}
    for label, rs in rates.items():
        med, disp = _median_disp(rs)
        out[label + "_tps"] = round(med, 1)
        out[label + "_dispersion"] = disp
    med, disp = _median_disp(ratios)
    out["speedup_vs_sorted"] = round(med, 2)
    out["speedup_dispersion"] = disp
    s = comp.summary()
    out["hit_rate"] = s["hit_rate"]
    out["overflow_share"] = s["overflow_share"]
    out["churn_per_sweep"] = s["churn_per_sweep"]
    out["big_fallbacks"] = s["big_fallbacks"]
    out["tuples"] = s["tuples"]
    return out


def run_bench_wire(platform: str, cfg: dict, jax) -> dict:
    """Wire-compression A/B (windflow_tpu/wire.py, guarded by
    tools/check_bench_keys.py + check_bench_regress.py): a SEEDED
    EVENT-time stream over the e2e record spec (i64 id/ts cadence lane,
    low-cardinality key lane, f32 value lane) driven through the
    staged FFAT pipeline twice — wire compression ON vs the
    WF_TPU_WIRE kill switch.  Reports the measured wire bytes/tuple +
    compression ratio (deterministic: EVENT time pins the ts lane's
    codec, so check_bench_regress can tripwire the scalar) and the
    DECODE DISPATCH DELTA: per-staged-batch ``staging.unpack``
    dispatches compressed minus kill-switch, which the zero-extra-
    dispatch contract pins at exactly 0 (the decode is traced INTO the
    unpack program, docs/OBSERVABILITY.md "Wire plane")."""
    import dataclasses

    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu.monitoring.jit_registry import default_registry

    CAP, K, NB = 4096, 256, 16
    n = NB * CAP
    rng = np.random.default_rng(5)
    ks = rng.integers(0, K, n)
    vs = rng.integers(0, 1024, n)

    def records():
        for i in range(n):
            yield {"key": int(ks[i]),
                   "v0": np.float32(vs[i] / 1024.0),
                   "ts": 1_000 + i * 7}

    reg = default_registry()

    def run(wire_on: bool):
        cfgg = dataclasses.replace(wf.default_config,
                                   wire_compression=wire_on)
        cfgg.punctuation_interval_usec = 10 ** 12   # determinism
        src = (wf.Source_Builder(records)
               .withTimestampExtractor(lambda t: t["ts"])
               .withOutputBatchSize(CAP)
               .withRecordSpec({"key": np.int64(0),
                                "v0": np.float32(0.0),
                                "ts": np.int64(0)}).build())
        w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"],
                                        lambda a, b: a + b)
             .withCBWindows(cfg["win"], cfg["slide"])
             .withKeyBy(lambda t: t["key"]).withMaxKeys(K).build())
        g = wf.PipeGraph("bench_wire", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT, config=cfgg)
        g.add_source(src).add(w).add_sink(
            wf.Sink_Builder(lambda r: None)
            .withColumnarSink(defer=4).build())
        base = reg.dispatch_counts().get("staging.unpack", 0)
        t0 = time.perf_counter()
        g.run()
        wall = time.perf_counter() - t0
        disp = reg.dispatch_counts().get("staging.unpack", 0) - base
        st = g.stats()
        return (st["Staging"]["Wire"], disp,
                st["Bytes_H2D_total"], st["Bytes_H2D_logical_total"],
                wall)

    ws_on, d_on, h2d_on, log_on, wall_on = run(True)
    ws_off, d_off, h2d_off, _log_off, wall_off = run(False)
    batches = max(1, ws_on["batches"] + ws_on["raw_batches"])
    return {
        # wire_bytes_per_tuple from the H2D total: raw-shipped batches
        # (if any) count at their full size, so the number is the real
        # transfer cost per tuple, not just the compressed batches'
        "wire_bytes_per_tuple": round(h2d_on / n, 3),
        "logical_bytes_per_tuple": round(log_on / n, 3),
        "compression_ratio": round(log_on / h2d_on, 4) if h2d_on
        else None,
        "decode_dispatch_delta": round((d_on - d_off) / batches, 4),
        "unpack_dispatches_on": d_on,
        "unpack_dispatches_off": d_off,
        "raw_batches": ws_on["raw_batches"],
        "fallback_lanes": ws_on["fallback_lanes"],
        "encode_usec": ws_on["encode_usec"],
        "killswitch_h2d_bytes": h2d_off,
        "wall_on_s": round(wall_on, 3),
        "wall_off_s": round(wall_off, 3),
        "codecs": ws_on["codecs"],
        "tuples": n,
    }


def _e2e_graph(cfg: dict, n_tuples: int, chunks, lat_sink, config=None):
    """Build the whole-framework pipeline (VERDICT r2 item 3: benchmark what
    ``PipeGraph.run()`` sustains, not the raw kernel): columnar byte ingest →
    staging → MapTPU → FilterTPU → FfatWindowsTPU → columnar Sink.  Matches
    the reference's measurement harnesses, which time whole pipelines
    (BASELINE.md: Source→Map_GPU→Filter_GPU→Sink, ``tests/graph_tests_gpu``).

    ``config``: optional :class:`windflow_tpu.Config` threaded to the
    graph — the megastep section forces ``megastep_sweeps`` through it."""
    import windflow_tpu as wf
    from windflow_tpu.io import FrameSource

    import numpy as np

    CAP, K = cfg["cap"], cfg["keys"]
    src = FrameSource(chunks, nv=1, fmt="frames", output_batch_size=CAP)
    # declared record spec (frames stage as i32 key + f32 value lanes):
    # gives preflight a chain to eval and the sweep ledger its
    # payload-vs-overhead byte model (per-hop excess_vs_model)
    src.record_spec = {"key": np.int32(0), "v0": np.float32(0.0)}
    m = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
    f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withCBWindows(cfg["win"], cfg["slide"])
         .withKeyBy(lambda t: t["key"]).withMaxKeys(K).build())
    snk = wf.Sink_Builder(lat_sink).withColumnarSink(defer=4).build()
    g = wf.PipeGraph("bench_e2e", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS, config=config)
    pipe = g.add_source(src)
    pipe.add(m)
    pipe.chain(f)        # Map+Filter fuse into ONE XLA program (chaining)
    pipe.add(w).add_sink(snk)
    return g


def _measure_e2e_graph(graph_factory, n_tuples: int, CAP: int,
                       kernel_tps: float) -> dict:
    """Time one ``PipeGraph.run()`` built by ``graph_factory(lat_sink)``
    and estimate the steady-state rate (shared by the staged and
    device-source e2e modes)."""
    import numpy as np

    lats = []
    rows = [0]
    first_out = [None]

    def lat_sink(c):
        if c is None:
            return
        if first_out[0] is None:
            # first result: every program of the pipeline is now compiled
            first_out[0] = time.perf_counter()
        rows[0] += len(c)
        now = time.time() * 1e6
        tss = np.asarray(c.tss, np.float64)
        tss = tss[tss > 0]      # EOS-flush rows carry ts=0: not steady-state
        if len(tss):
            lats.append(now - tss)

    g = graph_factory(lat_sink)
    t0 = time.perf_counter()
    g.run()
    t_end = time.perf_counter()
    elapsed = t_end - t0
    # sweep ledger (monitoring/sweep_ledger.py): per-hop dispatch/HBM
    # attribution of THIS run — main() folds the median run's section
    # into roofline.per_hop so the 8x bytes/tuple excess is named hop by
    # hop in bench_history.json
    try:
        _st = g.stats()
        sweep = _st.get("Sweep")
        # wire plane (windflow_tpu/wire.py): the staged run's measured
        # compression — main() folds it into the guarded `wire` section
        wire_stats = (_st.get("Staging") or {}).get("Wire")
        # megastep plane (windflow_tpu/megastep.py): resolved K and
        # per-edge scan/fallback accounting of THIS run — the megastep
        # section reads it for its dispatches_per_batch number
        megastep_stats = _st.get("Megastep")
    except Exception:  # lint: broad-except-ok (a ledger read must not
        # cost the bench its artifact; the missing roofline.per_hop key
        # fails check_bench_keys loudly instead)
        sweep = None
        wire_stats = None
        megastep_stats = None
    # steady-state window: from the first sink result (compilation and
    # first-batch warmup done) to the end; the first batch's tuples are out
    # of the window.  The total number is reported alongside.  The steady
    # estimate is only meaningful when the window covers a real share of
    # the run — with few batches the deferred sink emits everything near
    # EOS and the window collapses — otherwise fall back to the full-run
    # number.
    steady_s = (t_end - first_out[0]) if first_out[0] else elapsed
    steady_tuples = max(1, n_tuples - CAP)
    full_rate = n_tuples / elapsed
    if steady_s < 0.2 * elapsed or n_tuples < 6 * CAP:
        steady_rate, estimator = full_rate, "full_run_fallback"
    else:
        steady_rate, estimator = steady_tuples / steady_s, "steady"
    # Sanity guard (VERDICT r3: a collapsed steady window once produced
    # 4.96e8 tup/s on CPU — 140x the kernel rate, physically impossible):
    # the pipeline can never beat its own kernel.  The guard is the
    # kernel rate when known, else a loose multiple of the full-run rate
    # — steady legitimately exceeds full-run by the trace-time share
    # (r5: a 2x faster kernel shrank runs until tracing was half the
    # elapsed time, and a 3x-full-rate guard rejected every honest
    # steady reading; e2e_tuples was also raised to amortize).
    implausible = (steady_rate > 2 * kernel_tps if kernel_tps
                   else steady_rate > 10 * full_rate)
    if estimator == "steady" and implausible:
        estimator = (f"full_run_rejected_outlier"
                     f"(steady={steady_rate:.3g})")
        steady_rate = full_rate
    lat_all = (np.concatenate(lats) if lats else np.array([0.0])) / 1e3
    return {
        "tuples_per_sec": round(steady_rate, 1),
        "steady_estimator": estimator,
        "tuples_per_sec_incl_compile": round(n_tuples / elapsed, 1),
        "p99_window_latency_ms": round(float(np.percentile(lat_all, 99)), 3),
        "p50_window_latency_ms": round(float(np.percentile(lat_all, 50)), 3),
        "window_rows": rows[0],
        "tuples": n_tuples,
        "elapsed_s": round(elapsed, 3),
        "sweep": sweep,
        "wire_stats": wire_stats,
        "megastep_stats": megastep_stats,
    }


def _median_of_runs(one_run, n_runs: int) -> dict:
    """Repeat a whole-graph e2e measurement and report the median run with
    dispersion — the kernel's median-of-windows methodology applied at the
    run level (VERDICT r4 item 6: a single e2e run could not distinguish
    the 0.86→0.74 ratio slide from noise)."""
    runs = [one_run() for _ in range(n_runs)]
    runs.sort(key=lambda r: r["tuples_per_sec"])
    med = dict(runs[len(runs) // 2])
    rates = [r["tuples_per_sec"] for r in runs]
    med["dispersion"] = {
        "runs": n_runs, "min": rates[0], "max": rates[-1],
        "rel_spread": round((rates[-1] - rates[0])
                            / med["tuples_per_sec"], 4),
    }
    return med


def run_bench_e2e(platform: str, cfg: dict, jax,
                  kernel_tps: float = 0.0) -> dict:
    """End-to-end framework throughput + p99 window latency, median of
    ``BENCH_E2E_RUNS`` (default 3) full runs.

    Tuples enter as binary frame bytes (columnar native ingest) and leave
    through a columnar sink; INGRESS time stamps each tuple's arrival in
    wall microseconds, so ``sink receipt − row timestamp`` is the event
    arrival → window result latency through staging, emitters, the driver
    loop, device programs, and egress.  XLA's persistent compilation cache
    is enabled and a small warmup graph (same shapes) is run first so the
    timed runs measure the framework, not the compiler."""
    import numpy as np

    _setup_compile_cache(jax)

    CAP, K = cfg["cap"], cfg["keys"]
    n_tuples = int(os.environ.get("BENCH_E2E_TUPLES", cfg["e2e_tuples"]))
    n_runs = int(os.environ.get("BENCH_E2E_RUNS", "3"))
    rng = np.random.default_rng(1)

    def make_blob(n):
        rec = np.empty(n, dtype=[("k", "<i8"), ("t", "<i8"), ("v", "<f8")])
        rec["k"] = rng.integers(0, K, n)
        rec["t"] = np.arange(n)          # overwritten by INGRESS stamping
        rec["v"] = rng.random(n)
        return rec.tobytes()

    def chunker(blob, chunk_bytes=1 << 20):
        def chunks():
            for lo in range(0, len(blob), chunk_bytes):
                yield blob[lo:lo + chunk_bytes]
        return chunks

    # warmup: compile every program shape (staging CAP, ffat state, sink)
    warm = _e2e_graph(cfg, cfg["e2e_warm_tuples"],
                      chunker(make_blob(cfg["e2e_warm_tuples"])),
                      lambda c: None)
    warm.run()

    blob = make_blob(n_tuples)
    return _median_of_runs(
        lambda: _measure_e2e_graph(
            lambda lat_sink: _e2e_graph(cfg, n_tuples, chunker(blob),
                                        lat_sink),
            n_tuples, CAP, kernel_tps),
        n_runs)


def run_bench_e2e_device(platform: str, cfg: dict, jax,
                         kernel_tps: float = 0.0) -> dict:
    """Device-resident-source e2e (VERDICT r4 item 3): the same pipeline
    shape as :func:`run_bench_e2e` but the source batches are GENERATED ON
    DEVICE (io/device_source.py), so no host→device staging is on the hot
    path.  ``ratio_vs_kernel`` here measures pure framework dispatch
    (driver loop, emitters, program launches); the gap between this and
    the staged e2e number is the staging/link share — the decomposition
    that turns the r3/r4 'link-bound' hypothesis into a measurement."""
    import jax.numpy as jnp
    import numpy as np

    import windflow_tpu as wf

    _setup_compile_cache(jax)
    CAP, K = cfg["cap"], cfg["keys"]
    n_tuples = int(os.environ.get("BENCH_E2E_TUPLES", cfg["e2e_tuples"]))
    n_runs = int(os.environ.get("BENCH_E2E_RUNS", "3"))
    NB = max(1, n_tuples // CAP)
    n_tuples = NB * CAP

    def batch_fn(i):
        # cheap on-device synth: lane-derived keys/values, index-mixed so
        # batches differ; matches the staged blob's key range
        lane = jnp.arange(CAP, dtype=jnp.int32)
        mixed = (lane * 2654435761 + i * 40503) & 0x7FFFFFFF
        return {"key": mixed % K,
                "v0": (mixed % 1024).astype(jnp.float32) / 1024.0}

    def build(lat_sink, nb=None):
        src = (wf.DeviceSource_Builder(batch_fn)
               .withCapacity(CAP).withNumBatches(nb or NB).build())
        m = wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
        f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
        w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"],
                                        lambda a, b: a + b)
             .withCBWindows(cfg["win"], cfg["slide"])
             .withKeyBy(lambda t: t["key"]).withMaxKeys(K).build())
        snk = wf.Sink_Builder(lat_sink).withColumnarSink(defer=4).build()
        g = wf.PipeGraph("bench_e2e_dev", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.INGRESS)
        pipe = g.add_source(src)
        pipe.add(m)
        pipe.chain(f)
        pipe.add(w).add_sink(snk)
        return g

    # warmup: compile the program shapes with a 2-batch stream (the
    # staged path's e2e_warm_tuples idea — not a discarded full run)
    warm_nb = min(2, NB)
    _measure_e2e_graph(lambda ls: build(ls, nb=warm_nb),
                       warm_nb * CAP, CAP, kernel_tps)
    return _median_of_runs(
        lambda: _measure_e2e_graph(build, n_tuples, CAP, kernel_tps),
        n_runs)


def run_bench_megastep(platform: str, cfg: dict, jax,
                       kernel_tps: float = 0.0) -> dict:
    """Megastep A/B (windflow_tpu/megastep.py, guarded by
    tools/check_bench_keys.py + check_bench_regress.py): the staged e2e
    pipeline driven at a DISPATCH-BOUND batch size (small cap, many
    sweeps — the regime the host pacer dominates and the megastep
    exists to fix), once with ``megastep_sweeps`` forced to K and once
    with the K=1 kill switch.  Reports the K-run's steady tuples/sec
    (the guarded floor: CPU >= 10x the r14 54.8k per-batch baseline),
    the measured speedup over the kill-switch run, and the dispatch
    accounting the jit registry pins: one ``megastep.*`` program
    dispatch serves K staged batches, so ``dispatches_per_batch`` over
    the scanned batches is 1/K exactly — warmup (first-batch compile
    probe) and EOS-remainder batches ship per-batch and are reported
    next to it, not hidden in it (docs/OBSERVABILITY.md "Megastep in
    the ledger")."""
    import dataclasses

    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu.megastep import AUTO_K
    from windflow_tpu.monitoring.jit_registry import default_registry

    _setup_compile_cache(jax)

    # dispatch-bound workload: 1k-row sweeps make the per-batch host
    # cost (emitter finalize, drain, ring stamps, sink fold) the
    # dominant term — at the default e2e cap the pipeline is
    # compute-bound on CPU and folding dispatches cannot show
    ms_cfg = dict(cfg, cap=1024, keys=64, win=256, slide=64)
    CAP = ms_cfg["cap"]
    n_tuples = int(os.environ.get("BENCH_MEGASTEP_TUPLES",
                                  2048 * CAP))
    n_runs = int(os.environ.get("BENCH_MEGASTEP_RUNS", "3"))
    K = int(os.environ.get("BENCH_MEGASTEP_K", str(AUTO_K)))
    rng = np.random.default_rng(3)

    rec = np.empty(n_tuples, dtype=[("k", "<i8"), ("t", "<i8"),
                                    ("v", "<f8")])
    rec["k"] = rng.integers(0, ms_cfg["keys"], n_tuples)
    rec["t"] = np.arange(n_tuples)   # overwritten by INGRESS stamping
    rec["v"] = rng.random(n_tuples)
    blob = rec.tobytes()

    def chunks():
        for lo in range(0, len(blob), 1 << 20):
            yield blob[lo:lo + (1 << 20)]

    reg = default_registry()

    def measure(k):
        config = dataclasses.replace(wf.default_config,
                                     megastep_sweeps=k)
        # determinism (same stance as the wire section): periodic
        # punctuations flush partial megastep groups mid-run and turn
        # the scanned/fallback split into wall-clock weather
        config.punctuation_interval_usec = 10 ** 12

        def build(lat_sink):
            return _e2e_graph(ms_cfg, n_tuples, chunks, lat_sink,
                              config=config)

        _measure_e2e_graph(build, n_tuples, CAP, kernel_tps)  # warm
        base = sum(n_disp for name, n_disp in
                   reg.dispatch_counts().items()
                   if name.startswith("megastep."))
        med = _median_of_runs(
            lambda: _measure_e2e_graph(build, n_tuples, CAP,
                                       kernel_tps), n_runs)
        mega_disp = sum(n_disp for name, n_disp in
                        reg.dispatch_counts().items()
                        if name.startswith("megastep.")) - base
        return med, mega_disp

    med_k, disp_k = measure(K)
    med_1, _ = measure(1)

    ms = med_k.pop("megastep_stats") or {}
    med_1.pop("megastep_stats", None)
    edge = (ms.get("edges") or [{}])[0]
    scanned = edge.get("batches", 0)
    megasteps = edge.get("megasteps", 0)
    tps_k, tps_1 = med_k["tuples_per_sec"], med_1["tuples_per_sec"]
    return {
        "k": ms.get("k", K),
        "e2e_tup_s": tps_k,
        # the guarded floor (check_bench_keys): 10x the r14 CPU
        # per-batch staged-e2e baseline (54.8k tup/s).  On TPU the
        # acceptance criterion is ratio_vs_kernel (roofline-relative),
        # not an absolute rate — the chip may sit behind a tunnel
        "e2e_floor_tup_s": 548_000 if platform == "cpu" else 0,
        "e2e_tup_s_k1": tps_1,
        "speedup_vs_k1": round(tps_k / tps_1, 4) if tps_1 else 0.0,
        "ratio_vs_kernel": round(tps_k / kernel_tps, 4)
        if kernel_tps else 0.0,
        # over the SCANNED batches: one compiled program per K sweeps,
        # pinned by the registry's megastep.* dispatch count (the
        # median-of-n run loop makes the count n_runs * megasteps)
        "dispatches_per_batch": round(megasteps / scanned, 4)
        if scanned else None,
        "megastep_dispatches": disp_k,
        "megasteps": megasteps,
        "scanned_batches": scanned,
        "fallback_batches": edge.get("fallback_batches", 0),
        "warmup_batches": edge.get("warmup_batches", 0),
        "steady_estimator": med_k["steady_estimator"],
        "p99_window_latency_ms": med_k["p99_window_latency_ms"],
        "dispersion": med_k.get("dispersion"),
        "tuples": n_tuples,
    }


def run_bench_latency_slo(platform: str, cfg: dict, jax,
                          kernel_tps: float = 0.0) -> dict:
    """Latency-mode leg (windflow_tpu/monitoring/latency_ledger.py,
    guarded by tools/check_bench_keys.py + check_bench_regress.py): a
    representative source→map→window→sink pipeline driven unthrottled —
    the p99 this records is the tail AT max sustainable throughput, the
    operating point named in the row — with the flight recorder and
    latency ledger ON and a declared SLO budget.  Reports the
    ledger-decomposed staged→sunk p50/p99, the dominant (operator,
    segment) pair, per-segment shares, and the SLO verdict state.
    check_bench_keys hard-fails the shipped shape when the measured p99
    exceeds 2x the recorded budget — the bench pipelines must run
    inside their own declared SLO with margin."""
    import dataclasses

    import numpy as np
    import windflow_tpu as wf

    budget_ms = float(os.environ.get("BENCH_SLO_MS", "1000"))
    # many-batch shape (the e2e cap would make the whole CPU run ONE
    # staged batch — nothing to decompose): 64 batches of 4k tuples
    slo_cfg = dict(cfg, cap=4096, keys=64, win=256, slide=64)
    CAP, K = slo_cfg["cap"], slo_cfg["keys"]
    n = int(os.environ.get("BENCH_SLO_TUPLES", str(64 * CAP)))
    # aggressive sampling (1-in-2 vs the production 1-in-64) so a
    # CI-sized run decomposes enough traces for an honest p99
    config = dataclasses.replace(
        wf.default_config, flight_recorder=True, trace_sample_every=2,
        latency_ledger=True, latency_slo_ms=budget_ms)
    src = (wf.Source_Builder(
        lambda: iter({"key": i % K, "v0": float(i)} for i in range(n)))
        .withOutputBatchSize(CAP)
        .withRecordSpec({"key": np.int32(0), "v0": np.float32(0.0)})
        .withName("slo_src").build())
    m = (wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0})
        .withName("slo_map").build())
    w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"], lambda a, b: a + b)
         .withCBWindows(slo_cfg["win"], slo_cfg["slide"])
         .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
         .withName("slo_win").build())
    snk = wf.Sink_Builder(lambda r: None).withName("slo_snk").build()
    g = wf.PipeGraph("bench_latency_slo", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.INGRESS, config=config)
    g.add_source(src).add(m).add(w).add_sink(snk)
    t0 = time.perf_counter()
    g.start()
    while not g.is_done():
        if not g.step():
            break
        g.health_tick()     # ledger tick every sweep: worst-case cadence
    g.wait_end()
    elapsed = time.perf_counter() - t0
    g.health_tick()         # final harvest after the sink's EOS flush
    lp = g.stats()["Latency_plane"]
    e2e_q = lp.get("e2e_usec") or {}
    segs = lp.get("segments_total_usec") or {}
    total = sum(segs.values()) or 1.0
    dom_op, dom_entry = None, {}
    for name, entry in (lp.get("per_op") or {}).items():
        if (entry.get("budget_share") or 0) >= \
                (dom_entry.get("budget_share") or 0):
            dom_op, dom_entry = name, entry
    slo = lp.get("slo") or {}
    return {
        # the operating-point label check_bench_keys requires on every
        # latency row: a p99 is meaningless without the rate it was
        # measured at
        "operating_point": "max_sustainable",
        "tuples_per_sec": round(n / elapsed, 1) if elapsed else 0.0,
        "slo_budget_ms": budget_ms,
        "e2e_p50_ms": round((e2e_q.get("p50") or 0) / 1e3, 3),
        "e2e_p99_ms": round((e2e_q.get("p99") or 0) / 1e3, 3),
        "traces_decomposed": lp.get("traces_decomposed", 0),
        "dominant_op": dom_op,
        "dominant_segment": dom_entry.get("dominant_segment"),
        "segment_share": {s: round(v / total, 4)
                          for s, v in segs.items()},
        "slo_active": bool(slo.get("active")),
        "tuples": n,
    }


def run_bench_tenant(platform: str, cfg: dict, jax) -> dict:
    """Tenant-plane leg (windflow_tpu/monitoring/tenant_ledger.py,
    guarded by tools/check_bench_keys.py + check_bench_regress.py): two
    seeded tenants in ONE process — a Zipf-hot keyed pipeline and a
    uniform one — with the shared ledger attributing HBM/dispatch/byte
    totals per tenant.  Reports the reconciliation fraction (attributed
    staged bytes over process staged bytes — check_bench_keys hard-fails
    under 0.9), the worst budget pressure, and the ledger's measured
    self-cost as a share of the run (same <2% stance as the flight
    recorder and the health watchdog)."""
    import dataclasses

    import numpy as np
    import windflow_tpu as wf
    from windflow_tpu.monitoring.tenant_ledger import default_ledger

    ledger = default_ledger()
    ledger.reset()
    CAP, K = 2048, 64
    n = int(os.environ.get("BENCH_TENANT_TUPLES", str(16 * 2048)))
    budget = 64 * 1024 * 1024   # generous: pressure stays well under 1
    total = 0.0

    def leg(tenant: str, prefix: str, keys) -> None:
        nonlocal total
        config = dataclasses.replace(
            wf.default_config, tenant=tenant, hbm_budget_bytes=budget)
        src = (wf.Source_Builder(
            lambda: iter({"key": keys(i), "v0": float(i)}
                         for i in range(n)))
            .withOutputBatchSize(CAP)
            .withRecordSpec({"key": np.int32(0), "v0": np.float32(0.0)})
            .withName(f"{prefix}_src").build())
        m = (wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0})
            .withName(f"{prefix}_map").build())
        w = (wf.Ffat_WindowsTPU_Builder(
            lambda t: t["v0"], lambda a, b: a + b)
            .withCBWindows(256, 64)
            .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
            .withName(f"{prefix}_win").build())
        snk = wf.Sink_Builder(lambda r: None) \
            .withName(f"{prefix}_snk").build()
        g = wf.PipeGraph(f"bench_tenant_{prefix}",
                         wf.ExecutionMode.DEFAULT, wf.TimePolicy.INGRESS,
                         config=config)
        g.add_source(src).add(m).add(w).add_sink(snk)
        t0 = time.perf_counter()
        g.start()
        while not g.is_done():
            if not g.step():
                break
            g.health_tick()     # ledger tick every sweep, throttled
        g.wait_end()
        total += time.perf_counter() - t0
        g.health_tick()         # final harvest before freeze-at-finalize

    # seeded Zipf-hot keys (key 0 carries ~3/4) vs uniform round-robin
    leg("tenant_hot", "th", lambda i: 0 if i % 4 else i % K)
    leg("tenant_uni", "tu", lambda i: i % K)

    sec = ledger.section()
    pressures = [((t.get("budget") or {}).get("pressure") or 0.0)
                 for t in (sec.get("tenants") or {}).values()]
    frac = (sec.get("attributed") or {}).get("staged_fraction")
    over = sec.get("overhead") or {}
    return {
        "tenants": len(sec.get("tenants") or {}),
        "hbm_attributed_fraction":
            round(frac, 4) if frac is not None else None,
        "budget_pressure": round(max(pressures), 6) if pressures else 0.0,
        "ledger_overhead_pct": round(
            100.0 * (over.get("collect_ms_total") or 0.0)
            / (total * 1e3), 3) if total else 0.0,
        "tuples": 2 * n,
    }


def scaling_step(jax, n: int, K: int, per_chip: int, seed: int = 2):
    """Build one width-``n`` rung of the weak-scaling sweep: the key-sharded
    mesh, the compiled keyed reduce, and its staged inputs.  Shared with the
    test suite so the composition the harness runs on real hardware is the
    composition CI exercises (tests/test_mesh.py)."""
    import jax.numpy as jnp
    import numpy as np

    from windflow_tpu.parallel import mesh as meshmod

    mesh = meshmod.make_mesh(n_devices=n, data=1)
    cap = per_chip * n
    fn = meshmod.make_sharded_keyed_reduce(
        mesh, cap, K,
        lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]},
        key_fn=lambda t: t["k"], use_psum=True)
    rng = np.random.default_rng(seed)
    sh = meshmod.batch_sharding(mesh)
    payload = {
        "k": jax.device_put(
            jnp.asarray(rng.integers(0, K, cap), jnp.int32), sh),
        "v": jax.device_put(
            jnp.asarray(rng.random(cap, dtype=np.float32)), sh),
    }
    valid = jax.device_put(jnp.ones(cap, bool), sh)
    return fn, payload, valid, cap


def _setup_compile_cache(jax) -> None:
    """Persistent XLA compilation cache: fresh operator objects (each graph
    build) re-jit, so cross-run reuse needs the disk cache."""
    os.makedirs("/tmp/wf_jax_cache", exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/wf_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # older jax: first graph still warms per-process caches


def run_bench_ysb(platform: str, cfg: dict, jax) -> dict:
    """Yahoo-Streaming-Benchmark-shaped pipeline throughput (BASELINE.md
    harness list: "YahooStreamingBench ad-analytics DAG"): columnar binary
    ingest → FilterTPU(view events) ⊕ MapTPU(ad→campaign device-table
    join), fused → per-campaign tumbling TB count windows → columnar sink,
    all through ``PipeGraph.run()``."""
    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu.io import FrameSource

    _setup_compile_cache(jax)
    CAP = cfg["cap"]
    n_ads, n_campaigns = 1000, 100
    n_tuples = int(os.environ.get("BENCH_YSB_TUPLES", cfg["e2e_tuples"]))
    rng = np.random.default_rng(3)
    table_np = rng.integers(0, n_campaigns, n_ads).astype(np.int32)

    rec = np.empty(n_tuples, dtype=[("k", "<i8"), ("t", "<i8"),
                                    ("v", "<f8")])
    rec["k"] = rng.integers(0, n_ads, n_tuples)          # ad_id
    # event time spans ~64 tumbling windows so the firing path runs in
    # steady state (not just the EOS flush)
    gap_usec = max(1, 64 * 10_000_000 // n_tuples)
    rec["t"] = np.arange(n_tuples, dtype=np.int64) * gap_usec
    rec["v"] = rng.integers(0, 3, n_tuples).astype(np.float64)  # etype
    blob = rec.tobytes()

    def chunks():
        for lo in range(0, len(blob), 1 << 20):
            yield blob[lo:lo + (1 << 20)]

    import jax.numpy as jnp
    table = jnp.asarray(table_np)
    rows = [0]

    def build():
        src = FrameSource(chunks, nv=1, fmt="frames",
                          output_batch_size=CAP)
        flt = wf.FilterTPU_Builder(lambda e: e["v0"] == 1.0).build()
        prj = wf.MapTPU_Builder(
            lambda e: {"campaign": table[e["key"]], "one": 1}).build()
        win = (wf.Ffat_WindowsTPU_Builder(lambda e: e["one"],
                                          lambda a, b: a + b)
               .withTBWindows(10_000_000, 10_000_000)
               .withKeyBy(lambda e: e["campaign"])
               .withMaxKeys(n_campaigns)
               .withSumCombiner().build())   # sort-free TB placement
        snk = (wf.Sink_Builder(
                lambda c: rows.__setitem__(0, rows[0] + len(c))
                if c is not None else None)
               .withColumnarSink().build())
        g = wf.PipeGraph("bench_ysb", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT)
        pipe = g.add_source(src)
        pipe.add(flt)
        pipe.chain(prj)       # Filter+Map fuse into one XLA program
        pipe.add(win).add_sink(snk)
        return g

    build().run()             # warmup: compile all program shapes
    rows[0] = 0
    t0 = time.perf_counter()
    build().run()
    elapsed = time.perf_counter() - t0
    return {
        "tuples_per_sec": round(n_tuples / elapsed, 1),
        "tuples": n_tuples,
        "window_rows": rows[0],
        "elapsed_s": round(elapsed, 3),
        "shape": "FrameSource->FilterTPU+MapTPU(join)->FfatTB->colSink",
    }


def run_bench_scaling(jax, max_devices: Optional[int] = None) -> dict:
    """Keyed-Reduce weak scaling over a ``(1, n)`` key-sharded mesh
    (BASELINE.json north star: "linear scaling to 8 chips on keyed
    Reduce").  Requires > 1 REAL device: per-chip work is held constant
    (weak scaling) while the mesh widens 1 → N, so ideal efficiency is a
    flat tuples/sec/chip line.  Opt-in (``--scaling`` /
    ``BENCH_SCALING=1``) and refused on virtual/forced-CPU meshes —
    host-core-sharing virtual devices would fabricate the numbers."""
    devs = jax.devices()
    if len(devs) < 2:
        return {"skipped": f"needs >1 real device, have {len(devs)}"}
    if devs[0].platform == "cpu":
        return {"skipped": "virtual CPU mesh: scaling numbers would be "
                           "host-core-sharing artifacts"}
    n_max = min(len(devs), max_devices or len(devs))
    K = 4096
    per_chip = 1 << 20
    series = []
    n = 1
    while n <= n_max:
        fn, payload, valid, cap = scaling_step(jax, n, K, per_chip)
        for _ in range(3):
            table, has = fn(payload, valid)
        jax.block_until_ready(table)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(10):
                table, has = fn(payload, valid)
            jax.block_until_ready(table)
            best = max(best, 10 * cap / (time.perf_counter() - t0))
        series.append({"devices": n,
                       "tuples_per_sec": round(best, 1),
                       "tuples_per_sec_per_chip": round(best / n, 1)})
        n *= 2
    base = series[0]["tuples_per_sec_per_chip"]
    for s in series:
        s["efficiency"] = round(s["tuples_per_sec_per_chip"] / base, 4)
    return {"mode": "weak", "keys": K, "tuples_per_chip": per_chip,
            "series": series}


def run_bench_pallas(platform: str, cfg: dict, jax) -> dict:
    """Pallas kernel section (windflow_tpu/kernels, docs/PERF.md round
    14): the fused FFAT step built with the hand-written kernels
    (segmented grouping + MXU pane combine) A/B'd against the pure-lax
    build of the SAME program, plus the grouping kernel standalone and
    a record-mismatch canary the CI hard-fails on.

    ``interpret_mode`` is the honesty flag: on the CPU fallback the
    kernels run under the Pallas interpreter — a tier-1 correctness
    vehicle, expected SLOWER than lax (the section then runs reduced
    shapes so CI stays fast) — real speedups are compiled-TPU numbers,
    where the ≥1.3x grouping-region target applies."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    import windflow_tpu as wf
    from windflow_tpu import kernels as pk
    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    from windflow_tpu.windows.grouping import order_and_hist

    mode = pk.resolve_pallas(
        dataclasses.replace(wf.default_config, pallas_kernels="auto"))
    sec = {
        "kernels_active": 0,
        "interpret_mode": None,
        "ffat_step_speedup_vs_lax": 0.0,
        "grouping_speedup": 0.0,
        "record_mismatch": 0,
    }
    if mode is None:
        sec["note"] = "no kernel lowering on this backend (lax path)"
        sec["provenance"] = "modeled"
        return sec
    sec["interpret_mode"] = bool(mode.interpret)
    # honesty tag (docs/OBSERVABILITY.md "Calibration plane"): interpreter
    # timings are correctness numbers, never performance evidence
    sec["provenance"] = "interpret" if mode.interpret else "measured"
    sec["kernels_active"] = 3   # grouping, pane combine, dense table
    if mode.interpret:
        CAP, K, steps = 8192, 256, 3
    else:
        CAP, K, steps = cfg["cap"], cfg["keys"], cfg["steps"]
    Pn = math.gcd(cfg["win"], cfg["slide"])
    R, D = cfg["win"] // Pn, cfg["slide"] // Pn

    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    # integer-valued f32 so the MXU banded-matmul sum is EXACT and the
    # record canary can demand bitwise equality
    payload = {
        "k": jax.device_put(
            jnp.asarray(rng.integers(0, K, CAP), jnp.int32), dev),
        "v": jax.device_put(
            jnp.asarray(rng.integers(0, 97, CAP).astype(np.float32)),
            dev),
    }
    ts = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), dev)
    valid = jax.device_put(jnp.ones(CAP, bool), dev)

    lift = lambda x: x["v"]          # noqa: E731
    comb = lambda a, b: a + b        # noqa: E731
    key_fn = lambda x: x["k"]        # noqa: E731

    def timed(pallas):
        step = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lift, comb,
                                      key_fn, monoid="sum",
                                      pallas=pallas))
        st = jax.device_put(
            make_ffat_state(jnp.zeros((), jnp.float32), K, R), dev)
        st, out, fired, ots = step(st, payload, ts, valid)
        jax.block_until_ready(st)
        first = (st, out, fired, ots)
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            s = st
            for _ in range(steps):
                s, out, fired, _ = step(s, payload, ts, valid)
            jax.block_until_ready(s)
            rates.append(steps * CAP / (time.perf_counter() - t0))
        rates.sort()
        return rates[len(rates) // 2], first

    tps_lax, ref = timed(None)
    tps_pal, got = timed(mode)
    sec["ffat_step_speedup_vs_lax"] = round(tps_pal / tps_lax, 4)
    sec["ffat_step_tps_pallas"] = round(tps_pal, 1)
    sec["ffat_step_tps_lax"] = round(tps_lax, 1)

    # record-mismatch canary: the kernel build's FIRST step (state +
    # fired windows) must be bit-identical to the lax build's
    mismatch = 0
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(got)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            mismatch = 1
            break
    # ...and the dense segmented-reduce kernel against the scatter —
    # int32 lanes, inside the COMPILED dtype gate (table_leaf_ok), so
    # this canary runs the same path on a real TPU as on CPU tier-1
    row = jnp.asarray(rng.integers(0, K, CAP), jnp.int32)
    v32 = jnp.asarray(rng.integers(0, 1000, CAP), jnp.int32)
    tab_pk = pk.dense_monoid_table(row, [v32], ["sum"], [0], K,
                                   mode.interpret)[0]
    tab_lax = jnp.zeros(K + 1, jnp.int32).at[row].add(v32)[:K]
    if not np.array_equal(np.asarray(tab_pk), np.asarray(tab_lax)):
        mismatch = 1
    sec["record_mismatch"] = mismatch

    # grouping kernel standalone (the profile's dominant region)
    ids = payload["k"]
    jl = jax.jit(lambda i: order_and_hist(i, K + 1))
    jp = jax.jit(lambda i: pk.order_hist(i, K + 1, mode.interpret))
    for fn in (jl, jp):
        jax.block_until_ready(fn(ids))
    ticks = {}
    for name, fn in (("lax", jl), ("pallas", jp)):
        rates = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(max(3, steps)):
                out = fn(ids)
            jax.block_until_ready(out)
            rates.append((time.perf_counter() - t0) / max(3, steps))
        rates.sort()
        ticks[name] = rates[len(rates) // 2]
    sec["grouping_speedup"] = round(ticks["lax"] / ticks["pallas"], 4)
    return sec


def load_history() -> dict:
    try:
        with open(HISTORY_PATH) as f:
            h = json.load(f)
        # migrate the old single-entry-per-platform shape to run lists
        for k, v in list(h.items()):
            if isinstance(v, dict):
                h[k] = [v]
        return h
    except (OSError, ValueError):
        return {}


def pick_baseline(runs: list, now: float,
                  methodology: Optional[str] = None) -> dict:
    """The previous *round's* number, not a minutes-old rerun: the most
    recent run at least 2 hours old (rounds are ~12 h apart; same-round
    debugging reruns are minutes apart), else the oldest run recorded.
    Prefers an entry recorded under the SAME methodology so vs_baseline
    never reports a methodology switch as a speedup."""
    old = [r for r in runs if now - r.get("t", 0) >= 2 * 3600]
    pool = old if old else (runs[:1] if runs else [])
    if methodology:
        same = [r for r in pool if r.get("methodology") == methodology]
        if same:
            return same[-1]
    return pool[-1] if pool else {}


def save_history(hist: dict) -> None:
    try:
        with open(HISTORY_PATH, "w") as f:
            json.dump(hist, f, indent=2)
            f.write("\n")
    except OSError:
        pass  # read-only checkout: the stdout line is still the artifact


def main() -> None:
    forced = os.environ.get("BENCH_PLATFORM")  # "cpu" forces the fallback
    tpu_error = None
    probe_attempts = None
    if forced == "cpu":
        platform = "cpu"
    else:
        ok, diag, probe_attempts = probe_tpu()
        platform = "tpu" if ok else "cpu"
        if not ok:
            tpu_error = diag

    result = {
        "metric": "ffat_sliding_window_sum_throughput",
        "value": 0.0,
        "unit": "tuples/sec/chip",
        "vs_baseline": 1.0,
    }
    if probe_attempts is not None:
        result["tpu_probe_attempts"] = probe_attempts
    if tpu_error:
        result["tpu_error"] = tpu_error

    if platform == "cpu":
        # The axon sitecustomize overrides JAX_PLATFORMS at interpreter
        # startup, so force CPU through the config API before backend init.
        import jax
        jax.config.update("jax_platforms", "cpu")
        # Pallas kernels resolve to interpret=True on CPU (the tier-1
        # correctness vehicle — docs/PERF.md round 14); the legacy
        # sections pin the lax build so their recorded history stays
        # methodology-comparable, and the `pallas` section below
        # measures the kernels explicitly.  On a real TPU the auto
        # default keeps the compiled kernels on everywhere.
        import windflow_tpu as _wf
        _wf.default_config.pallas_kernels = "0"
    else:
        import jax

    try:
        measured = run_bench(platform, CONFIGS[platform], jax)
    except Exception as e:  # backend died mid-run: report, don't traceback
        result["error"] = f"{type(e).__name__}: {e}"[:400]
        print(json.dumps(result))
        sys.exit(1)

    result.update(measured)

    # backend stamp (docs/OBSERVABILITY.md "Calibration plane"): every
    # result — and every history row appended below — names the backend,
    # device kind, and jax version it was measured on, so
    # check_bench_regress can refuse to compare rows across hardware and
    # a TPU-leg row can never silently come from the CPU fallback.
    result["backend"] = platform
    try:
        result["device_kind"] = str(jax.devices()[0].device_kind)
    except Exception as e:  # lint: broad-except-ok (stamp must not kill
        # the run when the backend probe already succeeded)
        result["device_kind"] = f"unknown ({type(e).__name__})"
    result["jax_version"] = jax.__version__

    # end-to-end framework path (VERDICT r2 item 3): sustained tuples/sec
    # through PipeGraph.run() + p99 event→window-result latency, alongside
    # the kernel number; the ratio shows what the runtime costs on top of
    # the device program.
    if "--scaling" in sys.argv or \
            os.environ.get("BENCH_SCALING") not in (None, "", "0"):
        try:
            result["scaling"] = run_bench_scaling(jax)
        except Exception as e:
            result["scaling"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    try:
        result["ysb"] = run_bench_ysb(platform, CONFIGS[platform], jax)
    except Exception as e:
        result["ysb_error"] = f"{type(e).__name__}: {e}"[:300]

    try:
        result["reduce"] = run_bench_reduce(platform, CONFIGS[platform],
                                            jax)
    except Exception as e:
        result["reduce_error"] = f"{type(e).__name__}: {e}"[:300]

    try:
        result["compaction"] = run_bench_compaction(
            platform, CONFIGS[platform], jax)
    except Exception as e:
        result["compaction_error"] = f"{type(e).__name__}: {e}"[:300]

    try:
        result["pallas"] = run_bench_pallas(platform, CONFIGS[platform],
                                            jax)
    except Exception as e:
        result["pallas_error"] = f"{type(e).__name__}: {e}"[:300]

    try:
        e2e = run_bench_e2e(platform, CONFIGS[platform], jax,
                            kernel_tps=result["value"])
        e2e["ratio_vs_kernel"] = round(
            e2e["tuples_per_sec"] / result["value"], 4) \
            if result["value"] else 0.0
        if e2e["ratio_vs_kernel"] < 0.5:
            # Diagnosis (VERDICT r2 item 3): the kernel number consumes
            # pre-staged device batches; the e2e number pays host→device
            # staging of ~16 B/tuple.  On this environment the chip is
            # remote (tunneled link, ~60-90 MB/s, ~100 ms/transfer RTT), so
            # e2e saturates the LINK, not the chip: staged MB/s below ≈
            # measured link bandwidth.  On host-attached TPU (PCIe/ICI,
            # tens of GB/s) the same path is compute-bound.
            if platform == "tpu":
                # wire-honest MB/s: use the run's MEASURED wire
                # bytes/tuple when the wire stats carry one (equating
                # staged bytes with the 16-B logical payload would
                # overstate the link share under compression)
                _ws = e2e.get("wire_stats") or {}
                _bpt = (_ws["wire_bytes"] / max(1, e2e["tuples"])
                        if _ws.get("wire_bytes") else 16)
                # the tunnel number is a calibration-store constant with
                # a modeled default — the diagnosis line says which, so
                # a "link-bound" verdict is never mistaken for a
                # measurement it didn't make
                from windflow_tpu.monitoring import calibration
                _tun, _tun_prov = calibration.constant(
                    "h2d_tunnel_bytes_per_sec")
                e2e["tunnel_bytes_per_sec"] = _tun
                e2e["tunnel_provenance"] = _tun_prov
                e2e["gap_diagnosis"] = (
                    "link-bound: staging "
                    f"{e2e['tuples_per_sec'] * _bpt / 1e6:.0f}"
                    f" MB/s at {_bpt:.1f} wire B/tuple vs tunnel "
                    f"{_tun / 1e6:.0f} MB/s ({_tun_prov}); kernel "
                    "reads pre-staged HBM")
            else:
                e2e["gap_diagnosis"] = (
                    "cpu fallback: kernel and pipeline share host cores; "
                    "ingest parsing + driver loop compete with compute")
        result["e2e"] = e2e
    except Exception as e:
        result["e2e_error"] = f"{type(e).__name__}: {e}"[:400]

    # device-resident-source e2e: same pipeline, batches born in HBM — the
    # staged-vs-device delta decomposes e2e overhead into staging/link
    # share vs framework-dispatch share (VERDICT r4 item 3)
    try:
        e2e_dev = run_bench_e2e_device(platform, CONFIGS[platform], jax,
                                       kernel_tps=result["value"])
        e2e_dev["ratio_vs_kernel"] = round(
            e2e_dev["tuples_per_sec"] / result["value"], 4) \
            if result["value"] else 0.0
        e2e = result.get("e2e")
        if e2e:
            staged, dev = e2e["tuples_per_sec"], e2e_dev["tuples_per_sec"]
            if dev > 0 and staged > 0:
                # per-tuple time decomposition: staged-run time = dispatch
                # time + staging time (to first order)
                stage_share = max(0.0, 1.0 - staged / dev)
                e2e_dev["decomposition"] = {
                    "staged_tps": staged,
                    "device_source_tps": dev,
                    "staging_share_of_staged_run": round(stage_share, 4),
                    "note": ("device-source run has no host->device "
                             "staging; the delta is the staging/link cost "
                             "the staged e2e pays"),
                }
        result["e2e_device_source"] = e2e_dev
    except Exception as e:
        result["e2e_device_source_error"] = f"{type(e).__name__}: {e}"[:400]

    # the default-config e2e runs above carry the resolved megastep K
    # (auto: per-batch on CPU, K=8 on accelerator backends) — surface
    # the scalar, drop the per-edge detail from the artifact
    for _leg in ("e2e", "e2e_device_source"):
        if isinstance(result.get(_leg), dict):
            _ms = result[_leg].pop("megastep_stats", None)
            result[_leg]["megastep_k"] = (_ms or {}).get("k", 1)

    # megastep section (windflow_tpu/megastep.py, guarded by
    # tools/check_bench_keys.py + check_bench_regress.py): the staged
    # e2e pipeline at a dispatch-bound batch size with K sweeps folded
    # into one compiled program vs the K=1 kill switch — the guarded
    # floor holds the K-run's CPU steady rate at >= 10x the r14
    # per-batch baseline, and dispatches_per_batch pins the 1-program-
    # per-K-sweeps contract via the jit registry
    try:
        result["megastep"] = run_bench_megastep(
            platform, CONFIGS[platform], jax,
            kernel_tps=result["value"])
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # other guarded legs: a megastep regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["megastep_error"] = f"{type(e).__name__}: {e}"[:400]

    # wire section (windflow_tpu/wire.py, guarded by
    # tools/check_bench_keys.py + check_bench_regress.py): the seeded
    # compression A/B over the e2e record spec — wire bytes/tuple,
    # compression ratio (hard floor 1.5x), and the decode dispatch
    # delta (hard-pinned 0: the decode rides the existing unpack
    # program).  staging_share re-reports the staged-vs-device-source
    # decomposition next to the wire numbers it exists to shrink, and
    # the staged e2e run's own measured compression rides along.
    try:
        wire_sec = run_bench_wire(platform, CONFIGS[platform], jax)
        dev = result.get("e2e_device_source")
        wire_sec["staging_share"] = (
            (dev.get("decomposition") or {}).get(
                "staging_share_of_staged_run")
            if isinstance(dev, dict) else None)
        e2e_ws = None
        if isinstance(result.get("e2e"), dict):
            e2e_ws = result["e2e"].pop("wire_stats", None)
        if isinstance(result.get("e2e_device_source"), dict):
            result["e2e_device_source"].pop("wire_stats", None)
        if isinstance(e2e_ws, dict) and e2e_ws.get("wire_bytes"):
            wire_sec["e2e_compression_ratio"] = \
                e2e_ws.get("compression_ratio")
            wire_sec["e2e_wire_bytes_per_tuple"] = round(
                e2e_ws["wire_bytes"] / max(1, result["e2e"]["tuples"]), 3)
        result["wire"] = wire_sec
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # other guarded legs: a wire regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["wire_error"] = f"{type(e).__name__}: {e}"[:300]

    # roofline decomposition (sweep ledger, guarded by
    # tools/check_bench_keys.py): the staged e2e run's per-hop ledger
    # section names where the measured bytes/tuple excess goes —
    # roofline.per_hop carries bytes/tuple + dispatches/batch per
    # operator hop, and attributed_fraction is the hop sum over the raw
    # kernel step's measured bytes (the window hop dominates a healthy
    # pipeline, so the ratio sits near 1; extra hops push it above)
    e2e_sweep = None
    if isinstance(result.get("e2e"), dict):
        e2e_sweep = result["e2e"].pop("sweep", None)
    if isinstance(result.get("e2e_device_source"), dict):
        result["e2e_device_source"].pop("sweep", None)
    roof = result.get("roofline")
    if isinstance(roof, dict):
        per_hop = {}
        for name, h in ((e2e_sweep or {}).get("per_hop") or {}).items():
            per_hop[name] = {
                "bytes_per_tuple": h.get("bytes_per_tuple"),
                "steady_bytes_per_tuple": h.get("steady_bytes_per_tuple"),
                "dispatches_per_batch": h.get("dispatches_per_batch"),
                "excess_vs_model": h.get("excess_vs_model"),
                "donation_miss": bool(h.get("donation_miss")),
            }
        roof["per_hop"] = per_hop
        # steady-state numbers: a short (CI-sized) run's EOS-flush
        # dispatch would dilute the amortized average and misread as
        # missing attribution
        attributed = sum(
            h.get("steady_bytes_per_tuple") or h.get("bytes_per_tuple")
            or 0 for h in per_hop.values())
        mbpt = roof.get("measured_bytes_per_tuple")
        roof["attributed_fraction"] = (
            round(attributed / mbpt, 4) if mbpt and attributed else None)

    # whole-chain fusion (windflow_tpu/fusion, guarded by
    # tools/check_bench_keys.py): the staged e2e run's realized fusion
    # savings — fused chain names, dispatches the sweep no longer pays
    # (N member hops -> one jitted dispatch per batch), and the interior
    # boundary bytes the fused program never materializes in HBM.
    # Recorded into bench_history.json so round-over-round comparisons
    # see fusion on/off regressions; with WF_TPU_FUSE=0 the section
    # still ships (zeros) so the keys guard holds on both paths.
    fus = (e2e_sweep or {}).get("fusion") or {}
    result["fusion"] = {
        "enabled": bool(fus.get("enabled")),
        "fused_chains": fus.get("fused_chains", []),
        "dispatches_saved": fus.get("dispatches_saved_per_batch", 0.0),
        "bytes_saved_per_batch": fus.get("bytes_saved_per_batch", 0.0),
    }

    # latency section (guarded by tools/check_bench_keys.py): the p50/p99
    # distribution numbers the flight-recorder observability layer makes
    # first-class — recorded into bench_history.json so round-over-round
    # comparisons read tails, not means (docs/OBSERVABILITY.md)
    latency = {"batch_p99_ms": result.get("p99_batch_latency_ms")}
    if result.get("e2e"):
        latency["e2e_p50_ms"] = result["e2e"].get("p50_window_latency_ms")
        latency["e2e_p99_ms"] = result["e2e"].get("p99_window_latency_ms")
    # every latency row names its operating point (check_bench_keys
    # rejects unlabeled rows): these numbers come from the default
    # unthrottled e2e runs above
    latency["operating_point"] = "default_e2e"
    result["latency"] = latency

    # latency-SLO section (windflow_tpu/monitoring/latency_ledger.py,
    # guarded by tools/check_bench_keys.py + check_bench_regress.py):
    # the ledger-decomposed staged->sunk p99 at max sustainable
    # throughput against a declared budget — check_bench_keys hard-fails
    # p99 > 2x the recorded SLO, check_bench_regress tripwires the p99
    # round over round
    try:
        result["latency_slo"] = run_bench_latency_slo(
            platform, CONFIGS[platform], jax, kernel_tps=result["value"])
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # other guarded legs: a latency-plane regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["latency_slo_error"] = f"{type(e).__name__}: {e}"[:400]

    # tenant section (windflow_tpu/monitoring/tenant_ledger.py, guarded
    # by tools/check_bench_keys.py + check_bench_regress.py): two seeded
    # tenants in one process — check_bench_keys hard-fails when the
    # ledger attributes under 90% of the process's staged bytes or its
    # measured self-cost crosses 2% of the run
    try:
        result["tenant"] = run_bench_tenant(platform, CONFIGS[platform],
                                            jax)
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # other guarded legs: a tenant-plane regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["tenant_error"] = f"{type(e).__name__}: {e}"[:400]

    # preflight cost (windflow_tpu/analysis, guarded by
    # tools/check_bench_keys.py): time PipeGraph.check() over the
    # representative e2e pipeline shape so the static-analysis cost every
    # start() now pays stays visible in the perf trajectory
    try:
        import numpy as np
        import windflow_tpu as wf
        pf_cfg = CONFIGS[platform]
        m = wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "v0": t["v0"] * 1.5 + 1.0}).build()
        f = wf.FilterTPU_Builder(lambda t: (t["key"] & 7) != 7).build()
        w = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v0"],
                                        lambda a, b: a + b)
             .withCBWindows(pf_cfg["win"], pf_cfg["slide"])
             .withKeyBy(lambda t: t["key"])
             .withMaxKeys(pf_cfg["keys"]).build())
        src = (wf.Source_Builder(lambda: iter(()))
               .withOutputBatchSize(pf_cfg["cap"])
               .withRecordSpec({"key": np.int32(0),
                                "v0": np.float32(0.0)}).build())
        pg = wf.PipeGraph("bench_preflight")
        pipe = pg.add_source(src)
        pipe.add(m)
        pipe.chain(f)
        pipe.add(w).add_sink(wf.Sink_Builder(lambda r: None).build())
        diags = pg.check()
        result["preflight"] = {"check_ms": pg._preflight_ms,
                               "diagnostics": len(diags)}
        # wfverify (windflow_tpu/analysis/tracecheck.py, guarded by
        # tools/check_bench_keys.py): the object-level verifier's cost
        # and finding count over the same representative pipeline —
        # `findings` doubles as a tripwire: the bench kernels ship
        # clean, so any nonzero count is a verifier false positive or a
        # real kernel regression.  check() above already ran the pass
        # and kept its report (with the COLD check_ms); re-verifying
        # here would publish a warm-cache time
        vrep = pg._tracecheck_report
        if vrep is None:
            from windflow_tpu.analysis.tracecheck import verify_graph
            vrep = verify_graph(pg)
        result["verify"] = {"findings": len(vrep.diagnostics),
                            "suppressed": len(vrep.suppressed),
                            "checked_callables": vrep.checked,
                            "check_ms": vrep.check_ms}
    except Exception as e:  # lint: broad-except-ok (the bench must not
        # die on an analysis regression; the missing key fails
        # check_bench_keys loudly instead)
        result["preflight_error"] = f"{type(e).__name__}: {e}"[:200]

    # wfir (windflow_tpu/analysis/ir_audit.py, guarded by
    # tools/check_bench_keys.py): context-free WF9xx audit over EVERY
    # program this bench process compiled — the real e2e/kernel/megastep
    # runs above, not a fixture.  `findings` is a hard tripwire: shipped
    # bench programs audit clean, so any nonzero count is a lowering
    # regression (a callback, a 64-bit survivor, a donation miss) or an
    # auditor false positive — both stop the bench leg.
    try:
        from windflow_tpu.analysis import ir_audit
        irep = ir_audit.process_report()
        result["ir_audit"] = {
            "programs_audited": irep.programs_audited,
            "findings": len(irep.findings),
            "check_ms": round(irep.check_ms, 3),
        }
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # preflight section: the missing key fails check_bench_keys
        # loudly instead of killing the bench)
        result["ir_audit_error"] = f"{type(e).__name__}: {e}"[:200]

    # health section (windflow_tpu/monitoring/health, guarded by
    # tools/check_bench_keys.py): drive a representative pipeline with the
    # watchdog ON and report stall events (any nonzero is a regression —
    # the bench pipelines must run healthy) plus the watchdog's measured
    # self-cost as a share of the run (same <2% stance as the flight
    # recorder; the plane only runs at cadence, so this stays ~0)
    try:
        import windflow_tpu as wf
        h_src = (wf.Source_Builder(
            lambda: iter({"key": i % 64, "v0": float(i)}
                         for i in range(65536)))
            .withOutputBatchSize(4096).withName("h_src").build())
        h_map = (wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "v0": t["v0"] * 1.5})
            .withName("h_map").build())
        h_snk = wf.Sink_Builder(lambda r: None).withName("h_snk").build()
        h_pg = wf.PipeGraph("bench_health")
        h_pg.add_source(h_src).add(h_map).add_sink(h_snk)
        t0 = time.perf_counter()
        h_pg.start()
        while not h_pg.is_done():
            if not h_pg.step():
                break       # wait_end raises the diagnosed stall error
            h_pg.health_tick()          # every sweep: worst-case cadence
        h_pg.wait_end()
        run_usec = (time.perf_counter() - t0) * 1e6
        h = h_pg.stats()["Health"]
        result["health"] = {
            "graph_state": h["graph_state"],
            "stall_events": h["stall_events"],
            "watchdog_samples": h["samples_taken"],
            "watchdog_overhead_pct": round(
                100.0 * h["watchdog_usec_total"] / run_usec, 3)
            if run_usec else 0.0,
        }
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # preflight leg: a health-plane regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["health_error"] = f"{type(e).__name__}: {e}"[:200]

    # durability section (windflow_tpu/durability, guarded by
    # tools/check_bench_keys.py + check_bench_regress.py): drive the
    # representative kafka->map->window->sink graph with checkpointing
    # OFF then ON (same data, same cadence contract the chaos harness
    # uses), report the checkpoint wall cost/bytes and the e2e overhead
    # of enabling durability (acceptance bound: <5%), then time a real
    # PipeGraph.restore() from the committed store — the restored run
    # replays the tail through the sink fence, so this leg doubles as an
    # exactly-once smoke (nonzero lost/duplicated output would change
    # the topic, caught by the chaos suite's record diff in CI).
    _dwork = None
    try:
        import tempfile as _tf
        from windflow_tpu.durability import chaos as _chaos
        _dn = int(os.environ.get("BENCH_DURABILITY_TUPLES", "32768"))
        _dwork = _tf.mkdtemp(prefix="bench_durability_")
        _chaos.make_cell("window_cb", "", n=_dn)["factory"]().run()  # warm
        t0 = time.perf_counter()
        _chaos.make_cell("window_cb", "", n=_dn)["factory"]().run()
        _t_off = time.perf_counter() - t0
        _dck = os.path.join(_dwork, "ckpt")
        _cell = _chaos.make_cell("window_cb", _dck, n=_dn,
                                 epoch_sweeps=16)
        t0 = time.perf_counter()
        _gd = _cell["factory"]().run()
        _t_on = time.perf_counter() - t0
        _dsec = _gd.stats()["Durability"]
        _gr = _cell["factory"]()
        _gr.restore(_dck)
        _gr.wait_end()
        result["durability"] = {
            "epochs_committed": _dsec["epochs_committed"],
            # mean over the run's epochs, not the last sample: each
            # checkpoint includes an fsync, so a single shot carries
            # I/O jitter the trend guards would trip on
            "checkpoint_ms": round(
                _dsec["checkpoint_ms_total"]
                / max(1, _dsec["epochs_committed"]), 3),
            "checkpoint_bytes": _dsec["last_checkpoint_bytes"],
            "restore_ms": _gr.stats()["Durability"]["restore_ms"],
            "overhead_pct": round(100.0 * (_t_on - _t_off)
                                  / max(_t_off, 1e-9), 2),
            "tuples": _dn,
        }
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # preflight/health legs: a durability regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["durability_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        if _dwork is not None:
            import shutil as _sh
            _sh.rmtree(_dwork, ignore_errors=True)

    # shard-plane section (windflow_tpu/monitoring/shard_ledger, guarded
    # by tools/check_bench_keys.py + check_bench_regress.py): drive a
    # seeded Zipf-skewed keyby workload (40% of the stream on one hot
    # key) through a keyed ReduceTPU at parallelism 2 with the shard
    # ledger ON and report the measured imbalance ratio, the hot key's
    # stream share, and the ICI model total (0.0 on a single chip — the
    # key exists so the multi-chip legs guard the same schema).  The
    # stream is deterministic, so these are regression tripwires, not
    # weather: a drifting imbalance_ratio means the sketch or the
    # placement hash broke.
    try:
        import numpy as np
        import windflow_tpu as wf
        _sn = int(os.environ.get("BENCH_SHARD_TUPLES", "32768"))
        _srng = np.random.default_rng(11)
        _sk = _srng.integers(0, 64, _sn)
        _sk[_srng.random(_sn) < 0.4] = 7          # injected hot key
        def _s_build():
            src = (wf.Source_Builder(
                lambda: iter({"key": int(k), "v": 1.0} for k in _sk))
                .withOutputBatchSize(4096).withName("sh_src").build())
            red = (wf.ReduceTPU_Builder(
                lambda a, b: {"key": b["key"], "v": a["v"] + b["v"]})
                .withKeyBy(lambda t: t["key"]).withParallelism(2)
                .withName("sh_red").build())
            pg = wf.PipeGraph("bench_shard")
            pg.add_source(src).add(red).add_sink(
                wf.Sink_Builder(lambda t, ctx=None: None)
                .withName("sh_snk").build())
            return pg
        _s_build().run()     # warmup: the overhead ratio below must
        #                      compare sketch time against a steady run,
        #                      not one dominated by first-compile wall
        _s_pg = _s_build()
        t0 = time.perf_counter()
        _s_pg.run()
        _s_run_usec = (time.perf_counter() - t0) * 1e6
        _s_sec = _s_pg.stats()["Shard"]
        _s_load = _s_sec["per_op"]["sh_red"]["load"]
        _s_tot = _s_sec["totals"]
        result["shard"] = {
            "imbalance_ratio": _s_load.get("imbalance_ratio"),
            "hot_key_share": _s_load.get("hot_key_share"),
            "hot_key": (_s_load.get("hot_keys") or [{}])[0].get("key"),
            "hot_shard": _s_load.get("hot_shard"),
            "ici_bytes_per_tuple": _s_tot.get("ici_bytes_per_tuple",
                                              0.0),
            "sketch_overhead_pct": round(
                100.0 * _s_tot.get("sketch_host_update_usec", 0.0)
                / _s_run_usec, 3) if _s_run_usec else 0.0,
            "tuples": _sn,
        }
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # preflight/health legs: a shard-plane regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["shard_error"] = f"{type(e).__name__}: {e}"[:200]

    # reshard-executor section (windflow_tpu/serving, guarded by
    # tools/check_bench_keys.py + check_bench_regress.py): two legs.
    # (1) live reshard — a seeded hash-colocated warm-key pair on a
    # keyed host Reduce at parallelism 3 with the executor ON: the
    # delta-window trigger fires, a move_keys plan applies through the
    # quiesce barrier, and the leg reports the apply wall cost, the
    # keys moved, and the post-reshard window imbalance (the number the
    # move exists to repair).  (2) rescale restore — a chaos cell
    # killed at 3 shards and restored at 2, timing the re-bucketing
    # restore (durability/rebucket.py).  Both streams are
    # deterministic: these are regression tripwires, not weather.
    _rwork = None
    try:
        import dataclasses as _rdc
        import tempfile as _tf

        import windflow_tpu as wf
        from windflow_tpu.basic import stable_hash as _sh64
        _rn = int(os.environ.get("BENCH_RESHARD_TUPLES", "24000"))
        _hot = [k for k in range(200) if _sh64(k) % 3 == 0][:2]

        def _r_stream():
            for i in range(_rn):
                r = i % 20
                k = _hot[0] if r < 5 else (
                    _hot[1] if r < 10 else (i % 12))
                yield {"key": k, "value": float(i % 97)}

        def _r_red(item, state):
            state["key"] = item["key"]
            state["n"] = state.get("n", 0) + 1

        _rcfg = _rdc.replace(wf.default_config)
        _rcfg.reshard_executor = True
        _rcfg.reshard_check_sweeps = 4
        _rcfg.reshard_trigger_ticks = 2
        _rcfg.reshard_ok_ticks = 2
        _rcfg.reshard_imbalance_threshold = 1.6
        _rcfg.punctuation_interval_usec = 10 ** 12
        _rg = wf.PipeGraph("bench_reshard", config=_rcfg)
        _rsrc = (wf.Source_Builder(_r_stream)
                 .withOutputBatchSize(256).withName("rs_src").build())
        _rred = (wf.Reduce_Builder(_r_red, dict)
                 .withKeyBy(lambda t: t["key"]).withParallelism(3)
                 .withName("rs_red").build())
        _rg.add_source(_rsrc).add(_rred).add_sink(
            wf.Sink_Builder(lambda t, ctx=None: None)
            .withName("rs_snk").build())
        _rg.run()
        _rsec = _rg.stats()["Reshard"]
        from windflow_tpu.durability import chaos as _rchaos
        _rwork = _tf.mkdtemp(prefix="bench_reshard_")
        _rv = _rchaos.run_rescale_ab(
            "reduce", "mid_epoch", _rwork, shards_kill=3,
            shards_restore=2,
            n=int(os.environ.get("BENCH_RESCALE_TUPLES", "4096")))
        if _rv["diff"] is not None:
            raise RuntimeError(f"rescale cell diverged: {_rv['diff']}")
        result["reshard"] = {
            "plans_applied": _rsec["plans_applied"],
            "keys_moved": _rsec["keys_moved"],
            "plan_apply_ms": _rsec["quiesce_ms"],
            "post_reshard_imbalance":
                (_rsec["ops"].get("rs_red") or {}).get(
                    "window_imbalance"),
            "rescale_restore_ms": _rv["restore_ms"],
            "tuples": _rn,
        }
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # durability/shard legs: a reshard regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["reshard_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        if _rwork is not None:
            import shutil as _sh
            _sh.rmtree(_rwork, ignore_errors=True)

    # device-plane section (windflow_tpu/monitoring/jit_registry, guarded
    # by tools/check_bench_keys.py): the compile watcher's process totals
    # over every leg above — compile wall cost, recompile events (any
    # nonzero here is a recompilation-storm regression in the bench
    # pipelines), plus the window kernel's cost table where the backend
    # reported one
    try:
        from windflow_tpu.monitoring.jit_registry import default_registry
        reg = default_registry()
        snap = reg.snapshot()
        flops = None
        for name, entry in sorted(snap.items()):
            f = (entry.get("cost") or {}).get("flops")
            if not f:
                continue
            if flops is None:
                flops = f           # any-op fallback: first with a cost
            if "ffat" in name or "win" in name:
                flops = f           # prefer the window kernel's number
                break
        totals = reg.totals()
        result["device"] = {"ops_compiled": totals["ops_compiled"],
                            "compiles": totals["compiles"],
                            "recompiles": totals["recompiles"],
                            "compile_ms_total": totals["compile_ms_total"],
                            "flops_per_batch": flops}
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # preflight leg: a watcher regression must fail check_bench_keys,
        # not kill the bench artifact)
        result["device_error"] = f"{type(e).__name__}: {e}"[:200]

    # calibration section (windflow_tpu/monitoring/calibration.py, guarded
    # by tools/check_bench_keys.py): which constants this run computed
    # modeled numbers from, and whether a calibration store replaced the
    # defaults — the bench artifact's own measured-vs-modeled manifest
    try:
        from windflow_tpu.monitoring import calibration as _calib
        result["calibration"] = _calib.provenance_summary()
    except Exception as e:  # lint: broad-except-ok (same stance as the
        # preflight leg: a provenance regression must fail
        # check_bench_keys loudly, not kill the bench artifact)
        result["calibration_error"] = f"{type(e).__name__}: {e}"[:200]

    # TPU acceptance leg (ROADMAP item 1, guarded by
    # tools/check_bench_keys.py): on a REAL chip — never the CPU
    # fallback, never the Pallas interpreter — record the item-1
    # acceptance numbers next to their criteria so a passing TPU round
    # is machine-checkable.  Each number names its provenance; a row
    # claiming interpret-mode timings hard-fails check_bench_keys.
    if platform == "tpu":
        pal = result.get("pallas") or {}
        _grp = pal.get("grouping_speedup")
        _e2e_wire = (result.get("wire") or {}).get(
            "e2e_wire_bytes_per_tuple")
        _msr = (result.get("megastep") or {}).get("ratio_vs_kernel")
        _interp = bool(pal.get("interpret_mode"))
        _pal_prov = "interpret" if _interp else "measured"
        result["tpu_acceptance"] = {
            "device_kind": result["device_kind"],
            "grouping_speedup": _grp,
            "grouping_speedup_target": 1.3,
            "grouping_speedup_met": (
                bool(_grp is not None and not _interp and _grp >= 1.3)),
            "grouping_provenance": _pal_prov,
            "e2e_wire_bytes_per_tuple": _e2e_wire,
            "wire_provenance": "measured",
            "ici_bytes_per_tuple": (result.get("shard") or {}).get(
                "ici_bytes_per_tuple"),
            "ici_provenance": ((result.get("calibration") or {})
                               .get("constants", {})
                               .get("ici_bytes_per_sec", {})
                               .get("provenance", "modeled")),
            "megastep_ratio_vs_kernel": _msr,
            "megastep_provenance": "measured",
            "interpret_mode": _interp,
        }

    now = time.time()
    hist = load_history()
    runs = hist.setdefault(platform, [])
    base = pick_baseline(runs, now, result.get("methodology"))
    if base.get("value"):
        if base.get("methodology") == result.get("methodology"):
            result["vs_baseline"] = round(
                result["value"] / base["value"], 4)
        elif result.get("dispatch_value") and base.get("dispatch_value"):
            # methodologies differ but both runs carry the per-dispatch
            # number: that is the one series present on both sides
            result["vs_baseline"] = round(
                result["dispatch_value"] / base["dispatch_value"], 4)
            result["vs_baseline_note"] = (
                "methodology differs from baseline; ratio compares "
                "dispatch_value on both sides")
        elif result.get("dispatch_value"):
            # the stored baseline predates scan-chaining and measured
            # per-dispatch throughput: compare like with like
            result["vs_baseline"] = round(
                result["dispatch_value"] / base["value"], 4)
            result["vs_baseline_note"] = (
                "baseline entry predates the scan-chained methodology; "
                "ratio uses dispatch_value (same per-dispatch "
                "measurement as the baseline)")
        else:
            result["vs_baseline"] = round(
                result["value"] / base["value"], 4)
            result["vs_baseline_note"] = (
                "methodology differs from baseline and no shared "
                "per-dispatch series exists; ratio is cross-methodology")
        result["prev_value"] = base["value"]
        result["prev_methodology"] = base.get("methodology")
    runs.append({"value": result["value"],
                 # comparability stamp: check_bench_regress refuses to
                 # diff rows recorded on different hardware
                 "backend": result.get("backend"),
                 "device_kind": result.get("device_kind"),
                 "jax_version": result.get("jax_version"),
                 "pallas": result.get("pallas"),
                 "tpu_acceptance": result.get("tpu_acceptance"),
                 "methodology": result.get("methodology"),
                 "dispersion": result.get("dispersion"),
                 "dispatch_value": result.get("dispatch_value"),
                 "dispatch_dispersion": result.get("dispatch_dispersion"),
                 "sum_decl_value": result.get("sum_decl_value"),
                 "sum_decl_methodology": result.get("sum_decl_methodology"),
                 "p99_batch_latency_ms": result["p99_batch_latency_ms"],
                 "roofline": result.get("roofline"),
                 "fusion": result.get("fusion"),
                 "latency": result.get("latency"),
                 "latency_slo": result.get("latency_slo"),
                 "tenant": result.get("tenant"),
                 "preflight": result.get("preflight"),
                 "verify": result.get("verify"),
                 "ir_audit": result.get("ir_audit"),
                 "device": result.get("device"),
                 "health": result.get("health"),
                 "shard": result.get("shard"),
                 "wire": result.get("wire"),
                 "megastep": result.get("megastep"),
                 "durability": result.get("durability"),
                 "e2e": result.get("e2e"),
                 "e2e_device_source": result.get("e2e_device_source"),
                 "ysb": result.get("ysb"),
                 "reduce": result.get("reduce"),
                 "compaction": result.get("compaction"),
                 "t": now,
                 "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S")})
    del runs[:-48]  # retention: debugging reruns can burn through a
    #                 20-entry window in one session and rotate out the
    #                 prior round's record the baseline picker needs
    save_history(hist)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
