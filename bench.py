"""Benchmark: FFAT sliding-window sum throughput on one chip (the north-star
metric, BASELINE.json: "tuples/sec/chip on FFAT sliding-window sum; p99
window latency").

Runs the flagship per-batch program (see ``__graft_entry__.entry``): staged
batches of ``CAP`` tuples over ``K`` keys, count-based sliding window
``WIN``/``SLIDE`` decomposed into panes, all fired windows of all keys
computed in one fused XLA program per batch.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is 1.0: the reference publishes no in-repo numbers
(BASELINE.md — `published: {}`), so this records round-over-round progress
against our own first measurement instead.
"""

import json
import math
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from windflow_tpu.windows.ffat_kernels import make_ffat_state, make_ffat_step

CAP = 262144         # tuples per staged batch (sweet spot on v5e: the
                     # sliding-reduce kernel is dispatch-bound below ~128k)
K = 1024             # distinct keys
WIN, SLIDE = 1024, 128
WARMUP = 6
STEPS = 40
LAT_STEPS = 20


def main() -> None:
    Pn = math.gcd(WIN, SLIDE)
    R, D = WIN // Pn, SLIDE // Pn

    lift = lambda x: x["v"]
    comb = lambda a, b: a + b
    key_fn = lambda x: x["k"]

    step = jax.jit(make_ffat_step(CAP, K, Pn, R, D, lift, comb, key_fn),
                   donate_argnums=(0,))

    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    # A few pre-staged batches cycled round-robin, so host staging cost is
    # off the timed path (the driver loop overlaps staging with compute in
    # production; here we isolate device throughput).
    batches = []
    for i in range(4):
        payload = {
            "k": jax.device_put(
                jnp.asarray(rng.integers(0, K, CAP), jnp.int32), dev),
            "v": jax.device_put(
                jnp.asarray(rng.random(CAP, dtype=np.float32)), dev),
        }
        ts = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), dev)
        valid = jax.device_put(jnp.ones(CAP, bool), dev)
        batches.append((payload, ts, valid))

    state = make_ffat_state(jnp.zeros((), jnp.float32), K, R)
    state = jax.device_put(state, dev)

    for i in range(WARMUP):
        p, t, v = batches[i % len(batches)]
        state, out, fired, _ = step(state, p, t, v)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for i in range(STEPS):
        p, t, v = batches[i % len(batches)]
        state, out, fired, _ = step(state, p, t, v)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0
    tuples_per_sec = STEPS * CAP / elapsed

    # p99 per-batch latency: timed with a sync per step (dispatch pipeline
    # drained), so it is an upper bound on steady-state window latency.
    lats = []
    for i in range(LAT_STEPS):
        p, t, v = batches[i % len(batches)]
        t1 = time.perf_counter()
        state, out, fired, _ = step(state, p, t, v)
        jax.block_until_ready(out)
        lats.append(time.perf_counter() - t1)
    p99_ms = float(np.percentile(np.array(lats) * 1e3, 99))

    result = {
        "metric": "ffat_sliding_window_sum_throughput",
        "value": round(tuples_per_sec, 1),
        "unit": "tuples/sec/chip",
        "vs_baseline": 1.0,
        "p99_batch_latency_ms": round(p99_ms, 3),
        "config": {"cap": CAP, "keys": K, "win": WIN, "slide": SLIDE,
                   "device": str(jax.devices()[0])},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
