from windflow_tpu.graph.multipipe import MultiPipe
from windflow_tpu.graph.pipegraph import PipeGraph
