"""Per-replica runtime context handed to "riched" user functions.

Equivalent of the reference's ``RuntimeContext`` (``/root/reference/wf/context.hpp:53-120``)
and ``LocalStorage`` (``local_storage.hpp:56-100``): replica index/parallelism,
the timestamp/watermark of the input being processed, and a name→object store
for user state that must live with the replica.
"""

from __future__ import annotations

from typing import Any, Dict


class LocalStorage:
    """Typed name→value store (reference ``local_storage.hpp:56-100``).
    Python needs no ``void*`` gymnastics — any object can be stored."""

    def __init__(self) -> None:
        self._store: Dict[str, Any] = {}

    def is_contained(self, name: str) -> bool:
        return name in self._store

    def get(self, name: str, default: Any = None) -> Any:
        return self._store.setdefault(name, default)

    def put(self, name: str, value: Any) -> None:
        self._store[name] = value

    def remove(self, name: str) -> None:
        self._store.pop(name, None)


class RuntimeContext:
    """Reference ``context.hpp:53-120``: identifies the replica and exposes the
    metadata of the input currently being processed."""

    def __init__(self, parallelism: int, replica_index: int,
                 operator_name: str = "") -> None:
        self._parallelism = parallelism
        self._replica_index = replica_index
        self._operator_name = operator_name
        self._current_ts = 0
        self._current_wm = 0
        self.local_storage = LocalStorage()

    # -- identification -----------------------------------------------------
    @property
    def parallelism(self) -> int:
        return self._parallelism

    @property
    def replica_index(self) -> int:
        return self._replica_index

    @property
    def operator_name(self) -> str:
        return self._operator_name

    # -- per-input metadata (set by the replica before each user call) ------
    def _set_context(self, ts: int, wm: int) -> None:
        self._current_ts = ts
        self._current_wm = wm

    def get_current_timestamp(self) -> int:
        return self._current_ts

    def get_last_watermark(self) -> int:
        return self._current_wm
