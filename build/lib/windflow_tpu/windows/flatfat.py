"""FlatFAT: flat fixed-size aggregator tree for incremental sliding-window
aggregation (reference ``/root/reference/wf/flatfat.hpp:54-``).

A segment tree over a ring buffer of ``capacity`` (power of two) leaves.
Leaves hold lifted values (or pane aggregates); internal nodes hold the
combination of their children, so any window range query costs O(log C) and a
leaf update costs O(log C) ancestor refreshes — instead of O(window) recompute
per slide (SURVEY.md §5.7a).  ``None`` is the identity: empty leaves/subtrees
are skipped, so no identity element is required of the user combiner (the
reference fills gaps with default-constructed results; ``None`` is cleaner).

Positions are *logical* (monotonically growing tuple index or pane id); the
physical slot is ``pos % capacity``.  The caller is responsible for not
querying ranges wider than the capacity (windows plus in-flight slack)."""

from __future__ import annotations

from typing import Any, Callable, Optional


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class FlatFAT:
    __slots__ = ("comb", "capacity", "_tree", "_slot_pos")

    def __init__(self, comb: Callable[[Any, Any], Any], capacity: int) -> None:
        self.comb = comb
        self.capacity = next_pow2(max(2, capacity))
        # 1-based heap layout: node 1 is the root, leaves at [C, 2C).
        self._tree = [None] * (2 * self.capacity)
        # logical position currently held by each leaf slot (-1 = empty)
        self._slot_pos = [-1] * self.capacity

    def _comb2(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return self.comb(a, b)

    def update(self, pos: int, value: Any,
               fold: Optional[Callable] = None) -> None:
        """Write (or fold into) the leaf for logical position ``pos`` and
        refresh its ancestors (reference leaf insert + ``update`` path,
        ``flatfat.hpp``)."""
        slot = pos % self.capacity
        i = self.capacity + slot
        if self._slot_pos[slot] == pos and self._tree[i] is not None \
                and fold is not None:
            self._tree[i] = fold(self._tree[i], value)
        else:
            self._tree[i] = value
            self._slot_pos[slot] = pos
        i >>= 1
        while i >= 1:
            self._tree[i] = self._comb2(self._tree[2 * i],
                                        self._tree[2 * i + 1])
            i >>= 1

    def evict(self, pos: int) -> None:
        """Clear the leaf for logical position ``pos`` if it still holds it."""
        slot = pos % self.capacity
        if self._slot_pos[slot] == pos:
            self._slot_pos[slot] = -1
            i = self.capacity + slot
            self._tree[i] = None
            i >>= 1
            while i >= 1:
                self._tree[i] = self._comb2(self._tree[2 * i],
                                            self._tree[2 * i + 1])
                i >>= 1

    def holds(self, pos: int) -> bool:
        return self._slot_pos[pos % self.capacity] == pos

    def live_items(self):
        """(logical position, value) for every occupied leaf."""
        return [(p, self._tree[self.capacity + s])
                for s, p in enumerate(self._slot_pos) if p >= 0]

    def query(self, lo: int, hi: int) -> Any:
        """Combine leaves for logical positions [lo, hi).  The range must not
        exceed ``capacity`` (reference prefix/suffix query,
        ``flatfat.hpp:84-,:311-340``)."""
        if hi <= lo:
            return None
        if hi - lo > self.capacity:
            raise ValueError("query range exceeds FlatFAT capacity")
        plo = lo % self.capacity
        phi = (hi - 1) % self.capacity
        if plo <= phi:
            return self._range(plo, phi + 1)
        return self._comb2(self._range(plo, self.capacity),
                           self._range(0, phi + 1))

    def _range(self, lo: int, hi: int) -> Any:
        """Standard iterative segment-tree combine over physical [lo, hi)."""
        res_l = None
        res_r = None
        lo += self.capacity
        hi += self.capacity
        while lo < hi:
            if lo & 1:
                res_l = self._comb2(res_l, self._tree[lo])
                lo += 1
            if hi & 1:
                hi -= 1
                res_r = self._comb2(self._tree[hi], res_r)
            lo >>= 1
            hi >>= 1
        return self._comb2(res_l, res_r)
