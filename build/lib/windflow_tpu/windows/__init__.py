from windflow_tpu.windows.ops import (KeyedWindows, ParallelWindows,
                                      PanedWindows, MapReduceWindows,
                                      WindowResult)
from windflow_tpu.windows.flatfat import FlatFAT
from windflow_tpu.windows.ffat_op import FfatWindows
from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
