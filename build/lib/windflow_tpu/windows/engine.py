"""The window engine: per-key window bookkeeping shared by every window
operator.

Re-design of the reference's single window engine ``Window_Replica``
(``/root/reference/wf/window_replica.hpp:61-419``), which powers
Keyed/Parallel/Paned/MapReduce windows through per-key ``Key_Descriptor``
structs (archive, open windows, next lwid), an lwid→gwid mapping for
round-robin window assignment, incremental vs non-incremental user logic, a
lateness gate in DEFAULT mode, and EOS flushing.  The same roles exist here
(``basic.hpp:219``): SEQ, PLQ, WLQ, MAP, REDUCE.

Windows are defined over a *domain*: a monotone integer per tuple per key —
the per-key arrival index for count-based windows, the timestamp for
time-based ones, and an explicit id (pane gwid) for the WLQ stage of paned
windows.  Window ``w`` covers domain values ``[w*slide, w*slide + win_len)``.

Firing:
* count/id domains fire eagerly when the domain frontier passes a window's
  end (id-domain inputs are fed through an OrderingCollector, as the
  reference does for WLQ/REDUCE in every mode — ``multipipe.hpp:209-215``);
* time domains in DEFAULT mode are gated by the watermark plus the
  user-configured lateness (``window_replica.hpp:305``); tuples whose every
  window has already fired are counted as ignored (reference
  ``inputs_ignored``); in DETERMINISTIC/PROBABILISTIC modes inputs arrive
  (re)ordered, so time windows also fire eagerly from the domain frontier;
* EOS flushes every open window (``window_replica.hpp:356-408``).
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Any, Callable, Dict, List, Optional

from windflow_tpu.basic import ExecutionMode, WindowRole, WinType
from windflow_tpu.batch import WM_NONE


@dataclasses.dataclass
class WindowSpec:
    win_type: WinType          # CB (count) or TB (time, microseconds)
    win_len: int
    slide: int
    lateness: int = 0          # TB + DEFAULT mode only (usec)

    def first_window_of(self, d: int) -> int:
        # smallest w with w*slide + win_len > d
        return max(0, -(-(d - self.win_len + 1) // self.slide))

    def last_window_of(self, d: int) -> int:
        return d // self.slide

    def window_end(self, w: int) -> int:
        return w * self.slide + self.win_len


class Archive:
    """Ordered store of ``(domain, arrival_id, item, ts)`` entries for
    non-incremental window logic (reference ``StreamArchive``,
    ``stream_archive.hpp:48-146``).  The default keeps everything in memory;
    the persistent suite substitutes a spilling variant
    (windflow_tpu/persistent/p_windows.py) whose overflow lives in the KV
    store, mirroring the reference's RocksDB window fragments
    (``p_window_replica.hpp:90-176``)."""

    __slots__ = ("_entries",)

    def __init__(self) -> None:
        self._entries: List = []

    def insert(self, entry) -> None:
        if self._entries and self._entries[-1][:2] > entry[:2]:
            bisect.insort(self._entries, entry)
        else:
            self._entries.append(entry)

    def range(self, start: int, end: int) -> List:
        """Entries with ``start <= domain < end``, in (domain, aid) order."""
        lo = bisect.bisect_left(self._entries, (start, -1))
        hi = bisect.bisect_left(self._entries, (end, -1))
        return self._entries[lo:hi]

    def purge_below(self, d: int) -> None:
        lo = bisect.bisect_left(self._entries, (d, -1))
        if lo > 0:
            del self._entries[:lo]

    def clear(self) -> None:
        self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)


class _OpenWindow:
    __slots__ = ("gwid", "acc", "count", "max_ts")

    def __init__(self, gwid: int):
        self.gwid = gwid
        self.acc = None     # incremental accumulator
        self.count = 0      # tuples folded in
        self.max_ts = 0


class _KeyDescriptor:
    """Reference ``Key_Descriptor`` (``window_replica.hpp:84-105``)."""

    __slots__ = ("next_id", "archive", "open", "next_unfired", "frontier",
                 "fired_ahead")

    def __init__(self, archive: Archive):
        self.next_id = 0                    # per-key arrival counter
        self.archive = archive              # (domain, aid, item, ts) entries
        self.open: Dict[int, _OpenWindow] = {}
        self.next_unfired = 0               # lowest gwid not yet fired
        self.frontier = WM_NONE             # max domain value seen
        self.fired_ahead: set = set()       # gwids fired out of order


class WindowEngine:
    """One engine instance per window-operator replica.

    ``emit(key, gwid, ts, value)`` is called for every fired window."""

    def __init__(self, spec: WindowSpec, fn: Callable, incremental: bool,
                 role: WindowRole, parallelism: int, replica_index: int,
                 mode: ExecutionMode,
                 emit: Callable[[Any, int, int, Any], None],
                 domain_fn: Optional[Callable] = None,
                 wm_to_domain: Optional[Callable[[int], int]] = None,
                 count_complete: bool = False,
                 stats=None,
                 archive_factory: Callable[[Any], Archive] = None) -> None:
        self.spec = spec
        self.fn = fn
        self.incremental = incremental
        self.role = role
        self.parallelism = parallelism
        self.replica_index = replica_index
        self.mode = mode
        self.emit = emit
        self.domain_fn = domain_fn          # id-domain extractor (WLQ)
        # maps a time watermark into the id domain (WLQ over time panes:
        # pane p is complete once wm >= (p+1)*pane_len)
        self.wm_to_domain = wm_to_domain
        # fire a window the moment it holds win_len contributions (WLQ over
        # count panes, where pane results may arrive out of order across the
        # upstream pane replicas)
        self.count_complete = count_complete
        self.stats = stats
        self.archive_factory = archive_factory or (lambda key: Archive())
        self.keys: Dict[Any, _KeyDescriptor] = {}
        self._eager = ((spec.win_type == WinType.CB
                        or mode != ExecutionMode.DEFAULT)
                       and domain_fn is None) and not count_complete

    # -- ingestion -----------------------------------------------------------
    def on_tuple(self, key: Any, item: Any, ts: int, wm: int) -> None:
        kd = self.keys.get(key)
        if kd is None:
            kd = self.keys[key] = _KeyDescriptor(self.archive_factory(key))
        aid = kd.next_id
        kd.next_id += 1
        d = self._domain_of(aid, item, ts)
        hi = self.spec.last_window_of(d)
        if hi < kd.next_unfired:
            # every window this tuple belongs to has already fired
            if self.stats is not None:
                self.stats.inputs_ignored += 1
            return
        lo = max(self.spec.first_window_of(d), kd.next_unfired)
        kd.frontier = max(kd.frontier, d)
        if not self.incremental:
            # archive ordered by (domain, arrival id) — reference
            # StreamArchive binary-search insert (stream_archive.hpp:48-146)
            kd.archive.insert((d, aid, item, ts))
        keep = self._keeps_tuple(aid)
        for w in range(lo, hi + 1):
            if not self._owns_window(w) or w in kd.fired_ahead:
                continue
            ow = kd.open.get(w)
            if ow is None:
                ow = kd.open[w] = _OpenWindow(w)
            ow.max_ts = max(ow.max_ts, ts)
            if keep:
                if self.incremental:
                    ow.acc = self.fn(item, ow.acc)
                ow.count += 1
            if self.count_complete and ow.count >= self.spec.win_len:
                self._fire(key, kd, w)
        if self._eager:
            # A window is complete once the frontier reaches its end.  Count
            # domains are dense per key, so id w*slide+win_len-1 completes
            # the window (limit = frontier+1); time domains allow ties, so a
            # window only completes once a strictly-later timestamp arrives
            # (limit = frontier).
            bump = 1 if self.spec.win_type == WinType.CB else 0
            self._fire_upto(key, kd, kd.frontier + bump)

    def on_watermark(self, wm: int) -> None:
        if self._eager or self.count_complete or wm == WM_NONE:
            return
        limit = wm - self.spec.lateness
        if self.wm_to_domain is not None:
            limit = self.wm_to_domain(limit)
        # Fire across ALL keys in global window-end order, so the watermarks
        # stamped on emitted results (their result ts) are monotone per
        # output channel — an out-of-order emission would over-promise the
        # downstream watermark frontier and make downstream time windows fire
        # before sibling results arrive.
        ready = sorted(
            ((self.spec.window_end(w), key, w)
             for key, kd in self.keys.items() for w in kd.open
             if self.spec.window_end(w) <= limit))
        for _, key, w in ready:
            self._fire(key, self.keys[key], w)

    def on_eos(self) -> None:
        for key in list(self.keys):
            kd = self.keys[key]
            self._fire_upto(key, kd, None)
            kd.archive.clear()

    # -- internals -----------------------------------------------------------
    def _domain_of(self, aid: int, item: Any, ts: int) -> int:
        if self.domain_fn is not None:
            return self.domain_fn(item)
        if self.spec.win_type == WinType.CB:
            return aid
        return ts

    def _owns_window(self, gwid: int) -> bool:
        """Round-robin window assignment for parallel window stages
        (reference lwid→gwid arithmetic, ``window_replica.hpp:253-276``)."""
        if self.role in (WindowRole.PLQ, WindowRole.WLQ) \
                and self.parallelism > 1:
            return gwid % self.parallelism == self.replica_index
        return True

    def _keeps_tuple(self, aid: int) -> bool:
        """MAP-role partitioning: each replica folds only its share of every
        window's tuples (reference MAP discard rule,
        ``window_replica.hpp:286-288``)."""
        if self.role == WindowRole.MAP and self.parallelism > 1:
            return aid % self.parallelism == self.replica_index
        return True

    def _fire_upto(self, key: Any, kd: _KeyDescriptor,
                   limit: Optional[int]) -> None:
        """Fire open windows with end <= ``limit`` (None = EOS: fire all)."""
        ready = sorted(w for w in kd.open
                       if limit is None or self.spec.window_end(w) <= limit)
        for w in ready:
            self._fire(key, kd, w)

    def _fire(self, key: Any, kd: _KeyDescriptor, gwid: int) -> None:
        ow = kd.open.pop(gwid)
        start = gwid * self.spec.slide
        end = self.spec.window_end(gwid)
        if self.incremental:
            value = ow.acc
        else:
            items = [e[2] for e in kd.archive.range(start, end)
                     if self._keeps_tuple(e[1])]
            value = self.fn(items)
        # advance the fired frontier, tolerating out-of-order completions
        # (count-complete mode can finish window w+1 before w)
        kd.fired_ahead.add(gwid)
        while kd.next_unfired in kd.fired_ahead:
            kd.fired_ahead.discard(kd.next_unfired)
            kd.next_unfired += 1
        self._purge(kd)
        ts = end - 1 if (self.spec.win_type == WinType.TB
                         and self.domain_fn is None) else ow.max_ts
        self.emit(key, gwid, ts, value)

    def _purge(self, kd: _KeyDescriptor) -> None:
        """Drop archived tuples no longer covered by any unfired window
        (reference ``StreamArchive::purge``)."""
        if self.incremental or not len(kd.archive):
            return
        kd.archive.purge_below(kd.next_unfired * self.spec.slide)
