from windflow_tpu.ops.base import Operator, Replica
from windflow_tpu.ops.filter_op import Filter
from windflow_tpu.ops.flatmap_op import FlatMap, Shipper
from windflow_tpu.ops.map_op import Map
from windflow_tpu.ops.reduce_op import Reduce
from windflow_tpu.ops.sink import Sink
from windflow_tpu.ops.source import Source
from windflow_tpu.ops.tpu import FilterTPU, MapTPU, ReduceTPU
from windflow_tpu.ops.tpu_stateful import StatefulFilterTPU, StatefulMapTPU
