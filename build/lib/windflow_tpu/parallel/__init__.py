"""Routing + distribution plane: emitters, collectors, and multi-chip sharding.

This package is the TPU-native replacement for the reference's communication
backend (SURVEY.md §5.8): lock-free thread queues + pointer multicast become a
host driver moving batch handles between stages, and cross-chip distribution
rides XLA collectives over ICI (``windflow_tpu.parallel.mesh``).
"""

from windflow_tpu.parallel.emitters import (
    Emitter, ForwardEmitter, KeyByEmitter, BroadcastEmitter,
    DeviceStageEmitter, create_emitter,
)
from windflow_tpu.parallel.collectors import (
    Collector, WatermarkCollector, OrderingCollector, KSlackCollector,
    create_collector,
)
_MESH_EXPORTS = (
    "DATA_AXIS", "KEY_AXIS", "batch_sharding", "make_mesh",
    "make_sharded_ffat_state", "make_sharded_ffat_step",
    "make_sharded_keyed_reduce", "replicated", "stage_batch",
    "state_sharding",
)


def __getattr__(name):
    # Lazy (PEP 562): mesh pulls in the windows package, which depends on
    # ops.base, which imports this package — eager import would cycle.
    if name in _MESH_EXPORTS + ("mesh",):
        import windflow_tpu.parallel.mesh as _mesh
        return _mesh if name == "mesh" else getattr(_mesh, name)
    raise AttributeError(name)
