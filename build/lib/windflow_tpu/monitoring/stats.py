"""Per-replica statistics (reference ``/root/reference/wf/stats_record.hpp:47-165``).

The reference records inputs/outputs/bytes and service times per replica, plus
GPU kernel-launch counts and H2D/D2H byte counts for device replicas
(``stats_record.hpp:80-82,152-160``).  The TPU equivalents map one-to-one:
compiled-program dispatches for kernel launches, stage/fetch bytes for the
transfer counters.
"""

from __future__ import annotations

import dataclasses
import time

from windflow_tpu.basic import current_time_usecs


@dataclasses.dataclass
class StatsRecord:
    operator_name: str = ""
    replica_index: int = 0
    is_tpu: bool = False
    start_time_usec: int = dataclasses.field(default_factory=current_time_usecs)
    inputs_received: int = 0
    inputs_ignored: int = 0   # e.g. late tuples at window operators
    outputs_sent: int = 0
    # Service-time accounting (reference startStatsRecording/endStatsRecording,
    # basic_operator.hpp:133-158).
    service_time_usec: float = 0.0
    num_service_samples: int = 0
    # Device-side counters (reference GPU extensions of Stats_Record).
    device_programs_launched: int = 0
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    _t0: float = 0.0

    def start_sample(self) -> None:
        self._t0 = time.perf_counter()

    def end_sample(self) -> None:
        self.service_time_usec += (time.perf_counter() - self._t0) * 1e6
        self.num_service_samples += 1

    def avg_service_time_usec(self) -> float:
        if self.num_service_samples == 0:
            return 0.0
        return self.service_time_usec / self.num_service_samples

    def to_json(self) -> dict:
        """Schema kept close to the reference's per-replica JSON dump
        (``basic_operator.hpp:292-317``) for dashboard compatibility."""
        return {
            "Replica_id": self.replica_index,
            "Starting_time_usec": self.start_time_usec,
            "Inputs_received": self.inputs_received,
            "Inputs_ignored": self.inputs_ignored,
            "Outputs_sent": self.outputs_sent,
            "Service_time_usec": round(self.avg_service_time_usec(), 3),
            "Is_terminated": True,
            "Device_programs_launched": self.device_programs_launched,
            "Bytes_H2D": self.h2d_bytes,
            "Bytes_D2H": self.d2h_bytes,
        }
