"""User-function signature introspection.

The reference deduces tuple/result/state/key types and riched-ness from C++
functor signatures with heavy template metaprogramming
(``/root/reference/wf/meta.hpp:84-256``).  In Python the same job is a
``inspect.signature`` arity check: a user function is "riched" when it accepts
a trailing ``RuntimeContext`` parameter.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable


def _positional_arity(fn: Callable) -> int:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return -1  # builtins / C callables: assume non-riched
    n = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            # Only *required* positionals count: a defaulted trailing param is
            # a closure helper, not a RuntimeContext slot.
            if p.default is inspect.Parameter.empty:
                n += 1
        elif p.kind == p.VAR_POSITIONAL:
            return -1
    return n


def is_riched(fn: Callable, base_arity: int) -> bool:
    """True when ``fn`` takes ``base_arity + 1`` positional args, the extra one
    being the RuntimeContext (reference meta.hpp riched variants)."""
    n = _positional_arity(fn)
    if n < 0:
        return False
    return n == base_arity + 1


def adapt(fn: Callable, base_arity: int) -> Callable:
    """Normalize a possibly-riched user function to always accept
    ``(*args, context)``: non-riched functions get the context swallowed."""
    if is_riched(fn, base_arity):
        return fn

    @functools.wraps(fn)
    def wrapper(*args):
        return fn(*args[:-1])

    return wrapper
