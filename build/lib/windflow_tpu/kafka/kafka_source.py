"""Kafka_Source operator (reference ``/root/reference/wf/kafka/
kafka_source.hpp:127,355``).

Each replica owns one consumer joined to the operator's consumer group, so
topic partitions spread across replicas and rebalance when replicas come
and go — exactly the reference's per-replica ``KafkaConsumer`` with the
cooperative rebalance callback (``kafka_source.hpp:57-123``).

The user deserializer runs per consumed message:
``fn(msg: KafkaMessage | None, shipper[, kafka_ctx]) -> bool | None`` —
``None`` msg means the consumer has been idle for ``idle_time_usec``
(reference ``consume(idleTime)`` timeout path); returning ``False`` stops
this replica (its EOS then flows through the graph).  Any other return
continues.  The shipper mirrors ``Source_Shipper``: ``push`` (ingress
timestamping) and ``pushWithTimestamp`` (event time).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from windflow_tpu.basic import WindFlowError, current_time_usecs
from windflow_tpu.batch import WM_NONE
from windflow_tpu.kafka.client import make_consumer
from windflow_tpu.kafka.kafka_context import KafkaRuntimeContext
from windflow_tpu.meta import adapt
from windflow_tpu.ops.source import Source, SourceReplica


class KafkaShipper:
    """Push interface handed to the deserializer (reference
    ``Source_Shipper``, ``source_shipper.hpp:59-``)."""

    __slots__ = ("_replica",)

    def __init__(self, replica: "KafkaSourceReplica") -> None:
        self._replica = replica

    def push(self, item: Any) -> None:
        r = self._replica
        ts = current_time_usecs()
        if ts <= r._last_ts:
            ts = r._last_ts + 1
        self.pushWithTimestamp(item, ts)

    def pushWithTimestamp(self, item: Any, ts: int) -> None:
        r = self._replica
        r._last_ts = max(r._last_ts, int(ts))
        r._advance_wm(r._last_ts)
        r.stats.outputs_sent += 1
        r.emitter.emit(item, int(ts), r.current_wm)
        r._count_toward_punctuation(1)


class KafkaSourceReplica(SourceReplica):
    def __init__(self, op: "KafkaSource", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.deser_fn, 2)
        self._shipper = KafkaShipper(self)
        self._consumer = None
        self._last_activity = 0

    def start(self) -> None:
        self._consumer = make_consumer(self.op.brokers)
        self._consumer.subscribe(self.op.topics, self.op.group_id,
                                 self.op.offsets)
        # riched deserializers see a KafkaRuntimeContext (reference passes
        # KafkaRuntimeContext instead of RuntimeContext, kafka_source.hpp:134)
        self.context = KafkaRuntimeContext(
            self.op.parallelism, self.index, self.op.name,
            consumer=self._consumer)
        self._last_activity = current_time_usecs()

    def tick(self, max_items: int) -> bool:
        if self._exhausted:
            return False
        msgs = self._consumer.poll(max_items)
        run = True
        if msgs:
            self._last_activity = current_time_usecs()
            for msg in msgs:
                ret = self._fn(msg, self._shipper, self.context)
                self.stats.inputs_received += 1
                if ret is False:
                    run = False
                    break
        else:
            now = current_time_usecs()
            if now - self._last_activity >= self.op.idle_time_usec:
                self._last_activity = now
                ret = self._fn(None, self._shipper, self.context)
                if ret is False:
                    run = False
        if not run:
            self._exhausted = True
            self._consumer.close()
            self._terminate()
            return True  # termination (EOS cascade) is progress
        return True


class KafkaSource(Source):
    replica_class = KafkaSourceReplica

    def __init__(self, deser_fn: Callable, brokers, topics: Sequence[str],
                 group_id: str = "windflow",
                 offsets: Optional[Sequence[int]] = None,
                 idle_time_usec: int = 100_000,
                 name: str = "kafka_source", parallelism: int = 1,
                 output_batch_size: int = 0) -> None:
        if not topics:
            raise WindFlowError("Kafka_Source needs at least one topic")
        # bypass Source.__init__'s generator plumbing; Operator init only
        super().__init__(gen_fn=lambda: iter(()), name=name,
                         parallelism=parallelism,
                         output_batch_size=output_batch_size)
        self.deser_fn = deser_fn
        self.brokers = brokers
        self.topics = list(topics)
        self.group_id = group_id
        self.offsets = list(offsets) if offsets is not None else None
        self.idle_time_usec = idle_time_usec
