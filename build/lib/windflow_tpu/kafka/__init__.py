"""Kafka integration (reference ``/root/reference/wf/kafka/`` — SURVEY.md
§2.7): Kafka_Source / Kafka_Sink operators, KafkaRuntimeContext, fluent
builders, and a client layer with an in-process broker for tests plus a
gated adapter for real clusters."""

from windflow_tpu.kafka.builders_kafka import (KafkaSink_Builder,
                                               KafkaSource_Builder)
from windflow_tpu.kafka.client import (ConsumerClient, InMemoryBroker,
                                       KafkaMessage, ProducerClient)
from windflow_tpu.kafka.kafka_context import KafkaRuntimeContext
from windflow_tpu.kafka.kafka_sink import KafkaSink, KafkaSinkMessage
from windflow_tpu.kafka.kafka_source import KafkaSource
