"""KafkaRuntimeContext (reference ``/root/reference/wf/kafka/
kafka_context.hpp:58``): the plain RuntimeContext plus access to the
replica's Kafka client, so riched deserializers/serializers can commit,
inspect assignment, or produce side-channel messages."""

from __future__ import annotations

from typing import Optional

from windflow_tpu.context import RuntimeContext
from windflow_tpu.kafka.client import ConsumerClient, ProducerClient


class KafkaRuntimeContext(RuntimeContext):
    def __init__(self, parallelism: int, replica_index: int,
                 operator_name: str = "",
                 consumer: Optional[ConsumerClient] = None,
                 producer: Optional[ProducerClient] = None) -> None:
        super().__init__(parallelism, replica_index, operator_name)
        self._consumer = consumer
        self._producer = producer

    @property
    def consumer(self) -> Optional[ConsumerClient]:
        return self._consumer

    @property
    def producer(self) -> Optional[ProducerClient]:
        return self._producer
