"""WordCount: the canonical streaming benchmark application (used by the
reference's evaluation papers, DSPBench suite).

``Source(lines) → FlatMap(split) → keyed Reduce(count) → Sink`` — exercises
FlatMap shipping, KEYBY routing and rolling keyed state.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional

import windflow_tpu as wf


def build(lines: Iterable[str],
          on_count: Optional[Callable[[str, int], None]] = None,
          source_parallelism: int = 1,
          splitter_parallelism: int = 1,
          counter_parallelism: int = 2,
          batch: int = 0) -> wf.PipeGraph:
    """Build the WordCount graph.  ``on_count(word, count)`` observes every
    updated (word, count) pair leaving the counter."""

    def split(line, shipper):
        for w in line.split():
            shipper.push(w.lower())

    def count(word, state):
        state["word"] = word
        state["n"] = state.get("n", 0) + 1

    def emit(state, ctx=None):
        if state is not None and on_count is not None:
            on_count(state["word"], state["n"])

    src = (wf.Source_Builder(lambda: iter(lines)).withName("line_source")
           .withParallelism(source_parallelism)
           .withOutputBatchSize(batch).build())
    splitter = (wf.FlatMap_Builder(split).withName("splitter")
                .withParallelism(splitter_parallelism)
                .withOutputBatchSize(batch).build())
    counter = (wf.Reduce_Builder(count, dict).withName("counter")
               .withParallelism(counter_parallelism)
               .withKeyBy(lambda w: w).build())
    sink = wf.Sink_Builder(emit).withName("count_sink").build()

    g = wf.PipeGraph("wordcount", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(splitter).add(counter).add_sink(sink)
    return g


def run(lines: Iterable[str], **kwargs) -> Dict[str, int]:
    """Run WordCount to completion; returns the final word→count table."""
    counts: Dict[str, int] = {}
    g = build(lines, on_count=lambda w, n: counts.__setitem__(w, n), **kwargs)
    g.run()
    return counts
