"""Persistent operator suite: keyed state in an embedded, durable KV store.

TPU-native re-design of the reference's RocksDB integration
(``/root/reference/wf/persistent/`` — see SURVEY.md §2.7): the store itself
is the native log-structured ``wf_kv`` (native/wf_kv.cpp) instead of
RocksDB, the operators do the same per-input keyed read-modify-write, and
persistent keyed windows spill archive fragments to the store so window
state can exceed RAM.
"""

from windflow_tpu.persistent.builders import (P_Filter_Builder,
                                              P_FlatMap_Builder,
                                              P_Keyed_Windows_Builder,
                                              P_Map_Builder,
                                              P_Reduce_Builder,
                                              P_Sink_Builder)
from windflow_tpu.persistent.db_handle import DBHandle
from windflow_tpu.persistent.kv import LogKV
from windflow_tpu.persistent.ops import (PFilter, PFlatMap, PMap, PReduce,
                                         PSink)
from windflow_tpu.persistent.p_windows import PKeyedWindows, SpillingArchive
