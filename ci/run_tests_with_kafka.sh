#!/usr/bin/env bash
# CI entry point WITH a live single-node Kafka broker (KRaft, no
# ZooKeeper): starts the broker, waits for it to answer, then runs the
# whole suite — tests/test_kafka_live.py stops skipping and exercises the
# real-client adapters (kafka/client.py "VALIDATION STATUS" items).
# Used as the CMD of dockerimages/Dockerfile_cpu; also runnable on any
# host with /opt/kafka + confluent_kafka installed.
set -euo pipefail
cd "$(dirname "$0")/.."

KAFKA_HOME=${KAFKA_HOME:-/opt/kafka}
export KAFKA_BOOTSTRAP=${KAFKA_BOOTSTRAP:-localhost:9092}
LOG_DIR=$(mktemp -d /tmp/wf-kraft-XXXX)

if [ -x "$KAFKA_HOME/bin/kafka-storage.sh" ]; then
    export KAFKA_HEAP_OPTS="-Xmx256m -Xms128m"
    CLUSTER_ID=$("$KAFKA_HOME/bin/kafka-storage.sh" random-uuid)
    cat > "$LOG_DIR/server.properties" <<EOF
process.roles=broker,controller
node.id=1
controller.quorum.voters=1@localhost:9093
listeners=PLAINTEXT://localhost:9092,CONTROLLER://localhost:9093
advertised.listeners=PLAINTEXT://localhost:9092
controller.listener.names=CONTROLLER
inter.broker.listener.name=PLAINTEXT
log.dirs=$LOG_DIR/data
num.partitions=2
offsets.topic.replication.factor=1
transaction.state.log.replication.factor=1
transaction.state.log.min.isr=1
group.initial.rebalance.delay.ms=0
EOF
    "$KAFKA_HOME/bin/kafka-storage.sh" format -t "$CLUSTER_ID" \
        -c "$LOG_DIR/server.properties"
    "$KAFKA_HOME/bin/kafka-server-start.sh" "$LOG_DIR/server.properties" \
        > "$LOG_DIR/broker.log" 2>&1 &
    BROKER_PID=$!
    trap 'kill $BROKER_PID 2>/dev/null || true' EXIT
    # wait for the broker to answer metadata requests; if it never does,
    # FAIL — this script's whole purpose is to stop the live tests from
    # skipping, and a green run with silently-skipped coverage is worse
    # than a red one
    up=0
    for i in $(seq 1 60); do
        if "$KAFKA_HOME/bin/kafka-topics.sh" --bootstrap-server \
                "$KAFKA_BOOTSTRAP" --list >/dev/null 2>&1; then
            echo "broker up after ${i}s"
            up=1
            break
        fi
        sleep 1
    done
    if [ "$up" != 1 ]; then
        echo "ERROR: KRaft broker never became ready; tail of log:"
        tail -50 "$LOG_DIR/broker.log" || true
        exit 1
    fi
else
    echo "WARNING: no Kafka at $KAFKA_HOME — live tests will skip"
fi

ci/run_tests.sh
