#!/usr/bin/env bash
# CI entry point: run the whole suite on the CPU backend (the conftest pins
# JAX to CPU and forces an 8-device virtual mesh so every multi-chip
# sharding path compiles and executes without TPU hardware), then the
# multi-chip dry run and a bench smoke on CPU.
set -euo pipefail
cd "$(dirname "$0")/.."

# static analysis first: wf_lint is pure AST (~1s, no jax import) and
# fails on any hot-path/except/lock-discipline violation before anything
# expensive runs
python tools/wf_lint.py

# wfverify stage (object-level, imports jax + the graphs): every kernel
# the repo ships — the bench e2e pipeline and one graph per chaos
# family — must verify clean under --strict (zero unsuppressed
# trace-safety/recompile/donation/determinism findings) before the test
# legs spend minutes.  The deliberately-violating determinism family
# (chaos "wallclock") is excluded by design: tests/test_tracecheck.py
# asserts it IS flagged.
python tools/wf_verify.py --strict \
    tools.verify_targets:bench_e2e \
    tools.verify_targets:wire_ingest \
    tools.verify_targets:pallas_window \
    tools.verify_targets:megastep_latency \
    tools.verify_targets:chaos_window_cb \
    tools.verify_targets:chaos_window_tb \
    tools.verify_targets:chaos_reduce \
    tools.verify_targets:chaos_stateful \
    tools.verify_targets:chaos_stateless_chain

# wfir stage (IR-level, runs the graphs): --drive feeds a seeded
# synthetic stream into every composed-only target and audits the
# lowered StableHLO of EVERY program the runs compile — collectives on
# promised-collective-free edges, host callbacks, 64-bit survivors,
# dynamic shapes, donation misses, D2H syncs, lost Mosaic custom calls
# (WF901-WF907) — plus an orphan sweep over the framework's own staging
# programs.  Zero extra compiles: the audit parses the compile
# watcher's existing first-compile lowering.
python tools/wf_ir.py --strict --drive 8192 \
    tools.verify_targets:bench_e2e \
    tools.verify_targets:wire_ingest \
    tools.verify_targets:pallas_window \
    tools.verify_targets:megastep_latency \
    tools.verify_targets:chaos_window_cb \
    tools.verify_targets:chaos_window_tb \
    tools.verify_targets:chaos_reduce \
    tools.verify_targets:chaos_stateful \
    tools.verify_targets:chaos_stateless_chain

# fast tier-1 gate: the staging-plane contracts (pool reuse, fused
# transfer round-trip, prefetch ordering), the observability contracts
# (histogram percentile math, trace-export schema, recorder-off zero-cost,
# the <2% overhead budget), the analysis contracts (preflight diagnostic
# codes, wf_lint fixtures, debug-mode race detector), the device-plane
# contracts (compile watcher, OpenMetrics exposition, HBM-gauge CPU
# guard), the shard-plane contracts (seeded Zipf-skew attribution,
# sketch accuracy bound, dispatch neutrality of the in-program sketch,
# reshard plan, kill-switch off-path budget),
# the health-plane contracts (watchdog state machine, stall
# attribution, postmortem/wf_doctor round trip, crash-path END_APP),
# the key-compaction contracts (record-for-record compacted vs sorted
# vs declared-dense A/B, overflow-to-sorted under adversarial streams,
# zero-extra-dispatch pin, churn/hit-rate surfacing, remap chaos
# restore), the pallas-kernel contracts (kernel-vs-lax record A/B
# across window/reduce families incl. regrow + EOS edges, bit-equality
# of the kernel bodies, zero-dispatch-delta pin, WF607, aligned-ingest
# extension, kill-switch off-path), the megastep contracts
# (record-for-record K=1 vs K>1 A/B across operator families, the
# 1-program-per-K-sweeps dispatch pin, WF608 downgrade preflight,
# per-batch trace-lane honesty, megastep-aligned durability epochs),
# and the durability contracts (one chaos kill->restore->record-diff cell
# per mechanism, checkpoint store layout/GC, WF602 restore validation,
# sink EOS fence, off-path budget — the full family x kill point x
# fusion soak matrix is slow-marked for the nightly leg) fail
# in seconds, before the full suite spends minutes.  The full-suite run
# below repeats them — accepted: the gate's job is fast failure.  The
# full suite deselects `slow` like the tier-1 gate does (same filter =
# comparable pass counts, and the ~3min of slow-marked soak/two-process/
# fuzz-tail tests stay inside the gate's timeout budget); run them
# explicitly with `pytest -m slow` on the nightly leg.
python -m pytest tests/test_staging.py tests/test_observability.py \
    tests/test_analysis.py tests/test_device_metrics.py \
    tests/test_health.py tests/test_sweep_ledger.py \
    tests/test_fusion.py tests/test_durability.py \
    tests/test_shard_plane.py tests/test_tracecheck.py \
    tests/test_key_compaction.py tests/test_reshard.py \
    tests/test_wire.py tests/test_pallas_kernels.py \
    tests/test_megastep.py tests/test_latency_plane.py \
    tests/test_ir_audit.py tests/test_tenant_plane.py \
    tests/test_calibration.py -q -m 'not slow'
python -m pytest tests/ -q -m 'not slow'
python __graft_entry__.py 8
BENCH_PLATFORM=cpu BENCH_E2E_TUPLES=131072 python bench.py | tee bench_ci_out.txt
# the e2e decomposition keys (ratio_vs_kernel, staging_share_of_staged_run)
# are the staging plane's evidence trail — fail if a bench refactor drops them
python tools/check_bench_keys.py bench_ci_out.txt
rm -f bench_ci_out.txt
# run-over-run perf tripwire on the guarded bench_history.json scalars:
# >10% regression vs the previous same-methodology run fails under CI=1
# (warns locally); the bench leg above just appended the run under
# judgment
CI="${CI:-1}" python tools/check_bench_regress.py
# calibration gate: probe the CI backend, then verify the written store
# is fresh + valid for THIS device kind (exit 1 = stale/corrupt/missing,
# exit 2 = kill switch set — CI must never silently run uncalibrated
# while claiming otherwise).  The store is CI-local scratch, not an
# artifact: production stores come from `wf_calibrate` on real chips.
python tools/wf_calibrate.py --out /tmp/wf_ci_calibration.json
python tools/wf_calibrate.py --check /tmp/wf_ci_calibration.json
rm -f /tmp/wf_ci_calibration.json
# host worker-pool smoke (reduced size; reports pool overhead on 1 core)
BENCH_HOST_TUPLES=4000 BENCH_HOST_VEC=2048 BENCH_HOST_REPS=1 python bench_host.py
# nightly leg (CI_NIGHTLY=1): the slow-marked tail — the RSS soaks, the
# two-OS-process DCN validation, the 100k ordering-perf pair, the
# heaviest fuzz seeds and spec-sweep cells, the grouping/bench-chain/
# sketch-overhead heavies (wfverify-round headroom pass), the chaos
# soak matrix, and the xplane-serialize profile capture — runs here so
# deselecting `slow` above never leaves them uncovered
if [ "${CI_NIGHTLY:-0}" != "0" ]; then
    python -m pytest tests/ -q -m slow
fi
