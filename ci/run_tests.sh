#!/usr/bin/env bash
# CI entry point: run the whole suite on the CPU backend (the conftest pins
# JAX to CPU and forces an 8-device virtual mesh so every multi-chip
# sharding path compiles and executes without TPU hardware), then the
# multi-chip dry run and a bench smoke on CPU.
set -euo pipefail
cd "$(dirname "$0")/.."

# static analysis first: wf_lint is pure AST (~1s, no jax import) and
# fails on any hot-path/except/lock-discipline violation before anything
# expensive runs
python tools/wf_lint.py

# fast tier-1 gate: the staging-plane contracts (pool reuse, fused
# transfer round-trip, prefetch ordering), the observability contracts
# (histogram percentile math, trace-export schema, recorder-off zero-cost,
# the <2% overhead budget), the analysis contracts (preflight diagnostic
# codes, wf_lint fixtures, debug-mode race detector), and the
# device-plane contracts (compile watcher, OpenMetrics exposition,
# HBM-gauge CPU guard) fail in seconds, before the full suite spends
# minutes.  The full-suite run below repeats them — accepted: the gate's
# job is fast failure, and keeping the full suite unfiltered means its
# pass count stays comparable with the tier-1 gate's.
python -m pytest tests/test_staging.py tests/test_observability.py \
    tests/test_analysis.py tests/test_device_metrics.py -q -m 'not slow'
python -m pytest tests/ -q
python __graft_entry__.py 8
BENCH_PLATFORM=cpu BENCH_E2E_TUPLES=131072 python bench.py | tee bench_ci_out.txt
# the e2e decomposition keys (ratio_vs_kernel, staging_share_of_staged_run)
# are the staging plane's evidence trail — fail if a bench refactor drops them
python tools/check_bench_keys.py bench_ci_out.txt
rm -f bench_ci_out.txt
# host worker-pool smoke (reduced size; reports pool overhead on 1 core)
BENCH_HOST_TUPLES=4000 BENCH_HOST_VEC=2048 BENCH_HOST_REPS=1 python bench_host.py
