"""Host-multicore scaling benchmark (VERDICT r3 item 3).

A host-only pipeline — Source -> keyed FlatMap -> KeyedWindows -> Sink —
whose per-tuple work is numpy (GIL-releasing), run at parallelism 1 on the
single cooperative driver loop vs parallelism 4 on a 4-thread host worker
pool (``Config.host_worker_threads`` — the TPU-native stand-in for the
reference's thread-per-replica FastFlow runtime, ``basic_operator.hpp:54``).

Prints ONE JSON line:
  {"metric": "host_pipeline_speedup_p4", "value": <p4_tps / p1_tps>, ...}

Representative workload: vector telemetry — each tuple carries a float32
lane block (8k values); the FlatMap normalizes it, the window accumulates a
per-key running sum over a sliding count window.  Pure-Python per-tuple
functions would be GIL-bound in any CPython pool; numpy/native inner loops
are exactly the host work this framework leaves on the CPU (parsers,
serializers, window folds over arrays).
"""

import json
import os
import statistics
import time

import numpy as np

import windflow_tpu as wf

N_TUPLES = int(os.environ.get("BENCH_HOST_TUPLES", 24_000))
N_KEYS = 32
VEC = int(os.environ.get("BENCH_HOST_VEC", 8192))
WIN, SLIDE = 16, 8
REPS = int(os.environ.get("BENCH_HOST_REPS", 3))


def _base_blocks():
    rng = np.random.default_rng(0)
    return [rng.random(VEC, dtype=np.float32) for _ in range(256)]


def run_once(par: int, workers: int, blocks) -> float:
    def gen():
        for i in range(N_TUPLES):
            yield {"k": i % N_KEYS, "v": blocks[i % len(blocks)]}

    def normalize(t, shipper):
        v = t["v"]
        out = np.sqrt(v * np.float32(1.0001) + np.float32(0.5))
        shipper.push({"k": t["k"], "v": out})

    def fold(t, acc):
        v = t["v"]
        return v.copy() if acc is None else acc + v

    done = []

    def sink(r):
        if r is not None:
            done.append(None)

    cfg = wf.Config(host_worker_threads=workers)
    g = wf.PipeGraph(f"host_bench_p{par}", wf.ExecutionMode.DEFAULT,
                     config=cfg)
    src = wf.Source_Builder(gen).withOutputBatchSize(64).build()
    fm = (wf.FlatMap_Builder(normalize).withKeyBy(lambda t: t["k"])
          .withParallelism(par).build())
    kw = (wf.Keyed_Windows_Builder(fold).withCBWindows(WIN, SLIDE)
          .withKeyBy(lambda t: t["k"]).withParallelism(par).build())
    snk = wf.Sink_Builder(sink).build()
    g.add_source(src).add(fm).add(kw).add_sink(snk)
    t0 = time.perf_counter()
    g.run()
    dt = time.perf_counter() - t0
    assert len(done) > 0
    return N_TUPLES / dt


def main():
    cores = (len(os.sched_getaffinity(0))
             if hasattr(os, "sched_getaffinity") else os.cpu_count() or 1)
    blocks = _base_blocks()
    run_once(1, 0, blocks)  # warm caches/allocator once
    p1 = statistics.median(run_once(1, 0, blocks) for _ in range(REPS))
    p4 = statistics.median(run_once(4, 4, blocks) for _ in range(REPS))
    out = {
        "metric": "host_pipeline_speedup_p4",
        "value": round(p4 / p1, 3),
        "unit": "x (throughput p=4+pool vs p=1)",
        "p1_tuples_per_sec": round(p1),
        "p4_tuples_per_sec": round(p4),
        "cpu_cores": cores,
        "workload": f"{N_TUPLES} tuples x float32[{VEC}], "
                    f"{N_KEYS} keys, CB {WIN}/{SLIDE}",
        "reps": REPS,
    }
    if cores == 1:
        # Thread scaling is physically impossible on one core; what this
        # number then proves is the POOL OVERHEAD bound — parallel drains,
        # lock-guarded counters and per-sweep submits must stay cheap.
        # Run on a multicore host for the speedup measurement.
        out["note"] = ("single-core environment: value is the pool-overhead "
                       "ratio (1.0 = free), not a scaling measurement")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
