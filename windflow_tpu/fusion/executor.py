"""Fusion executor: lower whole operator chains into one XLA dispatch.

PR 6's sweep ledger attributed the roofline's ~8x bytes/tuple excess to
per-hop HBM round-trips, and the fusion advisor
(``analysis/fusion.plan``) named the chains one program could replace.
This module is the executor that plan is the contract for: at
``PipeGraph._build`` every executable chain — a run of stateless TPU
stages (map / filter / chained pairs) optionally ending in a window
lift/combine, keyed reduce, or dense-key stateful tail — is routed as
ONE hop whose program threads payload/valid/ts/keys/state end to end
with no hop-boundary materialization, generalizing ``ops/chained.py``
from pairwise map/filter specs to arbitrary chains with stateful tails
(the ``whole_chain`` link kind the advisor records, single-replica
KEYBY relays included: key extraction already runs inside the compiled
program, so the relay edge simply disappears).

Mechanism (three cooperating pieces):

* **Prelude** — :func:`build_prelude` folds the stateless members'
  record transforms into one traced ``(payload, valid) -> (payload,
  valid)`` body.  Stateful tails inline it at program-build time
  (``windows/ffat_tpu._build_step``, ``ops/tpu.ReduceTPU._get_step`` /
  ``_get_dense_step``, ``ops/tpu_stateful._get_step`` consult
  ``op._fused_prelude``), so the tail's existing host machinery — TB
  ring regrow/rebase, EOS flush, overflow policy, donation of the state
  buffers — keeps working unchanged with the prelude fused in.
* **Stateless host** — an all-stateless chain has no tail program to
  extend; :class:`FusedStatelessExec` compiles the combined spec run
  (plus in-program key extraction for a downstream KEYBY consumer) and
  the last member's replicas dispatch it via the
  ``_TPUReplica._op_step`` hook (one attribute check per batch).
* **Graph rewiring** — ``PipeGraph._build`` wires edges INTO a fused
  segment's head to the segment host instead (keeping the head edge's
  routing contract), skips the interior edges entirely, and marks the
  member replicas inert.  Member operators stay in ``_operators``:
  preflight (which runs pre-build), the health watchdog, gauges, and
  ``stats()`` keep their shapes, with member numbers attributed from
  the fused hop by :func:`attribute_member_stats`.

Safety gates: fusion is skipped on a mesh (the sharded program
factories compose differently), for host-interning stateful tails (the
key intern needs a host round-trip mid-chain), and input-buffer
donation is only enabled when every producer of the head's batches is a
staging edge or a FORWARD DeviceSource — the only cases where the
arrays are provably unshared (split/broadcast/keyby device edges alias
one payload across destinations).

``Config.whole_chain_fusion`` / ``WF_TPU_FUSE=0`` is the kill switch;
tier-1 exercises both paths on CPU (tests/test_fusion.py A/B families).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from windflow_tpu.basic import RoutingMode
from windflow_tpu.batch import DeviceBatch
from windflow_tpu.monitoring.jit_registry import wf_jit


def fused_name(members) -> str:
    """Display/program name of a fused segment — the chained-pair
    ``a|b`` convention (ops/chained.fuse) extended to the whole run."""
    return "|".join(op.name for op in members)


def _is_stateless(op) -> bool:
    from windflow_tpu.ops.chained import ChainedTPU
    from windflow_tpu.ops.tpu import FilterTPU, MapTPU
    return isinstance(op, (MapTPU, FilterTPU, ChainedTPU))


def _tail_supported(op) -> bool:
    """Stateful chain tails the executor can extend with a prelude.
    Host-interning stateful ops are excluded: their key intern reads
    distinct keys back to host BEFORE the step, which would need the
    prelude's output mid-chain — a second dispatch, defeating fusion."""
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    if isinstance(op, FfatWindowsTPU):
        # compacted key spaces (withCompactedKeys, max_keys None) stay
        # un-fused: their remap admits keys at the HOST staging boundary
        # (parallel/compaction.py), and a prelude would move key
        # extraction behind the chain where no host admission path can
        # see it — a pinned table that never fills.  Compacted REDUCE
        # tails fuse fine: their cold tail is the in-program sorted
        # lane, so a slow-to-seed table costs speed, never records.
        return op.max_keys is not None
    if isinstance(op, ReduceTPU):
        return True
    if isinstance(op, _StatefulTPUBase):
        return bool(op.dense_keys)
    return False


def build_prelude(members):
    """One traced ``(payload, valid) -> (payload, valid)`` body applying
    every stateless member's record transform in chain order — the
    generalization of ``ChainedTPU``'s spec loop that stateful tails
    inline ahead of their own step.  Returns ``(prelude, has_filter)``.

    Wire-compressed staging (windflow_tpu/wire.py) composes AHEAD of
    this prelude at zero dispatch cost: ``batch.stage_packed`` inlines
    the traced ``wire.build_wire_decode`` stage into the unpack program
    the staged path already dispatches, so by the time a fused segment's
    program (prelude + tail) sees the batch, its lanes are decoded —
    the per-batch dispatch sequence stays exactly ``unpack → fused
    program``, compressed or not (pinned by tests/test_wire.py).

    Pallas kernels (windflow_tpu/kernels) compose BEHIND it the same
    way: the tail builders that inline this prelude
    (``ffat_tpu._build_step``, ``ReduceTPU._get_dense_step`` /
    ``_get_compacted_step``) resolve ``Config.pallas_kernels`` at
    program-build time, so a fused chain's single program carries
    prelude + Pallas kernel bodies + tail state machine in ONE
    dispatch — the kill switch (``WF_TPU_PALLAS=0``) swaps the kernel
    regions back to lax without touching the fusion plan (pinned by
    tests/test_pallas_kernels.py's zero-dispatch-delta test)."""
    from windflow_tpu.ops.chained import _tpu_specs
    specs = []
    for op in members:
        specs.extend(_tpu_specs(op))
    has_filter = any(kind == "filter" for kind, _ in specs)

    def prelude(payload, valid):
        for kind, fn in specs:
            if kind == "map":
                payload = jax.vmap(fn)(payload)
            elif kind == "batch_map":
                payload = fn(payload, valid)
            else:
                valid = valid & jax.vmap(fn)(payload)
        return payload, valid

    return prelude, has_filter


def prelude_out_spec(prelude: Callable, payload, valid):
    """Abstract post-prelude payload (``jax.eval_shape`` — zero device
    work): what the tail's record-structure checks and state layouts
    must be sized against when a prelude rewrites the records."""
    return jax.eval_shape(lambda p, v: prelude(p, v)[0], payload, valid)


def donation_aliases_cleanly(fn, *args) -> bool:
    """True when every input leaf of ``args`` finds a DISTINCT same-
    shape/dtype output leaf of ``fn(*args)`` — the condition under which
    ``donate_argnums`` elides whole-buffer copies instead of tripping
    XLA's "donated buffers were not usable" warning.  A chain whose map
    rewrites a field's dtype (int64 counter -> float value) leaves the
    old buffer unaliased, so donation is decided per program at the
    first batch (``jax.eval_shape`` — zero device work), not assumed."""
    try:
        out = jax.eval_shape(fn, *args)
    except Exception:  # lint: broad-except-ok (abstract eval of an
        # arbitrary user chain — ANY failure just means "don't donate";
        # the real dispatch will surface a genuine error on its own)
        return False
    pool: dict = {}
    for leaf in jax.tree_util.tree_leaves(out):
        sig = (tuple(leaf.shape), str(leaf.dtype))
        pool[sig] = pool.get(sig, 0) + 1
    for leaf in jax.tree_util.tree_leaves(args):
        sig = (tuple(getattr(leaf, "shape", ())),
               str(getattr(leaf, "dtype", None)))
        if pool.get(sig, 0) <= 0:
            return False
        pool[sig] -= 1
    return True


class FusedStatelessExec:
    """Executor for an all-stateless fused segment: ONE ``wf_jit``
    program for the member chain, installed on the LAST member (the
    segment host) and dispatched through ``_TPUReplica._op_step``.
    Mirrors ``ChainedTPU._step``'s batch contract — size is unknown
    after any fused filter, watermark/frontier/ts extrema relay — and
    adds the two whole-chain upgrades: in-program key extraction for a
    downstream KEYBY consumer (the keys lane rides the output batch so
    the consumer never re-extracts) and input-buffer donation when the
    graph proves the staged inputs unshared."""

    def __init__(self, name: str, members,
                 donate_inputs: bool = False) -> None:
        self.name = name
        self._prelude, self._has_filter = build_prelude(members)
        self._key_extractor: Optional[Callable] = None
        # donation is two-phase: the graph walk proves the inputs
        # UNSHARED at build (donate_inputs); whether they actually ALIAS
        # the chain's outputs is only knowable against the first batch's
        # concrete specs (donation_aliases_cleanly)
        self._donate_pending = donate_inputs
        self._donate = False
        # shard plane (monitoring/shard_ledger.py): when the ledger
        # attaches a sketch, the downstream key extraction this program
        # already performs also updates an on-device count-min/candidate
        # state threaded through as one donated operand — zero extra
        # dispatches; None leaves one check per batch in step()
        self._sketch = None
        self._sk_n = 1
        self._sk_state = None
        self._raw_step = None
        self._jit = None
        self._build()

    def set_downstream_key_extractor(self, key_fn: Callable) -> None:
        """Fuse the downstream KEYBY consumer's key extraction into the
        chain program: keys are computed on the chain's OUTPUT records
        (exactly what the consumer's own in-program extraction would
        see) and attached to the output batch's keys lane."""
        self._key_extractor = key_fn
        self._build()

    def enable_input_donation(self) -> None:
        """Arm the two-phase input donation (see ``__init__``): the
        caller proved the inputs unshared; the aliasing half is checked
        against the first batch.  ``PipeGraph._build`` calls this for
        unfused ``ChainedTPU`` hops, which share this machinery."""
        self._donate_pending = True

    def attach_shard_sketch(self, sketch, n_shards: int) -> None:
        """Fold the shard-plane sketch update into this chain program:
        the keys computed for the downstream KEYBY consumer feed the
        on-device count-min/candidate state inside the SAME dispatch.
        Called by the shard ledger at graph build (before any compile);
        ``n_shards`` is the consumer's replica count, so the sketch's
        per-shard counts use the exact splitmix placement the keyby
        routing applies downstream."""
        self._sketch = sketch
        self._sk_n = max(1, n_shards)
        sketch.register_device_state(lambda: self._sk_state)
        self._build()

    def _build(self) -> None:
        prelude = self._prelude
        kx = self._key_extractor
        sketched = self._sketch is not None and kx is not None
        n_sh = self._sk_n

        def raw(payload, valid):
            payload, valid = prelude(payload, valid)
            keys = (jax.vmap(kx)(payload).astype(jnp.int32)
                    if kx is not None else None)
            return payload, valid, keys

        # the donation aliasing probe always evaluates the sketch-free
        # two-arg form: the sketch state trivially aliases itself and
        # must not mask a payload lane that fails to alias
        self._raw_step = raw
        if sketched:
            from windflow_tpu.monitoring.shard_ledger import \
                device_sketch_update

            def step(payload, valid, sk):
                payload, valid, keys = raw(payload, valid)
                return payload, valid, keys, device_sketch_update(
                    sk, keys, valid, n_sh)

            donate = ((0, 1) if self._donate else ()) + (2,)
        else:
            step = raw
            donate = (0, 1) if self._donate else ()
        self._jit = wf_jit(step, op_name=self.name, donate_argnums=donate)

    def step(self, batch: DeviceBatch) -> DeviceBatch:
        if self._donate_pending:
            self._donate_pending = False
            if donation_aliases_cleanly(self._raw_step, batch.payload,
                                        batch.valid):
                self._donate = True
                self._build()
        if self._sketch is not None and self._key_extractor is not None:
            if self._sk_state is None:
                from windflow_tpu.monitoring.shard_ledger import \
                    device_sketch_init
                self._sk_state = device_sketch_init(self._sk_n)
            payload, valid, keys, self._sk_state = self._jit(
                batch.payload, batch.valid, self._sk_state)
        else:
            payload, valid, keys = self._jit(batch.payload, batch.valid)
        size = None if self._has_filter else batch.known_size
        return DeviceBatch(payload, batch.ts, valid, keys=keys,
                           watermark=batch.watermark, size=size,
                           frontier=batch.frontier, ts_max=batch.ts_max,
                           ts_min=batch.ts_min)


# ---------------------------------------------------------------------------
# Segment planning: the advisor's chains, trimmed to what executes today
# ---------------------------------------------------------------------------

def plan_segments(graph) -> List[dict]:
    """Executable fused segments of a composed graph: each advisor chain
    (``analysis/fusion.fusible_chains`` — the shared walk, so executor
    and advisor can never disagree about linkability) trimmed to its
    executable run — the stateless prefix plus at most one supported
    stateful tail.  Segments of fewer than two members are dropped."""
    from windflow_tpu.analysis.fusion import fusible_chains
    segments = []
    for chain in fusible_chains(graph):
        run = []
        for op in chain["ops"]:
            if _is_stateless(op):
                run.append(op)
                continue
            if run and _tail_supported(op):
                run.append(op)
            break
        if len(run) < 2:
            continue
        segments.append({
            "name": fused_name(run),
            "members": run,
            "member_names": [op.name for op in run],
            "host_name": run[-1].name,
        })
    return segments


def _upstream_edges(graph) -> dict:
    """id(op) -> [(upstream op, arrived_via_split)] over every graph
    edge — the donation-safety walk (split fan-outs alias device
    buffers across branches, so they matter here where the preflight
    upstream map folds them away)."""
    ups: dict = {}
    for edge in graph._edges():
        if edge[0] == "op":
            _, a, b = edge
            ups.setdefault(id(b), []).append((a, False))
        else:
            _, mp = edge
            src_op = mp.operators[-1]
            for child in mp.split_children:
                if child.operators:
                    ups.setdefault(id(child.operators[0]), []).append(
                        (src_op, True))
    return ups


def input_donation_safe(head, upstreams: dict) -> bool:
    """True when every producer of ``head``'s input batches stages
    FRESH, unshared device arrays per batch, so the fused program may
    take them with ``donate_argnums`` (eliding the whole-buffer copies
    the sweep ledger's donation-miss tripwire counts):

    * a host→device staging edge materializes new arrays from host
      records every batch (the pool recycles HOST buffers only, gated
      on the unpack output — batch.stage_packed);
    * a FORWARD DeviceSource emits its program's fresh outputs to one
      destination per tick.

    Everything else — device keyby splits, broadcast, device splits —
    aliases ONE payload across several destinations' masks, where a
    donation by any consumer would invalidate its siblings' views."""
    from windflow_tpu.io.device_source import DeviceSource
    ups = upstreams.get(id(head))
    if not ups:
        return False
    for up_op, via_split in ups:
        if not up_op.is_tpu:
            continue
        if isinstance(up_op, DeviceSource) and not via_split \
                and head.routing == RoutingMode.FORWARD:
            continue
        return False
    return True


def apply_fusion(graph) -> List[dict]:
    """Install the fused segments on a graph being built (called by
    ``PipeGraph._build`` after replica construction, before edge
    wiring).  Marks members, installs the prelude/exec on each segment
    host, decides input donation, and chains member closers onto the
    host so per-replica shutdown callbacks still run once.  Returns the
    segment list ``PipeGraph._fused_segments`` keeps for the wiring
    redirect, the sweep ledger, and stats attribution."""
    segments = plan_segments(graph)
    if not segments:
        return []
    upstreams = _upstream_edges(graph)
    for seg in segments:
        members = seg["members"]
        host = members[-1]
        donate = input_donation_safe(members[0], upstreams)
        seg["donate_inputs"] = donate
        for m in members[:-1]:
            m._fused_into = seg["name"]
        host._fused_name = seg["name"]
        if _is_stateless(host):
            host._fusion_exec = FusedStatelessExec(
                seg["name"], members, donate_inputs=donate)
        else:
            prelude, _ = build_prelude(members[:-1])
            host._fused_prelude = prelude
            host._fused_donate_inputs = donate
        _chain_closers(members, host)
    return segments


def _chain_closers(members, host) -> None:
    """Member closing_funcs run at HOST termination (the fused replica
    is the only one that terminates through the normal EOS path) — the
    ops/chained.fuse stance generalized to the whole segment."""
    closers = [m.closing_func for m in members if m.closing_func is not None]
    if not closers or closers == [host.closing_func]:
        return
    from windflow_tpu.meta import adapt
    adapted = [adapt(f, 0) for f in closers]

    def closing(ctx):
        for f in adapted:
            f(ctx)

    host.closing_func = closing


def attribute_member_stats(graph) -> None:
    """Per-op stats for fused members, attributed from the fused hop at
    stats-read cadence: the members' replicas never dispatch, so their
    input/output counters mirror the host hop's input count (records
    thread through the fused program; per-member survivor counts after
    interior filters are only observable with a device sync the hot
    path must never pay).  Replica 0 carries the whole-hop number."""
    for seg in graph._fused_segments:
        host = seg["members"][-1]
        inputs = sum(r.stats.inputs_received for r in host.replicas)
        for m in seg["members"][:-1]:
            for i, rep in enumerate(m.replicas):
                rep.stats.inputs_received = inputs if i == 0 else 0
                rep.stats.outputs_sent = inputs if i == 0 else 0
