"""Whole-chain fusion: compile operator chains into one XLA dispatch.

The executor half of the fusion story (ROADMAP item 1): the advisor
(windflow_tpu/analysis/fusion.py) *plans* maximal fusible chains; this
package *executes* them — at ``PipeGraph._build`` each executable chain
lowers into ONE ``wf_jit`` program per batch sweep, with the sweep
ledger (monitoring/sweep_ledger.py) attributing the before/after
dispatch and HBM-byte savings.  See ``fusion/executor.py`` for the
mechanism and ``docs/PERF.md`` round 10 for the measured effect.
"""

from windflow_tpu.fusion.executor import (apply_fusion,
                                          attribute_member_stats,
                                          build_prelude, fused_name,
                                          plan_segments)

__all__ = ["apply_fusion", "attribute_member_stats", "build_prelude",
           "fused_name", "plan_segments"]
