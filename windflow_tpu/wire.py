"""Wire plane: columnar compression of staged batches with device decode.

The ``gap_diagnosis`` bench decomposition pinned the last measured e2e
gap on the host→device tunnel: the staged path feeds ~19 MB/s against a
kernel that reads pre-staged HBM four orders of magnitude faster, so the
wire itself — not compute — bounds end-to-end numbers (ROADMAP item 4;
the compile-the-pipeline stance of arXiv 2207.00257 extended to the
decode step).  This module shrinks the wire: the staging plane's packed
uint32 buffer (``staging.PackedBatchBuilder``) is re-encoded lane by
lane with cheap columnar codecs before the ONE fused transfer, and the
inverse decode is a traced stage folded into the SAME device unpack
program ``batch.stage_packed`` already dispatches — compressed batches
cost **zero extra dispatches** and the compressed bytes never
materialize on host after the pack.

Codecs (per lane, chosen per reseed cadence from the measured data):

* ``raw``    — passthrough words (the fallback; also any lane whose data
  defeats every other codec this batch).
* ``const``  — all rows equal: 2 header words carry the value
  (all-null/constant lanes collapse to nothing).
* ``delta``  — zigzag deltas bit-packed at 8/16/32 bits (+ width 0 for a
  constant stride of 0) behind an int64 base: monotone-ish ts/id lanes.
  Arithmetic wraps two's-complement on both sides, so reconstruction is
  exact for the full int64 domain.
* ``delta2`` — delta-of-delta behind base + first delta: constant-cadence
  timestamp lanes collapse to width 0 (a handful of header words).
* ``dict``   — low-cardinality lanes: a ≤64Ki-entry sorted value table
  (stable between reseeds, shipped with each batch) + bit-packed indices.

Codec choice is re-evaluated every ``reseed_every`` batches (the key-
compaction reseed cadence); between reseeds each batch pays only a
vectorized fit-check + encode pass per lane, and a lane whose data stops
fitting its codec degrades to ``raw`` for that batch (counted, and the
next batch reseeds).  The per-lane codec descriptor is host metadata:
it keys the cached decode program (a new descriptor compiles a fresh
program — never a re-trace of an existing one, so the recompile
tripwire stays quiet) and rides no wire bytes beyond the per-batch
headers (bases, dict tables).

Wire buffer layout (padded to a :func:`staging.size_class` so the pool
recycles across codec churn — the size-class keying fix)::

    [lane0 header+payload | lane1 ... | ts lane | pad ... | n]

Requires a declared/inferred record spec on the feeding edge
(``Source_Builder.withRecordSpec`` / ``DeviceSource.batch_fn``
inference): an undeclared-spec source under ``Config.wire_compression``
downgrades to raw passthrough with a named preflight warning (WF606)
instead of silently guessing lane semantics.  Mesh-sharded staging keeps
the uncompressed per-lane path (its transfers are assembled per shard,
not packed); ``Config.wire_compression`` / ``WF_TPU_WIRE=0`` is the kill
switch, leaving one ``is not None`` check per staged batch.

Host packing uses little-endian byte views (every supported host);
device-side unpacking is pure 32-bit word arithmetic, endian-agnostic.
"""

from __future__ import annotations

import time
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from windflow_tpu import staging

#: codec kind tags (descriptor fields are plain strings/ints so the
#: descriptor tuple is hashable — it keys the cached decode program)
RAW, CONST, DELTA, DELTA2, DICT = "raw", "const", "delta", "delta2", "dict"

#: largest dictionary a lane may ship per batch (16-bit indices)
DICT_MAX = 1 << 16
#: dictionaries at/below this size pack 8-bit indices
DICT_SMALL = 1 << 8


class LaneCodec(NamedTuple):
    """Static per-lane codec descriptor: ``kind``, packed bits per
    element (``width`` in {0, 8, 16, 32}), and ``extra`` (padded dict
    table size; 0 otherwise).  Hashable — part of the decode-program
    cache key."""

    kind: str
    width: int = 32
    extra: int = 0


class WireFormat(NamedTuple):
    """Whole-buffer descriptor: one :class:`LaneCodec` per lane
    (payload lanes in order, then the implicit int64 ts lane) plus the
    size-class-padded word count of the wire buffer."""

    codecs: Tuple[LaneCodec, ...]
    words: int


RAW_CODEC = LaneCodec(RAW, 32, 0)


def _packed_words(count: int, width: int) -> int:
    if width == 0 or count <= 0:
        return 0
    per = 32 // width
    return (count + per - 1) // per


def lane_wire_words(codec: LaneCodec, dtype, capacity: int) -> int:
    """Static wire words one lane occupies under ``codec`` (headers are
    always int64 → 2 words each; dict entries are raw lane words)."""
    w = staging.lane_words(dtype)
    if codec.kind == RAW:
        return w * capacity
    if codec.kind == CONST:
        return 2
    if codec.kind == DELTA:
        return 2 + _packed_words(capacity - 1, codec.width)
    if codec.kind == DELTA2:
        return 4 + _packed_words(capacity - 2, codec.width)
    if codec.kind == DICT:
        return codec.extra * w + _packed_words(capacity, codec.width)
    raise ValueError(f"unknown lane codec kind {codec.kind!r}")


def wire_words_total(fmt_codecs, dtypes, capacity: int) -> int:
    """Unpadded wire words of a whole batch (+1 for the fill count)."""
    return 1 + sum(lane_wire_words(c, d, capacity)
                   for c, d in zip(fmt_codecs, dtypes))


# ---------------------------------------------------------------------------
# host-side encode (numpy, vectorized — runs once per staged batch)
# ---------------------------------------------------------------------------

def _zigzag(d: np.ndarray) -> np.ndarray:
    """Signed int64 deltas → unsigned zigzag (small magnitudes of either
    sign become small unsigned values).  Shift overflow wraps two's-
    complement, matching the device-side inverse exactly."""
    return ((d << 1) ^ (d >> 63)).astype(np.uint64)


def _width_for(zz_max: int) -> Optional[int]:
    if zz_max == 0:
        return 0
    if zz_max < (1 << 8):
        return 8
    if zz_max < (1 << 16):
        return 16
    if zz_max < (1 << 32):
        return 32
    return None


def _pack_width(vals: np.ndarray, width: int) -> np.ndarray:
    """Bit-pack uint32 values at ``width`` bits into little-endian
    uint32 words (byte-aligned widths only — the device unpack is a
    shift+mask, no cross-word fields)."""
    if width == 0 or len(vals) == 0:
        return np.empty(0, np.uint32)
    if width == 32:
        return np.ascontiguousarray(vals, np.uint32)
    per = 32 // width
    words = np.zeros((len(vals) + per - 1) // per, np.uint32)
    view = words.view(np.uint8 if width == 8 else np.uint16)
    view[:len(vals)] = vals.astype(view.dtype)
    return words


def _i64_header(v: int) -> List[np.ndarray]:
    """An int64 header value as [lo, hi] uint32 words (python-int
    masking: exact for the full signed domain)."""
    v = int(v)
    return [np.array([v & 0xFFFFFFFF], np.uint32),
            np.array([(v >> 32) & 0xFFFFFFFF], np.uint32)]


def _pow2ceil(n: int) -> int:
    return 1 << max(0, (max(1, n) - 1).bit_length())


class _LaneState:
    """Per-lane encoder state: the current codec choice plus the dict
    table it was chosen with (tables stay stable between reseeds so the
    per-batch fit check is one searchsorted pass)."""

    __slots__ = ("codec", "table")

    def __init__(self) -> None:
        self.codec: Optional[LaneCodec] = None
        self.table: Optional[np.ndarray] = None


class WireStats:
    """Wire-plane counters for ``stats()["Staging"]["Wire"]`` and the
    OpenMetrics ``wf_wire_*`` families.  Plain int adds (telemetry
    tolerance of the staging plane's other counters)."""

    __slots__ = ("batches", "raw_batches", "fallback_lanes", "reseeds",
                 "logical_bytes", "wire_bytes", "encode_usec")

    def __init__(self) -> None:
        self.batches = 0          # compressed batches shipped
        self.raw_batches = 0      # batches where compression lost
        self.fallback_lanes = 0   # per-batch codec misfits (lane → raw)
        self.reseeds = 0
        self.logical_bytes = 0    # decoded bytes (what raw would ship)
        self.wire_bytes = 0       # bytes actually transferred
        self.encode_usec = 0.0

    def merge(self, other: "WireStats") -> None:
        self.batches += other.batches
        self.raw_batches += other.raw_batches
        self.fallback_lanes += other.fallback_lanes
        self.reseeds += other.reseeds
        self.logical_bytes += other.logical_bytes
        self.wire_bytes += other.wire_bytes
        self.encode_usec += other.encode_usec

    def to_json(self) -> dict:
        ratio = (round(self.logical_bytes / self.wire_bytes, 4)
                 if self.wire_bytes else None)
        return {
            "batches": self.batches,
            "raw_batches": self.raw_batches,
            "fallback_lanes": self.fallback_lanes,
            "reseeds": self.reseeds,
            "logical_bytes": self.logical_bytes,
            "wire_bytes": self.wire_bytes,
            "compression_ratio": ratio,
            "encode_usec": round(self.encode_usec, 1),
        }


class WireEncoder:
    """Per-emitter lane encoder: turns one finished logical staging
    buffer into a (usually much smaller) wire buffer + its
    :class:`WireFormat`.  Codec choice per lane is re-evaluated every
    ``reseed_every`` encoded batches; in between, each batch pays one
    vectorized fit-check+encode pass per lane.  A batch compression
    cannot shrink ships the logical buffer unchanged (``fmt=None``)."""

    def __init__(self, dtypes: Sequence, capacity: int,
                 reseed_every: int = 64) -> None:
        self.dtypes = tuple(np.dtype(d) for d in dtypes) \
            + (np.dtype(np.int64),)             # + implicit ts lane
        self.capacity = capacity
        self.reseed_every = max(1, reseed_every)
        self._lane_words = [staging.lane_words(d) for d in self.dtypes]
        self._offsets = []
        off = 0
        for w in self._lane_words:
            self._offsets.append(off)
            off += w * capacity
        self._logical_words = off + 1
        self._lanes = [_LaneState() for _ in self.dtypes]
        self._since = self.reseed_every     # force choice on first batch
        self.stats = WireStats()

    # -- lane value views ---------------------------------------------------
    def _values(self, buf: np.ndarray, i: int) -> np.ndarray:
        """Lane ``i`` of the logical buffer as int64 work values (signed
        interpretation for 4-byte lanes; lo/hi recombined for 8-byte) —
        the exact domain the device decode reconstructs."""
        off, w = self._offsets[i], self._lane_words[i]
        seg = buf[off:off + w * self.capacity]
        if w == 1:
            return seg.view(np.int32).astype(np.int64)
        lo = seg[0::2].astype(np.uint64)
        hi = seg[1::2].astype(np.uint64)
        return (lo | (hi << np.uint64(32))).view(np.int64)

    def _raw_words(self, buf: np.ndarray, i: int) -> np.ndarray:
        off, w = self._offsets[i], self._lane_words[i]
        return buf[off:off + w * self.capacity]

    # -- codec selection (reseed cadence) -----------------------------------
    def _choose(self, v: np.ndarray, i: int) -> None:
        st = self._lanes[i]
        dt = self.dtypes[i]
        cap = self.capacity
        best, best_w = RAW_CODEC, lane_wire_words(RAW_CODEC, dt, cap)
        prev_table = st.table if (st.codec is not None
                                  and st.codec.kind == DICT) else None
        st.table = None
        if cap >= 1 and bool((v == v[0]).all()):
            c = LaneCodec(CONST)
            w = lane_wire_words(c, dt, cap)
            if w < best_w:
                best, best_w = c, w
        if cap >= 2:
            d = np.diff(v)
            wd = _width_for(int(_zigzag(d).max()))
            if wd is not None:
                c = LaneCodec(DELTA, wd)
                w = lane_wire_words(c, dt, cap)
                if w < best_w:
                    best, best_w = c, w
            if cap >= 3:
                wdd = _width_for(int(_zigzag(np.diff(d)).max()))
                if wdd is not None:
                    c = LaneCodec(DELTA2, wdd)
                    w = lane_wire_words(c, dt, cap)
                    if w < best_w:
                        best, best_w = c, w
        uniq = np.unique(v)
        if prev_table is not None:
            # UNION with the previous table: a low-cardinality lane
            # whose batches sample the value space converges on the
            # full set instead of flip-flopping dict→raw per batch —
            # each flip would mint a new descriptor and recompile the
            # decode; the pow2 padding usually keeps the grown table's
            # descriptor (and its compiled program) stable
            uniq = np.unique(np.concatenate([prev_table, uniq]))
        if len(uniq) <= DICT_MAX:
            padded = _pow2ceil(len(uniq))
            c = LaneCodec(DICT, 8 if padded <= DICT_SMALL else 16, padded)
            w = lane_wire_words(c, dt, cap)
            if w < best_w:
                best, best_w = c, w
                st.table = np.concatenate(
                    [uniq, np.full(padded - len(uniq), uniq[-1],
                                   np.int64)])
        st.codec = best

    # -- per-batch encode ---------------------------------------------------
    def _encode_lane(self, buf, v: np.ndarray,
                     i: int) -> Tuple[List[np.ndarray], LaneCodec]:
        """Encode lane ``i`` under its current codec; a misfit (data
        stopped matching the choice) degrades to raw for this batch and
        forces a reseed at the next."""
        st = self._lanes[i]
        c = st.codec or RAW_CODEC
        out = self._try_encode(buf, v, i, c, st)
        if out is not None:
            return out, c
        self.stats.fallback_lanes += 1
        self._since = self.reseed_every     # re-choose next batch
        return [self._raw_words(buf, i)], RAW_CODEC

    def _try_encode(self, buf, v, i, c: LaneCodec,
                    st: _LaneState) -> Optional[List[np.ndarray]]:
        if c.kind == RAW:
            return [self._raw_words(buf, i)]
        if c.kind == CONST:
            if not bool((v == v[0]).all()):
                return None
            return _i64_header(v[0])
        if c.kind == DELTA:
            d = np.diff(v)
            zz = _zigzag(d)
            if len(zz) and int(zz.max()) >= (1 << max(1, c.width)):
                return None
            if c.width == 0 and len(zz) and int(zz.max()) != 0:
                return None
            return _i64_header(v[0]) \
                + [_pack_width(zz.astype(np.uint32), c.width)]
        if c.kind == DELTA2:
            d = np.diff(v)
            dd = np.diff(d)
            zz = _zigzag(dd)
            if len(zz) and int(zz.max()) >= (1 << max(1, c.width)):
                return None
            if c.width == 0 and len(zz) and int(zz.max()) != 0:
                return None
            return _i64_header(v[0]) + _i64_header(d[0] if len(d) else 0) \
                + [_pack_width(zz.astype(np.uint32), c.width)]
        if c.kind == DICT:
            table = st.table
            if table is None:
                return None
            idx = np.searchsorted(table, v)
            idx = np.clip(idx, 0, len(table) - 1)
            if not bool((table[idx] == v).all()):
                return None
            w = self._lane_words[i]
            if w == 1:
                tw = (table & np.int64(0xFFFFFFFF)).astype(np.uint32)
            else:
                u = table.view(np.uint64)
                tw = np.empty(2 * len(table), np.uint32)
                tw[0::2] = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
                tw[1::2] = (u >> np.uint64(32)).astype(np.uint32)
            return [tw, _pack_width(idx.astype(np.uint32), c.width)]
        return None

    def encode(self, buf: np.ndarray,
               pool=None) -> Tuple[np.ndarray, Optional[WireFormat]]:
        """Encode one FINISHED logical staging buffer (tail zeroed, fill
        count stamped at ``buf[-1]``).  Returns ``(wire_buf, fmt)`` —
        the wire buffer is acquired from ``pool`` at its size class and
        ``buf`` is released back (host-only use, no gate) — or
        ``(buf, None)`` when compression would not shrink the transfer
        (the caller ships the logical buffer exactly as before)."""
        t0 = time.perf_counter()
        if buf.shape[0] != self._logical_words:
            # capacity drift (defensive): ship raw rather than corrupt
            return buf, None
        if self._since >= self.reseed_every:
            for i in range(len(self.dtypes)):
                self._choose(self._values(buf, i), i)
            self._since = 0
            self.stats.reseeds += 1
        self._since += 1
        parts: List[List[np.ndarray]] = []
        used: List[LaneCodec] = []
        total = 1
        for i in range(len(self.dtypes)):
            st = self._lanes[i]
            # raw lanes copy words straight through: no int64 lift, no
            # fit check — the steady-state cost of an incompressible
            # lane is one memcpy, nothing more
            v = None if (st.codec is None or st.codec.kind == RAW) \
                else self._values(buf, i)
            arrs, c = self._encode_lane(buf, v, i)
            parts.append(arrs)
            used.append(c)
            total += lane_wire_words(c, self.dtypes[i], self.capacity)
        padded = staging.size_class(total)
        if padded >= self._logical_words:
            # compression lost: the logical buffer ships unchanged —
            # accrue it at FULL size on both counters so the reported
            # compression_ratio is the blended transfer truth, not the
            # compressed-batches-only flatter (the honesty contract)
            self.stats.raw_batches += 1
            self.stats.wire_bytes += self._logical_words * 4
            self.stats.logical_bytes += self._logical_words * 4
            self.stats.encode_usec += (time.perf_counter() - t0) * 1e6
            return buf, None
        wire = pool.acquire(padded) if pool is not None \
            else np.empty(padded, np.uint32)
        off = 0
        for arrs in parts:
            for a in arrs:
                wire[off:off + len(a)] = a
                off += len(a)
        # pad gap is never read by the decode program; recycled buffers
        # arrive with undefined contents anyway (StagingPool contract)
        wire[-1] = buf[-1]
        if pool is not None:
            pool.release(buf, None)     # host-only scratch: no gate
        self.stats.batches += 1
        self.stats.logical_bytes += self._logical_words * 4
        self.stats.wire_bytes += padded * 4
        self.stats.encode_usec += (time.perf_counter() - t0) * 1e6
        return wire, WireFormat(tuple(used), padded)

    def codec_table(self) -> list:
        """Current per-lane codec choices (stats surface)."""
        return [{"lane": i, "dtype": str(d),
                 "codec": (st.codec.kind if st.codec else "unseeded"),
                 "width": (st.codec.width if st.codec else None),
                 "dict_size": (st.codec.extra if st.codec else 0)}
                for i, (d, st) in enumerate(zip(self.dtypes, self._lanes))]


# ---------------------------------------------------------------------------
# device-side decode (traced; inlined into batch._get_unpack's program)
# ---------------------------------------------------------------------------

def build_wire_decode(fmt: WireFormat, dtypes, capacity: int):
    """Traced inverse of :class:`WireEncoder`: maps the uint32 wire
    buffer to the typed payload columns + int64 ts lane, for
    ``batch._get_unpack`` to inline AHEAD of its existing valid-mask
    derivation — the whole decode rides the one unpack dispatch the
    staged path already pays (zero extra dispatches, pinned by
    tests/test_wire.py via the jit registry).  ``dtypes`` are the
    payload lane dtype strings; the ts lane is implicit."""
    import jax.numpy as jnp

    all_dts = tuple(np.dtype(d) for d in dtypes) + (np.dtype(np.int64),)

    def _unpack_width(b, off, count, width):
        if width == 0 or count <= 0:
            return jnp.zeros(max(count, 0), jnp.uint32)
        if width == 32:
            return b[off:off + count]
        per = 32 // width
        idx = jnp.arange(count, dtype=jnp.int32)
        w = b[off + idx // per]
        sh = ((idx % per) * width).astype(jnp.uint32)
        return (w >> sh) & jnp.uint32((1 << width) - 1)

    def _i64(lo, hi):
        return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.int64)

    def _unzigzag(zz):
        z = zz.astype(jnp.int64)
        return (z >> 1) ^ -(z & 1)

    def _from_i64(v, dt):
        import jax
        if dt.itemsize == 8:
            return v if dt == np.dtype(np.int64) \
                else v.astype(jnp.uint64)
        w = (v & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        return jax.lax.bitcast_convert_type(w, dt)

    def _words_to_dtype(w32, dt):
        import jax
        return jax.lax.bitcast_convert_type(w32, dt) \
            if dt != np.dtype(np.uint32) else w32

    def decode(b):
        cols = []
        off = 0
        for c, dt in zip(fmt.codecs, all_dts):
            w = staging.lane_words(dt)
            if c.kind == RAW:
                seg = b[off:off + w * capacity]
                if w == 2:
                    lo = seg[0::2].astype(jnp.int64)
                    hi = seg[1::2].astype(jnp.int64)
                    cols.append(((hi << 32) | lo).astype(dt))
                else:
                    cols.append(_words_to_dtype(seg, dt))
            elif c.kind == CONST:
                v = _i64(b[off], b[off + 1])
                cols.append(jnp.broadcast_to(_from_i64(v, dt),
                                             (capacity,)))
            elif c.kind == DELTA:
                base = _i64(b[off], b[off + 1])
                zz = _unpack_width(b, off + 2, capacity - 1, c.width)
                d = _unzigzag(zz)
                v = base + jnp.concatenate(
                    [jnp.zeros(1, jnp.int64), jnp.cumsum(d)])
                cols.append(_from_i64(v, dt))
            elif c.kind == DELTA2:
                base = _i64(b[off], b[off + 1])
                d0 = _i64(b[off + 2], b[off + 3])
                zz = _unpack_width(b, off + 4, capacity - 2, c.width)
                dd = _unzigzag(zz)
                d = d0 + jnp.concatenate(
                    [jnp.zeros(1, jnp.int64), jnp.cumsum(dd)])
                v = base + jnp.concatenate(
                    [jnp.zeros(1, jnp.int64), jnp.cumsum(d)])
                cols.append(_from_i64(v, dt))
            elif c.kind == DICT:
                idx = _unpack_width(b, off + c.extra * w, capacity,
                                    c.width).astype(jnp.int32)
                if w == 1:
                    tw = b[off:off + c.extra]
                    cols.append(_words_to_dtype(tw[idx], dt))
                else:
                    seg = b[off:off + 2 * c.extra]
                    lo = seg[0::2][idx].astype(jnp.int64)
                    hi = seg[1::2][idx].astype(jnp.int64)
                    cols.append(_from_i64((hi << 32) | lo, dt))
            else:
                raise ValueError(f"unknown lane codec {c.kind!r}")
            off += lane_wire_words(c, dt, capacity)
        return cols

    return decode


# ---------------------------------------------------------------------------
# graph attachment + stats surfaces
# ---------------------------------------------------------------------------

def wire_enabled(cfg) -> bool:
    """Resolve ``Config.wire_compression``: True/False ("1"/"0") are
    explicit; "auto" (the default) enables compression exactly when the
    default backend is a real accelerator — on the CPU fallback host
    and "device" share memory, so the wire is a memcpy and encode/
    decode would be pure overhead on the staged path (measured ~40% at
    the e2e capacity), while on a TPU tunnel every wire byte is the
    bottleneck the plane exists to shrink."""
    v = getattr(cfg, "wire_compression", "auto")
    if v in (True, 1, "1", "on", "true"):
        return True
    if v in (False, 0, None, "", "0", "off", "false"):
        return False
    import jax
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # lint: broad-except-ok (an uninitialized or
        # exotic backend resolves conservatively to "no compression")
        return False


def iter_stage_emitters(graph):
    """Yield ``(edge_src_op, route_op, emitter)`` for every host→device
    staging emitter in a BUILT graph, descending into keyed staging
    emitters' per-partition inner emitters and split branches — the one
    walk shared by :func:`attach_wire` and :func:`wire_section`."""
    from windflow_tpu.parallel.emitters import (DeviceStageEmitter,
                                                KeyedDeviceStageEmitter,
                                                SplittingEmitter)

    def expand(a, route_op, em):
        if em is None:
            return
        if isinstance(em, KeyedDeviceStageEmitter):
            for inner in em._inner:
                yield a, route_op, inner
        elif isinstance(em, DeviceStageEmitter):
            yield a, route_op, em

    for edge in graph._edges():
        if edge[0] == "op":
            _, a, b = edge
            for rep in a.replicas:
                yield from expand(a, b, rep.emitter)
        else:
            _, mp = edge
            src = mp.operators[-1]
            heads = [c.operators[0] for c in mp.split_children
                     if c.operators]
            for rep in src.replicas:
                em = rep.emitter
                if not isinstance(em, SplittingEmitter):
                    continue
                for head, br in zip(heads, em.branches):
                    yield from expand(src, head, br)


def attach_wire(graph) -> None:
    """Enable wire compression on the staging emitters whose feeding
    edge has a declared/inferred record spec (the WF606 contract:
    spec-less edges stay raw passthrough — preflight already named
    them).  Called by ``PipeGraph._build`` after wiring, before any
    batch stages; with ``Config.wire_compression`` off this is never
    called and no encoder attaches anywhere."""
    from windflow_tpu.analysis.preflight import _UNKNOWN, propagate_specs
    try:
        in_specs, _ = propagate_specs(graph)
    except Exception:  # lint: broad-except-ok (abstract eval of
        # arbitrary user kernels — the wire plane degrades to raw
        # passthrough, it must never take the build down)
        in_specs = {}
    reseed = getattr(graph.config, "key_compaction_reseed", 64)
    for _src, route_op, em in iter_stage_emitters(graph):
        if em._stage_target is not None:
            continue    # mesh staging: per-shard assembly, not packed
        spec = in_specs.get(id(route_op))
        if spec is None or spec is _UNKNOWN:
            continue    # WF606: documented raw-passthrough downgrade
        em.enable_wire(reseed)


def wire_section(graph) -> dict:
    """``stats()["Staging"]["Wire"]``: merged wire-plane counters over
    the graph's staging emitters plus the current per-lane codec table
    (one table per distinct lane layout)."""
    enabled = wire_enabled(graph.config)
    agg = WireStats()
    codecs = []
    emitters = 0
    for _src, _route, em in iter_stage_emitters(graph):
        for enc in getattr(em, "_wire_encoders", {}).values():
            emitters += 1
            agg.merge(enc.stats)
            if enc.stats.batches and len(codecs) < 8:
                codecs.append(enc.codec_table())
    out = {"enabled": enabled, "encoders": emitters}
    out.update(agg.to_json())
    out["codecs"] = codecs[0] if len(codecs) == 1 else codecs
    return out
