"""Diagnostic records shared by every static-analysis pass.

One record type surfaces everything the analysis subsystem finds: the
pre-flight graph checker (``analysis/preflight.py``), the hot-path AST
lint (``tools/wf_lint.py``), and the debug-mode race detector
(``analysis/debug_concurrency.py``).  WindFlow gets the same guarantees
from C++ template/concept errors at compile time; a Python/JAX framework
has no compiler seam, so the seam is built here: stable ``WFxxx`` codes,
a severity, the graph node or file:line the finding anchors to, and a fix
hint — machine-consumable (``to_json``) and human-readable (``__str__``)
from the same record.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from windflow_tpu.basic import WindFlowError

#: code -> (default severity, one-line description).  The table is the
#: contract: tests assert codes, docs/ANALYSIS.md renders it, and
#: tools/wf_check.py --json ships it.  Codes are append-only — a released
#: code never changes meaning.
CODES = {
    # -- abstract evaluation of operator chains (WF1xx) ----------------------
    "WF101": ("error", "operator kernel failed abstract evaluation "
                       "(dtype/shape mismatch in the chain)"),
    "WF102": ("error", "filter predicate must return a boolean scalar"),
    "WF103": ("error", "reduce combiner must preserve the record "
                       "structure, shapes and dtypes"),
    "WF104": ("error", "key extractor of a keyed device operator must "
                       "return an integer scalar"),
    "WF105": ("error", "window combiner must preserve the lifted "
                       "aggregate structure"),
    "WF106": ("warning", "merged branches deliver different record "
                         "structures"),
    # -- window specifications (WF2xx) ---------------------------------------
    "WF201": ("error", "window length and slide must be positive"),
    # warning, not error: hopping windows WITH gaps are a supported
    # semantic (the FFAT spec sweep exercises them against an oracle) —
    # but an accidental swap of (length, slide) silently drops gap
    # tuples, so it is surfaced loudly
    "WF202": ("warning", "window slide exceeds window length: tuples in "
                         "the gaps belong to no window"),
    "WF203": ("warning", "lateness on a count-based window is ignored"),
    "WF204": ("error", "window lateness must be non-negative"),
    # -- graph composition / routing (WF3xx) ---------------------------------
    "WF301": ("error", "operator follows a terminal (sink) operator"),
    "WF302": ("error", "pipeline does not end in a sink"),
    "WF303": ("error", "KEYBY routing requires a key extractor"),
    "WF304": ("error", "malformed graph composition"),
    # -- mesh / sharding (WF4xx) ---------------------------------------------
    "WF401": ("error", "staged batch capacity not divisible across the "
                       "mesh devices"),
    "WF402": ("error", "keyed state space not divisible by the mesh key "
                       "axis"),
    "WF403": ("error", "merged upstream paths deliver unequal fixed "
                       "batch capacities"),
    # key compaction (parallel/compaction.py, docs/PERF.md round 12):
    # a declared-bounded reduce without a monoid runs the SORTED path —
    # declared dense beats both sorting and the compacted remap
    "WF404": ("warning", "bounded key space declared but no monoid "
                         "combiner: the reduce takes the sorted path"),
    # the declared kind REPLACES the combiner on every specialized stage
    # (dense table, compacted remap, mesh collective) — a combiner that
    # provably diverges from it leafwise silently changes results there
    "WF405": ("warning", "declared monoid combiner diverges from the "
                         "user combiner on at least one record leaf"),
    # -- watermarks / time (WF5xx) -------------------------------------------
    "WF501": ("error", "EVENT time policy requires a timestamp "
                       "extractor on every source"),
    "WF502": ("error", "merge joins branches with mixed watermark modes"),
    "WF503": ("warning", "time-based windows fed by a watermark-less "
                         "source fire only at end-of-stream"),
    # -- durability / checkpoint-restore (WF6xx) -----------------------------
    "WF601": ("warning", "checkpointing enabled with a source that "
                         "cannot replay deterministically"),
    "WF602": ("error", "restore target graph mismatches the checkpoint "
                       "manifest topology"),
    "WF603": ("warning", "operator holds cross-batch state the "
                         "checkpoint cannot capture"),
    # rescale-on-restore (durability/rebucket.py, docs/DURABILITY.md
    # "Multi-chip checkpoints & rescale-on-restore"): a restore onto a
    # different mesh shape / shard count re-buckets keyed state through
    # the operator's declared key space or compaction remap — operators
    # providing neither refuse the shape change
    "WF604": ("warning", "keyed operator on a mesh checkpoints state "
                         "with no declared key space or compaction "
                         "remap: a shape-changing restore cannot "
                         "re-bucket it"),
    "WF605": ("error", "restore manifest shard shape cannot be "
                       "re-bucketed onto the target graph"),
    # wire plane (windflow_tpu/wire.py, docs/OBSERVABILITY.md "Wire
    # plane"): codec choice needs the lane semantics only a
    # declared/inferred record spec provides — a spec-less staging edge
    # under Config.wire_compression downgrades to raw passthrough, and
    # that downgrade is NAMED here instead of happening silently
    "WF606": ("warning", "wire compression downgraded to raw "
                         "passthrough: the staging edge has no "
                         "declared/inferred record spec"),
    # Pallas kernels (windflow_tpu/kernels, docs/PERF.md round 14):
    # ``WF_TPU_PALLAS=1`` forces the hand-written FFAT kernels on, but
    # three downgrades are built in — a backend with no lowering
    # (neither TPU Mosaic nor the CPU interpreter) keeps the lax path,
    # a MESH graph keeps it too (the shard_map step factories compose
    # lax bodies this round), and a window whose combiner is a GENERIC
    # traced function (no declared sum/max/min monoid) keeps the lax
    # sliding fold (only declared monoids ride the MXU pane combine).
    # Forcing makes those downgrades NAMED instead of silent, mirroring
    # WF606's raw-passthrough contract; "auto" picks silently.
    "WF607": ("warning", "Pallas kernels forced on but downgraded to "
                         "the lax path (unsupported backend, mesh "
                         "graph, or a generic combiner on the MXU "
                         "pane-combine path)"),
    # Megastep executor (windflow_tpu/megastep.py, docs/PERF.md round
    # 15): ``WF_TPU_MEGASTEP=K`` forces K staged sweeps folded into one
    # compiled scan program, but the fold only exists for a
    # single-dest device staging edge whose tail steps entirely on
    # device — a host operator, a mesh-sharded or host-interning
    # stateful tail, a compacted key space (host admission runs per
    # batch), or a spec-less source keeps the per-batch cadence.
    # Forcing makes that downgrade NAMED instead of silent — the
    # WF606/WF607 contract applied to the megastep plane.  "auto"
    # picks silently.
    "WF608": ("warning", "megastep forced on but the edge downgraded "
                         "to per-batch dispatch (host operator, mesh "
                         "or host-interning tail, compacted key "
                         "space, or spec-less source)"),
    # -- determinism for replay (WF61x, wfverify — analysis/tracecheck.py):
    #    kernels and callbacks of a durability-enabled graph must
    #    regenerate the committed prefix identically on replay
    #    (docs/DURABILITY.md "Determinism requirements") -------------------
    "WF611": ("warning", "RNG without an explicitly threaded key in a "
                         "kernel/callback of a checkpointed graph"),
    "WF612": ("warning", "wall-clock read in a kernel/callback of a "
                         "checkpointed graph"),
    "WF613": ("warning", "id()/hash() identity dependence in a "
                         "kernel/callback of a checkpointed graph"),
    "WF614": ("warning", "set iteration-order dependence in a "
                         "kernel/callback of a checkpointed graph"),
    # -- hot-path lint (WF7xx, emitted by tools/wf_lint.py) ------------------
    "WF701": ("error", "allocation inside a @hot_path function"),
    "WF702": ("error", "host synchronization inside a @hot_path function"),
    "WF703": ("error", "lock acquisition inside a @hot_path function"),
    "WF711": ("error", "bare except"),
    "WF712": ("error", "broad 'except Exception' without an allowlist "
                       "justification"),
    "WF721": ("error", "lock-guarded attribute accessed outside its "
                       "declared lock"),
    # -- wfverify: object-level static verification of the actual
    #    function objects handed to device operators plus the
    #    framework's wf_jit wrapper bodies (analysis/tracecheck.py) --------
    "WF800": ("warning", "wfverify pass failed internally and was "
                         "skipped (analysis degraded, graph unchecked "
                         "by the object-level verifier)"),
    # trace-safety (WF80x)
    "WF801": ("error", "host materialization of a traced value inside a "
                       "jit-traced kernel"),
    "WF802": ("error", "Python control flow on a traced value inside a "
                       "jit-traced kernel"),
    "WF803": ("warning", "mutation of closure/global/default-arg state "
                         "inside a jit-traced kernel (trace-time side "
                         "effect)"),
    "WF804": ("warning", "print() inside a jit-traced kernel (runs at "
                         "trace time only; use jax.debug.print)"),
    # recompile hazards (WF81x) — the static twin of the wf_jit
    # recompile-storm tripwire (monitoring/jit_registry.py)
    "WF811": ("warning", "trace-time value that can vary per call baked "
                         "into a jit-traced kernel (stale constant / "
                         "recompile driver)"),
    "WF812": ("warning", "data-dependent output shape inside a "
                         "jit-traced kernel (fails to trace or "
                         "recompiles per batch)"),
    # donation safety (WF82x) — the static twin of the sweep ledger's
    # donation-miss audit (monitoring/sweep_ledger.py)
    "WF821": ("error", "donated operand read after dispatch (the buffer "
                       "is dead once the compiled program owns it)"),
    # -- wfir: IR-level audit of the LOWERED StableHLO of every wf_jit
    #    program (analysis/ir_audit.py, tools/wf_ir.py).  The preflight
    #    checker reasons about the composed graph and wfverify about the
    #    Python source; this family is proved on the module XLA actually
    #    compiles — captured from the registry's existing first-compile
    #    lowering, zero extra compiles (docs/ANALYSIS.md "wfir") -----------
    "WF900": ("warning", "ir-audit pass failed internally and was "
                         "skipped (analysis degraded, lowered programs "
                         "unchecked)"),
    "WF901": ("error", "cross-chip collective in a program on an edge "
                       "the aligned-ingest plan promised (or would "
                       "make) collective-free"),
    "WF902": ("error", "host callback / infeed-outfeed custom call "
                       "inside a hot-path program"),
    "WF903": ("error", "f64/i64 values survived into a TPU-targeted "
                       "program past the compiled-dtype gates"),
    "WF904": ("warning", "dynamic-shape op in the lowered module (IR "
                         "twin of the WF812 recompile hazard)"),
    "WF905": ("error", "donation miss at IR level: donated operands "
                       "with no input-output aliasing in the lowered "
                       "module"),
    "WF906": ("warning", "mid-program device<->host transfer (scalar "
                         "D2H sync) in the lowered module"),
    "WF907": ("warning", "Pallas kernel lowered without a Mosaic "
                         "custom call on a compiled backend "
                         "(interpret/lax fallback — the WF607 "
                         "downgrade, proven on the IR)"),
}


@dataclasses.dataclass
class Diagnostic:
    """One analysis finding.

    ``node`` names the graph operator (pre-flight passes) and ``location``
    carries ``file:line`` (lint passes); either may be None — the two
    anchor styles share the record so ``wf_check --json`` and
    ``wf_lint --json`` emit the same schema.
    """

    code: str
    message: str
    node: Optional[str] = None
    location: Optional[str] = None
    hint: Optional[str] = None
    severity: str = ""

    def __post_init__(self) -> None:
        if not self.severity:
            self.severity = CODES.get(self.code, ("error",))[0]

    def to_json(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity,
            "message": self.message,
            "node": self.node,
            "location": self.location,
            "hint": self.hint,
        }

    def __str__(self) -> str:
        where = self.location or (f"node '{self.node}'" if self.node
                                  else "graph")
        s = f"{self.code} [{self.severity}] {where}: {self.message}"
        if self.hint:
            s += f" (hint: {self.hint})"
        return s


class PreflightWarning(UserWarning):
    """Carrier for warning-severity pre-flight diagnostics (and for
    error-severity ones under ``Config.preflight = "warn"``)."""


class PreflightError(WindFlowError):
    """Raised by ``PipeGraph.start()`` under ``Config.preflight="error"``
    when the checker finds error-severity diagnostics.  Carries ALL of
    them — the message lists every violation, not just the first."""

    def __init__(self, diagnostics: List[Diagnostic]) -> None:
        self.diagnostics = list(diagnostics)
        n = len(self.diagnostics)
        lines = "\n  ".join(str(d) for d in self.diagnostics)
        super().__init__(
            f"pre-flight check found {n} error(s) "
            f"(Config.preflight='warn'/'off' to bypass):\n  {lines}")
