"""The ``@hot_path`` annotation: a machine-checkable performance contract.

PRs 1-2 established three hot-path invariants by convention — no
allocation, no host synchronization, no lock acquisition — on the staging
pack loop, the flight-recorder ring writes, and the emitter/collector
service loops.  This decorator makes the convention visible to the AST
lint (``tools/wf_lint.py``), which enforces it on every function carrying
the mark:

* **no allocation** — no ``np.zeros``-family calls, no ``list()``/
  ``dict()``/``set()`` calls, no comprehensions (small literals are fine:
  they are arena-cheap and unavoidable for message passing);
* **no host sync** — no ``np.asarray``, ``.block_until_ready()``,
  ``jax.device_get`` (each can stall the driver on device work);
* **no locks** — no ``with ...lock`` / ``.acquire()`` (a hot-path lock
  serializes the worker pool on its hottest path).

At runtime the decorator is an identity function plus one attribute — it
adds NOTHING to the marked function's cost; the enforcement is entirely
static.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)

#: attribute stamped on marked functions (introspection / tests)
HOT_PATH_ATTR = "__wf_hot_path__"


def hot_path(fn: F) -> F:
    """Mark ``fn`` as hot-path code: ``tools/wf_lint.py`` rejects
    allocation, host synchronization and lock acquisition in its body
    (codes WF701/WF702/WF703)."""
    setattr(fn, HOT_PATH_ATTR, True)
    return fn
