"""Latency advisor: turn the ledger's decomposition into a sizing plan.

The latency ledger (monitoring/latency_ledger.py) *measures* — five
critical-path segment histograms per operator, the rolling e2e p99, the
SLO verdict; this module *plans*: given a live
``stats()["Latency_plane"]`` section it ranks every operator by its
share of the decomposed critical path and emits the concrete
per-operator knob contract an adaptive sizer implements — exactly the
ledger→advisor→executor progression of PRs 6/7 (fusion) and 9/12
(resharding).  The PR-18 adaptive sizer is the consumer.

The plan's unit of work is a **knob override**:

``set_megastep_sweeps``
    the dominant segment is ``emitted_to_dispatched`` (the megastep
    K-wait) on an operator with a megastep edge and the e2e p99 is over
    budget — K is buying throughput with latency, so shrink it:
    ``recommended_k = clamp(k // ceil(p99 / budget), 1, k)``, i.e. cut
    the group wait by at least the overshoot factor.

``shrink_tick_chunk``
    the dominant segment is ``staged_to_emitted`` (ingest/staging
    batching) and the p99 is over budget — the source's tick chunk is
    holding tuples before they ever reach the graph; shrink it by the
    overshoot factor.

``regrow_megastep_sweeps``
    the p99 is UNDER budget with at least ``REGROW_HEADROOM``× headroom
    and the operator runs a megastep edge below its configured ceiling —
    latency is being left on the table; double K back toward
    throughput.  Emitted only with an SLO declared: with no budget there
    is no headroom to speak of.

Entry points: :func:`rank` (per-op summary, worst budget share first)
and :func:`plan` (the sizer contract), both consumed by
``tools/wf_slo.py``.  Pure stdlib — no jax, no numpy — so the CLI keeps
the ``wf_metrics``/``wf_doctor`` scrape-host stance.
"""

from __future__ import annotations

import math
from typing import List, Optional

#: p99 must be under budget by this factor before the advisor suggests
#: regrowing megastep K back toward throughput
REGROW_HEADROOM = 2.0

#: segments whose fix is a megastep-K shrink vs a source-side shrink
_K_WAIT_SEGMENT = "emitted_to_dispatched"
_INGEST_SEGMENT = "staged_to_emitted"


def rank(latency_section: dict) -> List[dict]:
    """Ranked per-operator summary out of a live
    ``stats()["Latency_plane"]`` section: largest budget share first."""
    out = []
    for name, entry in (latency_section.get("per_op") or {}).items():
        if not isinstance(entry, dict):
            continue
        segs = entry.get("segments_usec") or {}
        row = {
            "op": name,
            "budget_share": entry.get("budget_share"),
            "total_usec": entry.get("total_usec"),
            "dominant_segment": entry.get("dominant_segment"),
            "segment_p99_usec": {
                seg: (q or {}).get("p99") for seg, q in segs.items()
                if isinstance(q, dict)},
            "device_busy_usec": entry.get("device_busy_usec"),
        }
        if entry.get("megastep_k"):
            row["megastep_k"] = entry["megastep_k"]
            row["freshness_floor_usec"] = \
                entry.get("freshness_floor_usec")
        if isinstance(entry.get("freshness_usec"), dict):
            row["freshness_p99_usec"] = \
                entry["freshness_usec"].get("p99")
        out.append(row)
    out.sort(key=lambda r: r["budget_share"] or 0.0, reverse=True)
    return out


def _actions(row: dict, over: float, headroom: float) -> List[dict]:
    """Knob overrides for one ranked op given the graph-wide overshoot
    factor (p99/budget; 0 when no SLO is declared)."""
    acts: List[dict] = []
    k = row.get("megastep_k") or 0
    dom = row.get("dominant_segment")
    if over > 1.0:
        if dom == _K_WAIT_SEGMENT and k > 1:
            rec = max(1, min(k, k // int(math.ceil(over))))
            if rec < k:
                acts.append({
                    "kind": "set_megastep_sweeps",
                    "from_k": k,
                    "recommended_k": rec,
                    "note": f"megastep K-wait dominates at "
                            f"{over:.2f}x the budget — cut the group "
                            f"wait by the overshoot factor",
                })
        elif dom == _INGEST_SEGMENT:
            factor = int(math.ceil(over))
            acts.append({
                "kind": "shrink_tick_chunk",
                "shrink_factor": factor,
                "note": f"ingest/staging wait dominates at "
                        f"{over:.2f}x the budget — tuples queue before "
                        f"entering the graph; shrink the source tick "
                        f"chunk {factor}x",
            })
    elif 0.0 < over and headroom >= REGROW_HEADROOM and k >= 1:
        acts.append({
            "kind": "regrow_megastep_sweeps",
            "from_k": k,
            "recommended_k": k * 2,
            "note": f"p99 holds {headroom:.1f}x headroom under the "
                    f"budget — trade latency back for throughput",
        })
    return acts


def plan(latency_section: dict, graph_name: Optional[str] = None,
         top: int = 0) -> dict:
    """The adaptive-sizer contract: ranked ops, each with its knob
    overrides.  ``over_budget``/``headroom_ratio`` are graph-wide (the
    SLO is an e2e budget); actions are per-operator, attributed by each
    op's dominant segment."""
    slo = latency_section.get("slo") or {}
    budget_ms = slo.get("budget_ms") or latency_section.get("slo_ms") or 0
    p99_usec = (latency_section.get("e2e_usec") or {}).get("p99") or 0
    p99_ms = p99_usec / 1000.0
    over = (p99_ms / budget_ms) if budget_ms and p99_ms else 0.0
    headroom = (budget_ms / p99_ms) if budget_ms and p99_ms else 0.0
    ops = []
    for row in rank(latency_section):
        row = dict(row)
        row["actions"] = _actions(row, over, headroom)
        ops.append(row)
    if top:
        ops = ops[:top]
    return {
        "advisor": "latency/1",
        "graph": graph_name,
        "slo_budget_ms": budget_ms or None,
        "e2e_p99_ms": round(p99_ms, 3),
        "over_budget": over > 1.0,
        "overshoot_factor": round(over, 4) if over else None,
        "headroom_ratio": round(headroom, 4) if headroom else None,
        "slo_active": bool(slo.get("active")),
        "verdict": slo.get("verdict") or slo.get("last_verdict"),
        "traces_decomposed": latency_section.get("traces_decomposed"),
        "actionable": sum(1 for o in ops if o["actions"]),
        "ops": ops,
    }
