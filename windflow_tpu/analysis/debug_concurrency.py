"""Debug-mode race detector (``WF_TPU_DEBUG_CONCURRENCY=1``).

The driver loop's shared mutable structures — the staging pool's slot
dict, the flight-recorder rings, a replica's inbox/inflight state, the
stats accumulators — are protected by a mix of locks and single-consumer
conventions.  A convention violated (two pool threads draining one
replica, a refactor touching ``StagingPool._slots`` outside its lock)
corrupts silently: wrong counters, aliased buffers, torn batches.  Under
the debug flag those violations become immediate
:class:`ConcurrencyViolation` diagnostics:

* **lock-held assertions** — :class:`DebugLock` records its owning
  thread and :class:`LockCheckedDict` rejects any mutation performed
  while the guarding lock is not held by the mutating thread
  (``StagingPool`` swaps both in when the flag is on);
* **owner-thread tagging / entry guards** — :func:`enter`/:func:`exit_`
  bracket single-consumer critical sections (replica drains, ring
  writes, stats samples, the staging pack loop); overlapping entry from
  a second thread raises with both thread names and sites.

Cost when the flag is off: every instrumentation site is guarded by a
single module-level flag check (``if debug_concurrency.ENABLED``) — no
wrapper objects, no dict lookups, nothing on the hot path.  The flag is
read from the environment once at import; tests flip it with
:func:`set_enabled`.
"""

from __future__ import annotations

import os
import threading
from collections import deque

from windflow_tpu.basic import WindFlowError

#: module-level switch — the ONLY thing hot paths check when the
#: detector is off.  Import-time environment read; set_enabled() for
#: tests and embedders.
ENABLED = bool(int(os.environ.get("WF_TPU_DEBUG_CONCURRENCY", "0")))


class ConcurrencyViolation(WindFlowError):
    """A cross-thread access broke a documented concurrency contract."""


def set_enabled(on: bool) -> None:
    """Flip the detector at runtime (tests; embedders that cannot set the
    environment before import).  Clears the entry-guard table so stale
    bracket state from a prior enablement cannot false-positive."""
    global ENABLED
    ENABLED = bool(on)
    _active.clear()


# -- entry guards (single-consumer critical sections) ------------------------

#: id(obj) -> (thread_id, thread_name, site) while a guarded section is
#: active.  Plain dict: CPython dict ops are atomic under the GIL, and the
#: guard only ever compares/installs whole entries.
_active: dict = {}


def enter(obj, site: str) -> None:
    """Enter a single-consumer critical section on ``obj``.  A second
    thread entering while the first is still inside is exactly the race
    the single-consumer convention forbids — raise with both sites."""
    me = threading.get_ident()
    cur = _active.get(id(obj))
    if cur is not None and cur[0] != me:
        raise ConcurrencyViolation(
            f"{site}: thread '{threading.current_thread().name}' entered "
            f"while thread '{cur[1]}' is inside {cur[2]} on the same "
            f"{type(obj).__name__} — this structure is single-consumer "
            "by construction (WF_TPU_DEBUG_CONCURRENCY)")
    _active[id(obj)] = (me, threading.current_thread().name, site)


def exit_(obj) -> None:
    """Leave a critical section entered with :func:`enter`."""
    _active.pop(id(obj), None)


class entry_guard:
    """``with entry_guard(obj, site):`` form of enter/exit_ for sections
    with multiple return paths (e.g. ``Replica.drain``)."""

    __slots__ = ("obj", "site")

    def __init__(self, obj, site: str) -> None:
        self.obj = obj
        self.site = site

    def __enter__(self) -> None:
        enter(self.obj, self.site)

    def __exit__(self, *exc) -> None:
        exit_(self.obj)


# -- lock-held assertions -----------------------------------------------------

class DebugLock:
    """A ``threading.Lock`` that records its owning thread, so guarded
    structures can assert "my lock is held by whoever is mutating me".
    Drop-in for the ``with``/acquire/release surface the framework uses."""

    __slots__ = ("_lock", "_owner", "name")

    def __init__(self, name: str = "lock") -> None:
        self._lock = threading.Lock()
        self._owner = None
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
        return got

    def release(self) -> None:
        self._owner = None
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LockCheckedDict(dict):
    """A dict whose MUTATIONS assert that a :class:`DebugLock` is held by
    the mutating thread.  Reads stay unchecked (lock-free reads of
    at-most-stale values are a documented pattern, see
    ``PipeGraph._backpressured``); it is unlocked *writes* that corrupt."""

    def __init__(self, guard: DebugLock, what: str, *args, **kw) -> None:
        super().__init__(*args, **kw)
        self._guard = guard
        self._what = what

    def _check(self) -> None:
        if not self._guard.held_by_current_thread():
            raise ConcurrencyViolation(
                f"{self._what} mutated by thread "
                f"'{threading.current_thread().name}' without holding "
                f"{self._guard.name} — take the lock around every "
                "mutation (WF_TPU_DEBUG_CONCURRENCY)")

    def __setitem__(self, k, v):
        self._check()
        return super().__setitem__(k, v)

    def __delitem__(self, k):
        self._check()
        return super().__delitem__(k)

    def setdefault(self, k, default=None):
        self._check()
        return super().setdefault(k, default)

    def pop(self, *a):
        self._check()
        return super().pop(*a)

    def popitem(self):
        self._check()
        return super().popitem()

    def update(self, *a, **kw):
        self._check()
        return super().update(*a, **kw)

    def clear(self):
        self._check()
        return super().clear()


class LockCheckedDeque(deque):
    """Deque counterpart of :class:`LockCheckedDict`: reads through dict
    lookups hand out the *mutable container*, so the values stored in a
    guarded dict must enforce the same discipline or the race just moves
    one level down (``pool._slots[n].append(...)`` without the lock)."""

    def __init__(self, guard: DebugLock, what: str, *args) -> None:
        super().__init__(*args)
        self._guard = guard
        self._what = what

    def _check(self) -> None:
        if not self._guard.held_by_current_thread():
            raise ConcurrencyViolation(
                f"{self._what} mutated by thread "
                f"'{threading.current_thread().name}' without holding "
                f"{self._guard.name} — take the lock around every "
                "mutation (WF_TPU_DEBUG_CONCURRENCY)")

    def append(self, x):
        self._check()
        return super().append(x)

    def appendleft(self, x):
        self._check()
        return super().appendleft(x)

    def extend(self, it):
        self._check()
        return super().extend(it)

    def pop(self):
        self._check()
        return super().pop()

    def popleft(self):
        self._check()
        return super().popleft()

    def remove(self, x):
        self._check()
        return super().remove(x)

    def clear(self):
        self._check()
        return super().clear()
