"""Pre-flight graph checker: abstract evaluation of a whole PipeGraph.

WindFlow rejects illegal pipeline compositions at C++ compile time through
template/concept checks; a Python/JAX graph has no compiler seam, so shape
and dtype mistakes historically surfaced only when a batch hit the device
mid-run (deep in ``ops/tpu.py`` or ``windows/ffat_tpu.py``) — and only the
FIRST one.  This module walks the *built-but-not-started* graph and reports
**every** violation it can prove, with zero device work:

* operator chains are abstractly evaluated with ``jax.eval_shape`` on the
  user kernels (DrJAX idiom: abstract evaluation type-checks the dataflow
  without touching an accelerator) — dtype/shape mismatches, non-boolean
  filter predicates, combiner contract drift, non-integer key extractors;
* window specs are checked for length/slide/lateness consistency;
* keyby routing, mesh shard-divisibility (``parallel/mesh.py`` contracts)
  and fixed-capacity merge consistency are validated structurally;
* watermark modes are folded across merge/split points
  (``graph/multipipe.py``): a branch that can never produce watermarks
  stalls every time window downstream of the merge.

Entry point: :func:`check_graph`, surfaced as ``PipeGraph.check()`` and
auto-run at ``start()`` under ``Config.preflight`` ("error" | "warn" |
"off").  Abstract record specs flow from sources: declared via
``Source_Builder.withRecordSpec(example)`` or inferred from a
``DeviceSource``'s traced generator; chains fed by undeclared sources skip
the kernel passes (structure/spec checks still run).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from windflow_tpu.analysis.diagnostics import Diagnostic
from windflow_tpu.basic import (RoutingMode, TimePolicy, WindFlowError,
                                WinType)

#: sentinel for "record structure unknown at this point of the chain"
_UNKNOWN = None


# ---------------------------------------------------------------------------
# record specs
# ---------------------------------------------------------------------------

def _as_struct(example):
    """An example record (pytree of scalars/arrays) or a pytree of
    ``jax.ShapeDtypeStruct`` -> per-record abstract spec.  Host numpy
    only — never touches a device."""
    import jax

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        a = np.asarray(x)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return jax.tree.map(leaf, example)


def _batched(spec, capacity: int):
    """Per-record spec -> batch spec (leading dim = capacity)."""
    import jax
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((capacity,) + tuple(s.shape),
                                       s.dtype), spec)


def _same_struct(a, b) -> bool:
    import jax
    return jax.tree.structure(a) == jax.tree.structure(b)


def _leaf_mismatch(want, got) -> Optional[str]:
    """First leaf whose shape/dtype drifts between two same-structure
    specs, rendered for the message; None when they agree."""
    import jax
    in_leaves, _ = jax.tree_util.tree_flatten_with_path(want)
    out_leaves = jax.tree.leaves(got)
    for (path, a), b in zip(in_leaves, out_leaves):
        if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
            return (f"field {jax.tree_util.keystr(path) or '.'} is "
                    f"{tuple(a.shape)}/{a.dtype} in the records but came "
                    f"back {tuple(b.shape)}/{b.dtype}")
    return None


# ---------------------------------------------------------------------------
# graph structure helpers (shared with PipeGraph._build)
# ---------------------------------------------------------------------------

def _upstream_map(edges) -> Dict[int, Tuple[Any, list]]:
    """id(op) -> (op, [upstream ops]) over every graph edge, including
    split fan-outs (same traversal as ``PipeGraph._check_fixed_capacity_ops``
    used before it moved here)."""
    upstreams: Dict[int, Tuple[Any, list]] = {}
    for edge in edges:
        if edge[0] == "op":
            _, a, b = edge
            upstreams.setdefault(id(b), (b, []))[1].append(a)
        else:  # split: each child's head is fed by the split source
            _, mp = edge
            src_op = mp.operators[-1]
            for child in mp.split_children:
                if child.operators:
                    head = child.operators[0]
                    upstreams.setdefault(id(head), (head, []))[1].append(
                        src_op)
    return upstreams


def _effective_caps(op, upstreams, seen=None) -> set:
    """Batch capacities a device batch can arrive with at ``op``: host
    operators stamp their ``output_batch_size``; TPU operators pass their
    input capacity through."""
    seen = seen or set()
    if id(op) in seen:
        return set()
    seen.add(id(op))
    if not op.is_tpu:
        return {op.output_batch_size}
    caps = set()
    for up in upstreams.get(id(op), (None, []))[1]:
        caps |= _effective_caps(up, upstreams, seen)
    return caps


def capacity_conflicts(graph, upstreams=None) -> List[Tuple[Any, str, set]]:
    """Fixed-capacity device operators fed by upstream paths delivering
    unequal batch capacities: ``[(op, label, caps), ...]``.  Shared by the
    pre-flight pass (code WF403) and ``PipeGraph._build``'s
    ``preflight="off"`` backstop; ``upstreams`` lets check_graph reuse
    the map it already built."""
    if upstreams is None:
        upstreams = _upstream_map(graph._edges())
    out = []
    for _, (op, ups) in upstreams.items():
        label = op.fixed_capacity_label
        if label is not None:
            caps = set()
            for up in ups:
                caps |= _effective_caps(up, upstreams)
            if len(caps) > 1:
                out.append((op, label, caps))
    return out


def _all_ops(graph) -> list:
    seen, out = set(), []
    for mp in graph._all_pipes():
        for op in mp.operators:
            if id(op) not in seen:
                seen.add(id(op))
                out.append(op)
    return out


# ---------------------------------------------------------------------------
# the passes
# ---------------------------------------------------------------------------

def check_graph(graph) -> List[Diagnostic]:
    """Run every pre-flight pass over an unstarted PipeGraph and return
    the full list of diagnostics (errors AND warnings — never just the
    first).  Performs no device work: the kernel pass is pure
    ``jax.eval_shape`` abstract evaluation."""
    diags: List[Diagnostic] = []
    try:
        edges = graph._edges()
    except WindFlowError as e:
        diags.append(Diagnostic("WF304", str(e)))
        return diags
    except IndexError:
        # merged MultiPipe with no operators yet: _edges() indexes
        # merged.operators[0] — report it instead of crashing the
        # diagnostic API that exists to explain malformed compositions
        diags.append(Diagnostic(
            "WF304",
            "a merged MultiPipe has no operators — add an operator (and "
            "a sink) to the merge result before running"))
        return diags
    ops = _all_ops(graph)
    upstreams = _upstream_map(edges)

    _structural_pass(graph, ops, edges, diags)
    _window_spec_pass(ops, diags)
    _capacity_pass(graph, upstreams, diags)
    _mesh_pass(graph, ops, edges, diags)
    _compaction_pass(graph, ops, diags)
    _watermark_pass(graph, ops, upstreams, diags)
    _durability_pass(graph, ops, diags)
    _kernel_pass(graph, ops, edges, upstreams, diags)
    _wire_pass(graph, ops, edges, upstreams, diags)
    _pallas_pass(graph, ops, diags)
    _megastep_pass(graph, ops, edges, upstreams, diags)
    _tracecheck_pass(graph, diags)
    _ir_audit_pass(graph, diags)
    return diags


def _pallas_pass(graph, ops, diags) -> None:
    """WF607: forced Pallas kernels (``WF_TPU_PALLAS=1``) name their
    downgrades instead of taking them silently — the WF606 contract
    applied to the kernel plane.  Two cases:

    * the runtime backend has no kernel lowering (neither TPU Mosaic
      nor the CPU interpreter): the whole plane downgrades to lax;
    * a MESH graph: the sharded program factories (parallel/mesh.py)
      compose their steps inside shard_map, which keeps the lax bodies
      this round — forcing the kernels there does nothing;
    * an FFAT window with a GENERIC traced combiner (no declared
      sum/max/min monoid): the MXU pane-combine path only exists for
      declared monoids, so the sliding fold keeps the lax body (the
      grouping kernel still applies).

    ``auto`` mode picks per backend silently and never warns."""
    from windflow_tpu.kernels import pallas_forced, resolve_pallas
    if not pallas_forced(graph.config):
        return
    if graph.config.mesh is not None:
        diags.append(Diagnostic(
            "WF607",
            "WF_TPU_PALLAS=1 forced on a mesh graph: sharded programs "
            "(shard_map step factories) keep the lax bodies this "
            "round, so no kernels build",
            hint="single-chip graphs take the kernels; kernels inside "
                 "shard_map are a future round (docs/PERF.md round "
                 "14)"))
        return
    mode = resolve_pallas(graph.config)
    if mode is None:
        import jax as _jax
        diags.append(Diagnostic(
            "WF607",
            "WF_TPU_PALLAS=1 forced but backend "
            f"'{_jax.default_backend()}' has no kernel lowering "
            "(TPU compiles Mosaic, CPU runs interpret=True): the lax "
            "path runs instead",
            hint="unset WF_TPU_PALLAS (auto picks per backend) or run "
                 "on a TPU/CPU backend"))
        return
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    for op in ops:
        if isinstance(op, FfatWindowsTPU) and op.monoid is None:
            diags.append(Diagnostic(
                "WF607",
                f"window '{op.name}' has a generic traced combiner: "
                "the MXU pane-combine kernel only exists for declared "
                "sum/max/min monoids, so its sliding fold keeps the "
                "lax body (the grouping kernel still applies)",
                node=op.name,
                hint="declare the combiner with withMonoidCombiner/"
                     "withSumCombiner if it is a leafwise monoid"))


def _megastep_pass(graph, ops, edges, upstreams, diags) -> None:
    """WF608: a FORCED megastep width (``WF_TPU_MEGASTEP=K`` /
    ``Config.megastep_sweeps > 1``) names its downgrades instead of
    taking them silently — the WF606/WF607 contract applied to the
    megastep plane.  The fold only exists for a single-destination
    host→device staging edge whose post-fusion tail steps entirely on
    device (windflow_tpu/megastep.py ``tail_kind`` — the same
    classifier ``attach_plane`` consults at build time, so preflight
    and runtime can never disagree about a reason).  Named cases:

    * a MESH graph (aligned per-shard ingest, collectives per batch);
    * a multi-destination staging edge (keyed/round-robin fan-out);
    * a host operator, host-interning stateful, compacted key space,
      or parallel tail — ``tail_kind``'s reason verbatim;
    * a spec-less source: packed signatures drift batch to batch, so
      a K-group never assembles (declare withRecordSpec).

    ``auto`` mode picks per backend silently and never warns; every
    case above runs correctly at the per-batch (K=1) cadence."""
    from windflow_tpu.fusion.executor import _is_stateless
    from windflow_tpu.io.device_source import DeviceSource
    from windflow_tpu.megastep import megastep_forced, tail_kind
    from windflow_tpu.ops.sink import Sink

    k = megastep_forced(graph.config)
    if not k:
        return
    if graph.config.mesh is not None:
        diags.append(Diagnostic(
            "WF608",
            f"WF_TPU_MEGASTEP={k} forced on a mesh graph: staging is "
            "per-shard aligned ingest with collectives every batch, so "
            "every edge keeps the per-batch (K=1) cadence",
            hint="single-chip graphs take the fold; scanning sharded "
                 "programs is a future round (docs/PERF.md round 15)"))
        return

    down: Dict[int, list] = {}
    roots = []
    for edge in edges:
        if edge[0] == "op":
            _, a, b = edge
            down.setdefault(id(a), []).append(b)
        else:
            _, mp = edge
            src = mp.operators[-1]
            for child in mp.split_children:
                if child.operators:
                    down.setdefault(id(src), []).append(
                        child.operators[0])
    for op in ops:
        ups = upstreams.get(id(op))
        if (ups is None or not ups[1]) and down.get(id(op)):
            roots.append(op)

    def warn(src, reason: str, node=None) -> None:
        diags.append(Diagnostic(
            "WF608",
            f"WF_TPU_MEGASTEP={k} forced but the staging edge from "
            f"'{src.name}' keeps per-batch dispatch: {reason}",
            node=node,
            hint="the downgrade is correctness-neutral (the per-batch "
                 "path is the reference semantics); unset "
                 "WF_TPU_MEGASTEP or restructure the edge to a "
                 "single-destination device tail (docs/PERF.md round "
                 "15)"))

    for src in roots:
        if getattr(src, "record_spec", None) is None and not (
                isinstance(src, DeviceSource)
                and src.batch_fn is not None):
            warn(src, "the source declares/infers no record spec, so "
                      "packed batch signatures can drift and a K-group "
                      "never assembles (declare withRecordSpec)",
                 node=src.name)
            continue
        tail = src
        while True:
            dests = down.get(id(tail), [])
            if len(dests) != 1:
                warn(src, "multi-destination staging edge "
                          "(keyed/round-robin fan-out ships per batch)",
                     node=tail.name)
                tail = None
                break
            tail = dests[0]
            if not (_is_stateless(tail) and getattr(tail, "is_tpu",
                                                    False)):
                break
        if tail is None or isinstance(tail, Sink):
            # an all-stateless run ending at the sink has no stateful
            # step to carry — tail_kind's fused-segment reason applies,
            # but only once the chain actually fused; stay quiet here
            continue
        if getattr(tail, "parallelism", 1) != 1 \
                and not isinstance(tail, _ffat_type()):
            warn(src, "parallel tail (per-replica state shards the "
                      "scan carry)", node=tail.name)
            continue
        if _will_compact(graph.config, tail):
            # the compactor only attaches at build time (parallel/
            # compaction.attach_compaction), so tail_kind cannot see it
            # on an unstarted graph — predict it from the same criteria
            warn(src, "compacted key space (host admission runs per "
                      "batch; Config.key_compaction=False folds this "
                      "edge)", node=tail.name)
            continue
        kind, reason = tail_kind(tail)
        if kind is None:
            warn(src, reason, node=tail.name)


def _ffat_type():
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    return FfatWindowsTPU


def _will_compact(config, op) -> bool:
    """Predict whether ``attach_compaction`` will hang a KeyCompactor on
    ``op`` at build time — the single-chip criteria of
    ``parallel/compaction.attach_compaction`` restated over the
    unstarted graph (mesh graphs never reach here: the megastep pass
    returns on them first)."""
    if not getattr(config, "key_compaction", True):
        return False
    from windflow_tpu.ops.tpu import ReduceTPU
    if isinstance(op, ReduceTPU):
        return op.key_extractor is not None and op.monoid is not None
    if isinstance(op, _ffat_type()):
        return op.key_extractor is not None and op.max_keys is None
    return False


def _wire_pass(graph, ops, edges, upstreams, diags) -> None:
    """WF606: wire compression (windflow_tpu/wire.py) engages only on
    staging edges whose record spec is declared/inferred — codec choice
    needs the lane semantics.  With ``Config.wire_compression`` on, a
    spec-less host→TPU edge gets a NAMED warning and the documented
    raw-passthrough downgrade instead of a silent one.  Mesh graphs are
    exempt: their staging is per-shard assembly, never the packed wire
    path."""
    from windflow_tpu.wire import wire_enabled
    if not wire_enabled(graph.config) or graph.config.mesh is not None:
        return
    try:
        in_specs, _ = propagate_specs(graph, ops=ops, edges=edges,
                                      upstreams=upstreams)
    except Exception:  # noqa: BLE001 - lint: broad-except-ok (abstract
        # eval of arbitrary user kernels; an internal failure must not
        # add spurious WF606s on top of the kernel pass's real findings)
        return
    seen = set()

    def specless_source_upstream(op, visited) -> bool:
        """True when some SOURCE feeding ``op`` declares/infers no
        record spec — the WF606 case.  A spec that is merely ambiguous
        (merge structure drift) is WF106's finding, not a new one."""
        if id(op) in visited:
            return False
        visited.add(id(op))
        ups = upstreams.get(id(op))
        if ups is None or not ups[1]:   # a root: source-like
            return source_spec(op) is _UNKNOWN
        return any(specless_source_upstream(u, visited) for u in ups[1])

    def source_spec(op):
        if getattr(op, "record_spec", None) is not None:
            return object()     # declared (well-formedness is WF101's)
        from windflow_tpu.io.device_source import DeviceSource
        if isinstance(op, DeviceSource) and op.batch_fn is not None:
            return object()     # inferred from batch_fn
        return _UNKNOWN

    def note(a, b) -> None:
        spec = in_specs.get(id(b))
        if spec is not None and spec is not _UNKNOWN:
            return
        if not specless_source_upstream(b, set()):
            return
        if (id(a), id(b)) in seen:
            return
        seen.add((id(a), id(b)))
        diags.append(Diagnostic(
            "WF606",
            f"staging edge '{a.name}' → '{b.name}' has no "
            "declared/inferred record spec: wire compression "
            "(Config.wire_compression) downgrades to raw passthrough "
            "on this edge",
            node=b.name,
            hint="declare the stream's record shape with "
                 "Source_Builder.withRecordSpec(example); DeviceSource "
                 "infers its spec from batch_fn"))

    for edge in edges:
        if edge[0] == "op":
            _, a, b = edge
            if b.is_tpu and not a.is_tpu:
                note(a, b)
        else:
            _, mp = edge
            src = mp.operators[-1]
            for child in mp.split_children:
                if child.operators and child.operators[0].is_tpu \
                        and not src.is_tpu:
                    note(src, child.operators[0])


def _tracecheck_pass(graph, diags) -> None:
    """wfverify (analysis/tracecheck.py): object-level trace-safety /
    recompile / donation / determinism verification of the live kernel
    objects.  Guarded: a verifier bug must degrade to 'unchecked', never
    block a run the runtime itself would have accepted."""
    try:
        from windflow_tpu.analysis.tracecheck import verify_graph
        report = verify_graph(graph)
        graph._tracecheck_report = report
        diags.extend(report.diagnostics)
    except Exception as e:  # noqa: BLE001 - lint: broad-except-ok (the
        # verifier inspects arbitrary user sources; any internal failure
        # degrades to a note instead of masking the preflight result)
        diags.append(Diagnostic(
            "WF800", f"wfverify pass failed internally and was skipped "
                     f"— {type(e).__name__}: {e}"[:300],
            severity="warning"))


def _ir_audit_pass(graph, diags) -> None:
    """wfir (analysis/ir_audit.py): WF9xx audit of the lowered StableHLO
    of every program — captured lowerings from the compile watcher's
    store plus a dry lower of the user kernels over the record specs
    when the graph has not compiled yet.  Guarded like wfverify: an
    auditor bug degrades to WF900 'unchecked', never blocks a run."""
    try:
        from windflow_tpu.analysis import ir_audit
        if not ir_audit.enabled(getattr(graph, "config", None)):
            return
        report = ir_audit.audit_graph(graph)
        graph._ir_audit_report = report
        diags.extend(report.diagnostics)
    except Exception as e:  # noqa: BLE001 - lint: broad-except-ok (the
        # auditor parses backend-emitted IR text; any internal failure
        # degrades to a note instead of masking the preflight result)
        diags.append(Diagnostic(
            "WF900", f"ir-audit pass failed internally and was skipped "
                     f"— {type(e).__name__}: {e}"[:300],
            severity="warning"))


def _durability_pass(graph, ops, diags) -> None:
    """WF6xx: with checkpointing enabled (Config.durability names a
    directory), warn about graph elements that undermine the restore
    contract — sources whose replay is not deterministic (WF601: a
    generator restarts from scratch; an INGRESS device source re-stamps
    wall-clock time) and operators whose cross-batch state the plane
    cannot snapshot yet (WF603: host window engines, persistent-DB
    suites).  docs/DURABILITY.md spells out the contract each warning
    points at."""
    if not getattr(graph.config, "durability", ""):
        return
    from windflow_tpu.io.device_source import DeviceSource
    from windflow_tpu.kafka.kafka_source import KafkaSource
    from windflow_tpu.ops.source import Source
    on_mesh = graph.config.mesh is not None
    # on a mesh the same gaps also block rescale-on-restore: state the
    # checkpoint never captured (or a replay that diverges) cannot be
    # re-bucketed onto a different shard shape either
    mesh_tail = (" — on a mesh this also makes the operator "
                 "rescale-incompatible (restore on N±1 shards replays "
                 "through the checkpoint)") if on_mesh else ""
    for op in ops:
        if isinstance(op, Source):
            if isinstance(op, KafkaSource):
                continue    # offset-addressed: the replayable case
            if isinstance(op, DeviceSource) and op.ts_fn is not None:
                continue    # EVENT-time device source: pure fn of the
                #             batch index, replays bit-identically
            diags.append(Diagnostic(
                "WF601",
                f"source '{op.name}' cannot replay deterministically "
                "after a restore (no offsets to seek, "
                "wall-clock/ingress timestamps re-stamp on replay) — "
                "restored runs will diverge from the checkpointed "
                "stream position" + mesh_tail,
                node=op.name,
                hint="feed checkpointed graphs from a Kafka source or "
                     "an EVENT-time DeviceSource (withTimestampFn / "
                     "withTimestampBounds)"))
        elif op.checkpoint_opaque:
            diags.append(Diagnostic(
                "WF603",
                f"operator '{op.name}' ({type(op).__name__}) holds "
                "cross-batch state the checkpoint cannot capture — a "
                "restore silently resets it" + mesh_tail,
                node=op.name,
                hint="use the TPU window/stateful operators "
                     "(FfatWindowsTPU, StatefulMapTPU, Reduce) for "
                     "checkpointed graphs"))
        elif on_mesh and op.key_extractor is not None \
                and _checkpoints_unrebucketable_state(op):
            # rescale-on-restore re-buckets keyed state through the
            # known state kinds (durability/rebucket.py: dense key
            # spaces, compaction remaps, shared slot tables); a keyed
            # operator checkpointing state of an unknown kind offers no
            # re-bucketing rule, so a shape-changing restore will
            # refuse with WF605
            diags.append(Diagnostic(
                "WF604",
                f"keyed operator '{op.name}' ({type(op).__name__}) on "
                "a mesh checkpoints state with no re-bucketing rule "
                "(no declared key space or compaction remap) — a "
                "restore onto a different mesh shape will refuse with "
                "WF605",
                node=op.name,
                hint="use the built-in keyed operators (FfatWindowsTPU, "
                     "StatefulMapTPU, ReduceTPU, Reduce) for rescalable "
                     "checkpoints, or keep the mesh shape fixed"))


def _checkpoints_unrebucketable_state(op) -> bool:
    """True when the operator overrides ``snapshot_state`` (it
    checkpoints something) but is none of the kinds
    ``durability/rebucket.py`` knows how to re-bucket."""
    from windflow_tpu.ops.base import Operator
    impl = type(op).snapshot_state
    if impl is Operator.snapshot_state:
        return False    # stateless: nothing to re-bucket
    from windflow_tpu.ops.reduce_op import Reduce
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    # identity on the IMPLEMENTATION, not the class: a subclass that
    # overrides snapshot_state checkpoints a kind the re-bucketer has
    # never seen, however familiar its base class is
    known = {Reduce.snapshot_state, ReduceTPU.snapshot_state,
             FfatWindowsTPU.snapshot_state,
             _StatefulTPUBase.snapshot_state}
    return impl not in known


def manifest_conflicts(graph, manifest,
                       allow_rescale: bool = False) -> List[Diagnostic]:
    """WF602: named diff between a composed (possibly unbuilt) graph and
    a checkpoint manifest's topology signature — the gate
    ``PipeGraph.restore()`` runs before touching any state.  Empty list
    means the restore may proceed.

    ``allow_rescale`` (the ``manifest_rescale_plan`` path) exempts the
    two supported shape changes from WF602: a parallelism difference on
    a KEYED non-terminal, non-source operator (restore on N±1 replica
    shards) and the mesh shape recorded in the manifest (restore on
    N±1 chips) — both re-bucket state through
    ``durability/rebucket.py`` instead of refusing."""
    from windflow_tpu.durability.checkpoint import topology_signature
    from windflow_tpu.ops.source import Source
    diags: List[Diagnostic] = []
    want = manifest.get("topology") or []
    ops = graph._topo_operators()
    have = topology_signature(ops)
    if len(want) != len(have):
        diags.append(Diagnostic(
            "WF602",
            f"checkpoint has {len(want)} operator(s), graph has "
            f"{len(have)} — "
            f"checkpoint: {[w['name'] for w in want]}, "
            f"graph: {[h['name'] for h in have]}"))
        return diags
    for i, (w, h) in enumerate(zip(want, have)):
        for field in ("name", "type", "parallelism", "routing",
                      "is_tpu", "record_spec"):
            if w.get(field) == h.get(field):
                continue
            op = ops[i]
            if allow_rescale and field == "parallelism" \
                    and op.key_extractor is not None \
                    and not op.is_terminal \
                    and not isinstance(op, Source):
                continue    # keyed replica rescale: re-bucketable
            hint = ("restore needs the same composition that wrote "
                    "the checkpoint (names, types, parallelism, "
                    "record specs)")
            if field == "parallelism":
                hint += ("; only keyed non-terminal operators may "
                         "change parallelism on a rescale restore")
            diags.append(Diagnostic(
                "WF602",
                f"operator #{i} {field} differs: checkpoint has "
                f"{w.get(field)!r} ('{w.get('name')}'), graph has "
                f"{h.get(field)!r} ('{h.get('name')}')",
                node=h.get("name"), hint=hint))
    return diags


def manifest_rescale_plan(graph, manifest):
    """Restore-time validation with rescale awareness: returns
    ``(diagnostics, rescaled)``.  Blocking diagnostics are WF602
    (genuine topology mismatch) and WF605 (a shape change the state
    cannot re-bucket: an operator of unknown state kind — the static
    half; dynamic refusals like disagreeing TB ring clocks raise
    :class:`~windflow_tpu.durability.rebucket.RescaleError` when the
    blobs are applied).  ``rescaled`` is True when any supported shape
    change (keyed parallelism or mesh shape) is in effect."""
    from windflow_tpu.durability.rebucket import mesh_shape
    diags = manifest_conflicts(graph, manifest, allow_rescale=True)
    want = manifest.get("topology") or []
    ops = graph._topo_operators()
    rescaled = False
    if len(want) == len(ops):
        for i, (w, op) in enumerate(zip(want, ops)):
            if w.get("parallelism") == op.parallelism:
                continue
            rescaled = True
            if _checkpoints_unrebucketable_state(op):
                diags.append(Diagnostic(
                    "WF605",
                    f"operator '{op.name}' ({type(op).__name__}) "
                    f"changes parallelism "
                    f"{w.get('parallelism')} → {op.parallelism} but "
                    "checkpoints state with no re-bucketing rule",
                    node=op.name,
                    hint="restore on the checkpointed shard shape, or "
                         "use the built-in keyed operators"))
    old_mesh = manifest.get("mesh")
    new_mesh = mesh_shape(graph.config.mesh)
    if old_mesh != new_mesh:
        rescaled = True
        for op in ops:
            if op.key_extractor is not None \
                    and _checkpoints_unrebucketable_state(op):
                diags.append(Diagnostic(
                    "WF605",
                    f"mesh shape changes {old_mesh} → {new_mesh} but "
                    f"keyed operator '{op.name}' "
                    f"({type(op).__name__}) checkpoints state with no "
                    "re-bucketing rule",
                    node=op.name,
                    hint="restore on the checkpointed mesh shape, or "
                         "use the built-in keyed operators"))
    return diags, rescaled


def _structural_pass(graph, ops, edges, diags) -> None:
    has_downstream = set()
    for edge in edges:
        if edge[0] == "op":
            _, a, b = edge
            has_downstream.add(id(a))
            if a.is_terminal:
                diags.append(Diagnostic(
                    "WF301",
                    f"operator '{b.name}' is composed downstream of sink "
                    f"'{a.name}' — a sink terminates its pipeline and "
                    "forwards nothing",
                    node=b.name,
                    hint="route the data before the sink (split the pipe) "
                         "or drop the trailing operators"))
        else:
            _, mp = edge
            has_downstream.add(id(mp.operators[-1]))
    for op in ops:
        if not op.is_terminal and id(op) not in has_downstream:
            diags.append(Diagnostic(
                "WF302",
                f"operator '{op.name}' has no downstream consumer — "
                "every MultiPipe must end in a Sink",
                node=op.name, hint="append add_sink(...) to the pipeline"))
        if op.routing == RoutingMode.KEYBY and op.key_extractor is None:
            diags.append(Diagnostic(
                "WF303",
                f"operator '{op.name}' uses KEYBY routing but declares no "
                "key extractor",
                node=op.name, hint="pass withKeyBy(fn) on the builder"))


def _window_spec_pass(ops, diags) -> None:
    from windflow_tpu.windows.engine import WindowSpec
    for op in ops:
        spec = getattr(op, "spec", None)
        if not isinstance(spec, WindowSpec):
            continue
        if spec.win_len <= 0 or spec.slide <= 0:
            diags.append(Diagnostic(
                "WF201",
                f"operator '{op.name}': window length {spec.win_len} / "
                f"slide {spec.slide} must both be positive",
                node=op.name))
            continue   # the remaining spec arithmetic assumes positives
        if spec.slide > spec.win_len:
            diags.append(Diagnostic(
                "WF202",
                f"operator '{op.name}': slide {spec.slide} exceeds window "
                f"length {spec.win_len} — tuples landing in the "
                f"{spec.slide - spec.win_len}-wide gaps belong to no "
                "window (hopping-with-gaps is supported, but a swapped "
                "(length, slide) pair silently drops data)",
                node=op.name,
                hint="use slide <= length unless the gaps are intended"))
        if spec.lateness < 0:
            diags.append(Diagnostic(
                "WF204",
                f"operator '{op.name}': lateness {spec.lateness} is "
                "negative", node=op.name))
        elif spec.lateness > 0 and spec.win_type == WinType.CB:
            diags.append(Diagnostic(
                "WF203",
                f"operator '{op.name}': lateness "
                f"{spec.lateness} declared on a count-based window — "
                "lateness gates time-based windows only and is ignored "
                "here", node=op.name,
                hint="drop withLateness or switch to withTBWindows"))


def _capacity_pass(graph, upstreams, diags) -> None:
    for op, label, caps in capacity_conflicts(graph, upstreams):
        diags.append(Diagnostic(
            "WF403",
            f"'{op.name}' ({label}) compiles for one fixed batch capacity "
            f"but its upstream paths deliver {sorted(caps)}; give the "
            "merged branches equal withOutputBatchSize",
            node=op.name))


def _mesh_pass(graph, ops, edges, diags) -> None:
    mesh = graph.config.mesh
    if mesh is None:
        return
    total = int(math.prod(mesh.devices.shape))
    extents = dict(zip(mesh.axis_names, mesh.devices.shape))
    key_extent = int(extents.get("key", 1))
    # host -> TPU staging edges: the staged batch lays out data-sharded
    # over the whole mesh (DeviceStageEmitter contract)
    for edge in edges:
        if edge[0] != "op":
            continue
        _, a, b = edge
        if b.is_tpu and not a.is_tpu and a.output_batch_size > 0 \
                and a.output_batch_size % total:
            diags.append(Diagnostic(
                "WF401",
                f"staging edge '{a.name}' -> '{b.name}': output batch "
                f"size {a.output_batch_size} not divisible by the mesh's "
                f"{total} devices",
                node=b.name,
                hint=f"pick a withOutputBatchSize that is a multiple of "
                     f"{total}"))
    # key-sharded state spaces (parallel/mesh.py raises the same at
    # compile time; reported here for the whole graph at once)
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    for op in ops:
        if isinstance(op, FfatWindowsTPU) and op.max_keys is None:
            # compacted key space (withCompactedKeys): the remap table
            # is single-chip device state — there is no per-shard slot
            # ownership to shard the pane rings by (the graph build
            # raises the same; reported here before any build work)
            diags.append(Diagnostic(
                "WF402",
                f"operator '{op.name}': compacted key space "
                "(withCompactedKeys) is single-chip; mesh execution "
                "needs a declared dense key space",
                node=op.name,
                hint=f"declare withMaxKeys (a multiple of the key axis "
                     f"{key_extent})"))
        elif isinstance(op, FfatWindowsTPU) and op.max_keys % key_extent:
            diags.append(Diagnostic(
                "WF402",
                f"operator '{op.name}': max_keys {op.max_keys} not "
                f"divisible by key axis {key_extent}",
                node=op.name))
        elif isinstance(op, _StatefulTPUBase) \
                and op.num_key_slots % key_extent:
            diags.append(Diagnostic(
                "WF402",
                f"operator '{op.name}': num_key_slots {op.num_key_slots} "
                f"not divisible by key axis {key_extent}",
                node=op.name))


_MONOID_PRIMS = {"add": "sum", "add_any": "sum", "max": "max", "min": "min"}


def _monoid_comb_mismatches(comb, key_fn, monoid, spec) -> list:
    """Leaves where the user combiner PROVABLY diverges from the declared
    monoid (WF405), found structurally on the comb's jaxpr — abstract
    tracing only, no device work.  Two classes, both zero-false-positive:
    an output leaf passed through from ONE input unchanged (legal only
    for the segment-constant key leaf under an idempotent max/min —
    the blessed ``{"key": a["key"], ...}`` idiom; under "sum" the dense
    scatter ADDS the equal keys), and a leaf combined by a recognized
    monoid primitive of the WRONG kind.  Anything else is inconclusive
    and stays silent — equivalence in general is the user's contract."""
    import jax
    closed = jax.make_jaxpr(comb)(spec, spec)
    jaxpr = closed.jaxpr
    leaves, _ = jax.tree_util.tree_flatten_with_path(spec)
    n = len(leaves)
    if len(jaxpr.invars) != 2 * n or len(jaxpr.outvars) != n:
        return []
    pos = {id(v): i for i, v in enumerate(jaxpr.invars)}
    key_leaf = None
    if key_fn is not None:
        kj = jax.make_jaxpr(key_fn)(spec).jaxpr
        if len(kj.outvars) == 1:
            kpos = {id(v): i for i, v in enumerate(kj.invars)}
            key_leaf = kpos.get(id(kj.outvars[0]))
    made_by = {}
    for eq in jaxpr.eqns:
        for ov in eq.outvars:
            made_by[id(ov)] = eq
    out = []
    for i, (path, _) in enumerate(leaves):
        name = jax.tree_util.keystr(path) or "."
        ov = jaxpr.outvars[i]
        j = pos.get(id(ov))
        if j is not None:
            # passthrough is legal only at the segment-constant key
            # LEAF ITSELF (output i IS the key leaf, copied from the
            # same leaf of either input) under an idempotent kind — a
            # key copied into a VALUE leaf diverges just the same
            if monoid == "sum" or key_leaf is None \
                    or i != key_leaf or j % n != i:
                out.append((name, f"returns input {'ab'[j // n]}'s leaf "
                                  "unchanged"))
            continue
        eq = made_by.get(id(ov))
        if eq is None:
            continue
        kind = _MONOID_PRIMS.get(eq.primitive.name)
        if kind is None or kind == monoid:
            continue
        operands = {pos.get(id(v)) for v in eq.invars}
        if operands == {i, n + i}:
            out.append((name, f"computes leafwise '{kind}'"))
    return out


def _compaction_pass(graph, ops, diags) -> None:
    """Key-compaction advice (parallel/compaction.py, WF404): a keyed
    reduce that DECLARED its key space bounded (``withMaxKeys``) but no
    monoid still runs the sorted segmented path — the dense
    scatter-combine table (and the compacted remap riding it) needs the
    declared-monoid contract.  Declared dense beats compaction: the
    user is one ``withMonoidCombiner`` away from the fast path, so say
    so instead of silently sorting.

    Also WF405: on every specialized stage the declared kind REPLACES
    the combiner (docs/API.md "declared-monoid contract"), so a
    combiner that provably diverges from it leafwise silently changes
    results exactly where the declaration kicks in — newly urgent now
    that key compaction routes UNDECLARED key spaces onto the monoid
    path by default."""
    from windflow_tpu.ops.tpu import ReduceTPU
    in_specs = None
    for op in ops:
        if isinstance(op, ReduceTPU) and op.monoid in _MONOID_PRIMS.values():
            if in_specs is None:
                in_specs = propagate_specs(graph, ops=ops)[0]
            spec = in_specs.get(id(op))
            if spec is None:
                continue
            try:
                bad = _monoid_comb_mismatches(
                    op.comb, op.key_extractor, op.monoid, spec)
            except Exception:  # noqa: BLE001 - lint: broad-except-ok (the
                # probe must never block a run the runtime would accept;
                # exotic-but-correct combiners simply go unchecked)
                bad = []
            for leaf, why in bad:
                diags.append(Diagnostic(
                    "WF405",
                    f"operator '{op.name}': declared "
                    f"withMonoidCombiner(\"{op.monoid}\") but the "
                    f"combiner {why} at record leaf {leaf} — the dense/"
                    "compacted/mesh stages compute the DECLARED "
                    f"'{op.monoid}' there instead, silently diverging "
                    "from the sorted path",
                    node=op.name,
                    hint="make the combiner leafwise "
                         f"'{op.monoid}' on every field (a key leaf may "
                         "pass through under idempotent max/min), or "
                         "drop the declaration to keep the sorted "
                         "path's semantics"))
    for op in ops:
        # mesh reduces are exempt: the sharded step's non-monoid variant
        # runs the dense per-chip partial + gather fold, never the
        # single-chip sorted path this warning prices
        if isinstance(op, ReduceTPU) and op.key_extractor is not None \
                and op.max_keys is not None and op.monoid is None \
                and op.mesh is None:
            diags.append(Diagnostic(
                "WF404",
                f"operator '{op.name}': withMaxKeys({op.max_keys}) "
                "declares a bounded key space but no monoid combiner — "
                "the reduce takes the sorted arbitrary-key path "
                "(BENCH_r05: 3-42x slower than the dense table)",
                node=op.name,
                hint="declare withMonoidCombiner/withSumCombiner for "
                     "the dense fast path; an undeclared key space "
                     "with a monoid still compacts (Config."
                     "key_compaction)"))


def _source_wm_mode(op, time_policy, diags) -> str:
    """Classify how a source advances watermarks: "ingress" (wall clock),
    "event" (data timestamps) or "none" (cannot advance — the stalling
    mode the merge pass hunts).  Unknown Source subclasses (Kafka, user
    sources with custom replicas) are assumed to manage time themselves."""
    from windflow_tpu.io.device_source import DeviceSource
    from windflow_tpu.ops.source import Source, SourceReplica
    if isinstance(op, DeviceSource):
        if time_policy == TimePolicy.EVENT:
            if op.ts_fn is None or op.wm_fn is None:
                diags.append(Diagnostic(
                    "WF501",
                    f"device source '{op.name}': EVENT time policy needs "
                    "both ts_fn (device lane) and wm_fn (host frontier)",
                    node=op.name, hint="use withTimestampFn(ts_fn, wm_fn)"))
                return "none"
            return "event"
        if op.ts_fn is not None:
            diags.append(Diagnostic(
                "WF501",
                f"device source '{op.name}': withTimestampFn requires the "
                "EVENT time policy (INGRESS stamps arrival time itself)",
                node=op.name))
        return "ingress"
    if type(op) is Source or op.replica_class is SourceReplica:
        if time_policy == TimePolicy.EVENT:
            if op.ts_extractor is None:
                diags.append(Diagnostic(
                    "WF501",
                    f"source '{op.name}': EVENT time policy requires a "
                    "timestamp extractor",
                    node=op.name,
                    hint="use withTimestampExtractor(fn) on the builder"))
                return "none"
            return "event"
        return "ingress"
    return "event" if time_policy == TimePolicy.EVENT else "ingress"


def _watermark_pass(graph, ops, upstreams, diags) -> None:
    from windflow_tpu.ops.source import Source
    from windflow_tpu.windows.engine import WindowSpec
    # demand-driven fold over the upstream map (merge-connection edges
    # sort last in _edges(), so a forward sweep would leave everything
    # past a merged pipe's head without modes — same ordering hazard the
    # kernel pass avoids the same way)
    memo: Dict[int, set] = {}

    def modes_of(op, stack=frozenset()):
        if id(op) in memo:
            return memo[id(op)]
        if id(op) in stack:         # defensive: compositions cannot cycle
            return set()
        if isinstance(op, Source):
            m = {_source_wm_mode(op, graph.time_policy, diags)}
        else:
            m = set()
            for up in upstreams.get(id(op), (None, []))[1]:
                m |= modes_of(up, stack | {id(op)})
        memo[id(op)] = m
        return m

    for op in ops:
        modes_of(op)    # classifies every source (WF501) exactly once
    # merge points: the WatermarkCollector min-folds channel watermarks, so
    # one watermark-less parent pins the merged frontier at WM_NONE forever
    for merged in graph._merges:
        if not merged.operators:
            continue
        head = merged.operators[0]
        got = memo.get(id(head), set())
        if len(got) > 1:
            diags.append(Diagnostic(
                "WF502",
                f"merge into '{head.name}' joins branches with mixed "
                f"watermark modes {sorted(got)} — the merged watermark "
                "min-folds over channels, so the least-advancing branch "
                "gates every time window downstream",
                node=head.name,
                hint="give every merged branch the same timestamping "
                     "(all event-timestamped, or all ingress)"))
    # TB windows downstream of a watermark-less branch never fire mid-run
    for op in ops:
        got = memo.get(id(op), set())
        if "none" not in got:
            continue
        spec = getattr(op, "spec", None)
        if isinstance(spec, WindowSpec) and spec.win_type == WinType.TB:
            diags.append(Diagnostic(
                "WF503",
                f"time-based window operator '{op.name}' is fed by a "
                "branch that never advances watermarks — its windows "
                "fire only at end-of-stream",
                node=op.name))


# ---------------------------------------------------------------------------
# abstract kernel evaluation
# ---------------------------------------------------------------------------

def _eval(fn, *specs):
    """``jax.eval_shape`` with the exception surfaced as a string (the
    diagnostic payload); no device work either way."""
    import jax
    try:
        return jax.eval_shape(fn, *specs), None
    except Exception as e:  # noqa: BLE001 - lint: broad-except-ok (user
        # kernels raise arbitrary exception types under abstract eval; the
        # whole point of this pass is to turn ANY of them into a WF101)
        return None, f"{type(e).__name__}: {e}"


def _check_key_extractor(op, spec, diags) -> None:
    if op.key_extractor is None:
        return
    out, err = _eval(op.key_extractor, spec)
    if err is not None:
        diags.append(Diagnostic(
            "WF104",
            f"operator '{op.name}': key extractor failed abstract "
            f"evaluation over the record spec — {err}",
            node=op.name))
        return
    shape = tuple(getattr(out, "shape", ())) if out is not None else ()
    dtype = getattr(out, "dtype", None)
    if shape != () or dtype is None \
            or not np.issubdtype(np.dtype(dtype), np.integer):
        diags.append(Diagnostic(
            "WF104",
            f"operator '{op.name}': key extractor must return an integer "
            f"scalar, got shape {shape} dtype {dtype} — keys are "
            "extracted inside the compiled program and index dense key "
            "tables",
            node=op.name,
            hint="return an int field (cast with .astype(jnp.int32))"))


def _check_comb(op, one, code, what, diags) -> bool:
    """Combiner must map (rec, rec) -> rec with structure, shapes and
    dtypes preserved — the associativity contract every fold path
    (sort/scan, dense tables, mesh collectives) compiles against."""
    import jax
    out, err = _eval(op.comb, one, one)
    if err is not None:
        diags.append(Diagnostic(
            code,
            f"operator '{op.name}': {what} combiner failed abstract "
            f"evaluation — {err}", node=op.name))
        return False
    if not _same_struct(one, out):
        want = jax.tree.structure(one)
        got = jax.tree.structure(out)
        diags.append(Diagnostic(
            code,
            f"operator '{op.name}': {what} combiner must return the same "
            f"record structure as its inputs (records have {want}, "
            f"combiner returned {got}); carry every field through the "
            "combine", node=op.name))
        return False
    drift = _leaf_mismatch(one, out)
    if drift is not None:
        diags.append(Diagnostic(
            code,
            f"operator '{op.name}': {what} combiner must preserve each "
            f"field's shape and dtype: {drift}", node=op.name))
        return False
    return True


def record_nbytes(spec) -> Optional[int]:
    """Payload bytes of ONE record under an abstract spec (summed leaf
    ``shape x itemsize``) — the declared-record byte model the sweep
    ledger (monitoring/sweep_ledger.py) splits measured HBM traffic
    against.  ``None`` when the spec is unknown."""
    if spec is _UNKNOWN:
        return None
    import jax
    total = 0
    for leaf in jax.tree.leaves(spec):
        n = 1
        for d in getattr(leaf, "shape", ()):
            n *= int(d)
        total += n * np.dtype(leaf.dtype).itemsize
    return total


def _kernel_pass(graph, ops, edges, upstreams, diags) -> None:
    """Diagnostic face of :func:`propagate_specs` (the WF1xx codes)."""
    propagate_specs(graph, ops=ops, edges=edges, upstreams=upstreams,
                    diags=diags)


def propagate_specs(graph, ops=None, edges=None, upstreams=None,
                    diags=None) -> Tuple[Dict[int, Any], Dict[int, Any]]:
    """Propagate abstract record specs from the sources through every
    chain, eval-shaping each user kernel where a spec is known.  Returns
    ``(in_specs, out_specs)``, both keyed by ``id(op)`` with ``None``
    marking "unknown at this point of the chain".

    This is THE shared graph walk: the pre-flight kernel pass appends
    its WF1xx diagnostics through ``diags``; the sweep ledger and the
    fusion advisor (analysis/fusion.py) call it with ``diags`` defaulted
    to a throwaway list just for the per-op record specs."""
    if diags is None:
        diags = []
    if edges is None:
        edges = graph._edges()
    if ops is None:
        ops = _all_ops(graph)
    if upstreams is None:
        upstreams = _upstream_map(edges)
    import jax
    from windflow_tpu.io.device_source import DeviceSource
    from windflow_tpu.ops.chained import ChainedTPU
    from windflow_tpu.ops.filter_op import Filter
    from windflow_tpu.ops.source import Source
    from windflow_tpu.ops.tpu import FilterTPU, MapTPU, ReduceTPU
    from windflow_tpu.ops.tpu_stateful import (StatefulFilterTPU,
                                               StatefulMapTPU)
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU

    in_spec: Dict[int, Any] = {}

    def cap_of(op) -> int:
        caps = sorted(c for c in _effective_caps(op, upstreams) if c)
        return caps[0] if caps else (graph.config.default_batch_size or 1)

    def source_spec(op):
        if getattr(op, "record_spec", None) is not None:
            try:
                return _as_struct(op.record_spec)
            except Exception as e:  # noqa: BLE001 - lint: broad-except-ok
                # (withRecordSpec takes arbitrary user pytrees; a bad one
                # must degrade to "unknown", never crash the checker)
                diags.append(Diagnostic(
                    "WF101",
                    f"source '{op.name}': withRecordSpec example could "
                    f"not be abstracted — {type(e).__name__}: {e}",
                    node=op.name))
                return _UNKNOWN
        if isinstance(op, DeviceSource) and op.batch_fn is not None:
            out, err = _eval(op.batch_fn,
                             jax.ShapeDtypeStruct((), np.int32))
            if err is None and out is not None:
                # per-record view of the [capacity] batch leaves
                return jax.tree.map(
                    lambda s: jax.ShapeDtypeStruct(tuple(s.shape)[1:],
                                                   s.dtype), out)
        return _UNKNOWN

    def out_spec(op, spec):
        """Abstract output record spec of ``op`` given input ``spec``
        (which may be _UNKNOWN), appending diagnostics for provable
        kernel violations.  Device kernels MUST trace (WF101); host
        functions are best-effort (arbitrary Python degrades to
        unknown, never to an error)."""
        if spec is not _UNKNOWN and op.is_keyed:
            # device-traced integer extractors only: ReduceTPU and FFAT
            # extract keys INSIDE the compiled program; dense-key stateful
            # ops index slot tables directly.  (Interned stateful keys and
            # host keyby extractors may return any hashable — no check.)
            if isinstance(op, (ReduceTPU, FfatWindowsTPU)) \
                    or (isinstance(op, (StatefulMapTPU, StatefulFilterTPU))
                        and op.dense_keys):
                _check_key_extractor(op, spec, diags)
        if isinstance(op, MapTPU):
            if spec is _UNKNOWN:
                return _UNKNOWN
            if op.batch_fn:
                cap = cap_of(op)
                out, err = _eval(op.fn, _batched(spec, cap),
                                 jax.ShapeDtypeStruct((cap,), np.bool_))
                if err is not None:
                    diags.append(Diagnostic(
                        "WF101",
                        f"operator '{op.name}': batch kernel failed "
                        f"abstract evaluation over the incoming record "
                        f"spec — {err}", node=op.name))
                    return _UNKNOWN
                return jax.tree.map(
                    lambda s: jax.ShapeDtypeStruct(tuple(s.shape)[1:],
                                                   s.dtype), out)
            out, err = _eval(op.fn, spec)
            if err is not None:
                diags.append(Diagnostic(
                    "WF101",
                    f"operator '{op.name}': kernel failed abstract "
                    f"evaluation over the incoming record spec — {err}",
                    node=op.name,
                    hint="the record fields/dtypes reaching this operator "
                         "do not match what the kernel expects"))
                return _UNKNOWN
            return out
        if isinstance(op, FilterTPU):
            if spec is _UNKNOWN:
                return _UNKNOWN
            out, err = _eval(op.fn, spec)
            if err is not None:
                diags.append(Diagnostic(
                    "WF101",
                    f"operator '{op.name}': predicate failed abstract "
                    f"evaluation — {err}", node=op.name))
            else:
                shape = tuple(getattr(out, "shape", (-1,)))
                dtype = getattr(out, "dtype", None)
                if shape != () or dtype is None \
                        or np.dtype(dtype) != np.dtype(np.bool_):
                    diags.append(Diagnostic(
                        "WF102",
                        f"operator '{op.name}': predicate must return a "
                        f"boolean scalar, got shape {shape} dtype "
                        f"{dtype} — the validity-mask intersection needs "
                        "a bool lane", node=op.name))
            return spec
        if isinstance(op, ChainedTPU):
            cur = spec
            for kind, fn in op.specs:
                if cur is _UNKNOWN:
                    return _UNKNOWN
                if kind == "map":
                    out, err = _eval(fn, cur)
                    if err is not None:
                        diags.append(Diagnostic(
                            "WF101",
                            f"operator '{op.name}': fused map stage "
                            f"failed abstract evaluation — {err}",
                            node=op.name))
                        return _UNKNOWN
                    cur = out
                elif kind == "batch_map":
                    cap = cap_of(op)
                    out, err = _eval(
                        fn, _batched(cur, cap),
                        jax.ShapeDtypeStruct((cap,), np.bool_))
                    if err is not None:
                        diags.append(Diagnostic(
                            "WF101",
                            f"operator '{op.name}': fused batch-map "
                            f"stage failed abstract evaluation — {err}",
                            node=op.name))
                        return _UNKNOWN
                    cur = jax.tree.map(
                        lambda s: jax.ShapeDtypeStruct(
                            tuple(s.shape)[1:], s.dtype), out)
                else:   # filter
                    out, err = _eval(fn, cur)
                    if err is not None:
                        diags.append(Diagnostic(
                            "WF101",
                            f"operator '{op.name}': fused predicate "
                            f"failed abstract evaluation — {err}",
                            node=op.name))
                    elif tuple(getattr(out, "shape", (-1,))) != () \
                            or np.dtype(out.dtype) != np.dtype(np.bool_):
                        diags.append(Diagnostic(
                            "WF102",
                            f"operator '{op.name}': fused predicate must "
                            "return a boolean scalar, got shape "
                            f"{tuple(getattr(out, 'shape', ()))} dtype "
                            f"{getattr(out, 'dtype', None)}",
                            node=op.name))
            return cur
        if isinstance(op, ReduceTPU):
            if spec is not _UNKNOWN:
                _check_comb(op, spec, "WF103", "reduce", diags)
            return spec
        if isinstance(op, FfatWindowsTPU):
            if spec is not _UNKNOWN:
                agg, err = _eval(op.lift, spec)
                if err is not None:
                    diags.append(Diagnostic(
                        "WF101",
                        f"operator '{op.name}': lift failed abstract "
                        f"evaluation over the incoming record spec — "
                        f"{err}", node=op.name))
                else:
                    _check_ffat_comb(op, agg, diags)
            return _UNKNOWN   # emits window results, not input records
        if isinstance(op, (StatefulMapTPU, StatefulFilterTPU)):
            if spec is not _UNKNOWN and op.assoc is None:
                state = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(
                        tuple(np.shape(a))[1:],
                        np.asarray(a).dtype if not hasattr(a, "dtype")
                        else a.dtype), op._state)
                out, err = _eval(op.fn, spec, state)
                if err is not None:
                    diags.append(Diagnostic(
                        "WF101",
                        f"operator '{op.name}': stateful kernel failed "
                        f"abstract evaluation — {err}", node=op.name))
                    return _UNKNOWN
                if isinstance(op, StatefulMapTPU):
                    try:
                        return out[0]
                    except (TypeError, IndexError):
                        return _UNKNOWN
                return spec
            return spec if isinstance(op, StatefulFilterTPU) else _UNKNOWN
        if isinstance(op, Filter):
            # the predicate is not invoked (host functions may be
            # side-effectful); records pass through unchanged either way
            return spec
        # host Map/FlatMap/Reduce, window engines, sinks, unknown types:
        # arbitrary Python the runtime never traces — calling it here
        # (even abstractly) could fire side effects before the stream
        # runs, so the spec goes unknown instead.  Device kernels above
        # are different: jit traces them at the first batch anyway, so
        # abstract evaluation adds no new execution contract.
        return _UNKNOWN

    # Demand-driven propagation over the upstream map (which already
    # includes merge and split fan-in edges): order-independent, so a
    # merged pipe's internal chain sees the specs its parents deliver
    # even though the merge-connection edges sort last in _edges().
    out_cache: Dict[int, Any] = {}
    visiting: set = set()

    def in_of(op):
        if id(op) in in_spec:
            return in_spec[id(op)]
        spec = _UNKNOWN
        first = True
        for up in upstreams.get(id(op), (None, []))[1]:
            s = out_of(up)
            if first:
                spec, first = s, False
            elif spec is _UNKNOWN or s is _UNKNOWN:
                spec = _UNKNOWN
            else:
                # structure AND leaf shapes/dtypes must agree: a merge of
                # {"v": int32} with {"v": float32} would otherwise be
                # checked against only the first branch
                drift = (f"record structures {jax.tree.structure(spec)} "
                         f"vs {jax.tree.structure(s)}"
                         if not _same_struct(spec, s)
                         else _leaf_mismatch(spec, s))
                if drift is not None:
                    diags.append(Diagnostic(
                        "WF106",
                        f"operator '{op.name}': merged branches deliver "
                        f"different records ({drift}) — downstream "
                        "kernels were checked against neither",
                        node=op.name))
                    spec = _UNKNOWN
        in_spec[id(op)] = spec
        return spec

    def out_of(op):
        if id(op) in out_cache:
            return out_cache[id(op)]
        if id(op) in visiting:      # defensive: compositions cannot cycle
            return _UNKNOWN
        visiting.add(id(op))
        if isinstance(op, Source):
            spec = source_spec(op)
        else:
            spec = out_spec(op, in_of(op))
        visiting.discard(id(op))
        out_cache[id(op)] = spec
        return spec

    for op in ops:
        out_of(op)      # force every operator's kernel checks
        in_of(op)       # ... and materialize every input spec
    return in_spec, out_cache


def _check_ffat_comb(op, agg, diags) -> None:
    """FFAT comb folds *lifted aggregates*: (agg, agg) -> agg with the
    lift's structure preserved (WF105)."""
    import jax
    out, err = _eval(op.comb, agg, agg)
    if err is not None:
        diags.append(Diagnostic(
            "WF105",
            f"operator '{op.name}': window combiner failed abstract "
            f"evaluation over the lifted aggregate — {err}",
            node=op.name))
        return
    if not _same_struct(agg, out):
        diags.append(Diagnostic(
            "WF105",
            f"operator '{op.name}': window combiner must return the "
            f"lift's aggregate structure ({jax.tree.structure(agg)}), "
            f"got {jax.tree.structure(out)}", node=op.name))
        return
    drift = _leaf_mismatch(agg, out)
    if drift is not None:
        diags.append(Diagnostic(
            "WF105",
            f"operator '{op.name}': window combiner must preserve the "
            f"aggregate's shapes and dtypes: {drift}", node=op.name))
