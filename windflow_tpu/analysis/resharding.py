"""Reshard advisor: rank shard imbalance, emit a rebalance plan.

The shard ledger (monitoring/shard_ledger.py) *measures* — per-shard
load, hot-key tables, lag spread; this module *plans*: given a live
``stats()["Shard"]`` section it ranks every keyed operator by imbalance
and emits the concrete rebalance contract a resharding executor
implements — exactly the sweep-ledger → fusion-advisor → fusion-executor
progression of PRs 6/7 (``analysis/fusion.plan`` is the template; a
PR-10 elastic/resharding executor is the consumer).

The plan's unit of work is a **key→shard override**: today every keyed
edge places ``splitmix64(key) % n`` (or ``stable_hash`` on host edges,
or dense key ranges on a mesh); an executor that honors an override map
routes the named keys to their assigned shard *before* falling back to
the hash.  The advisor builds that map greedily from the ledger's
hot-key table — move the hottest known keys off the most loaded shard
onto the least loaded until the projection is balanced — and flags keys
too hot to place anywhere (``split_hot_key``: one key above the mean
per-shard load needs key *splitting* — a partial aggregation tier — not
placement, so the executor must not pretend routing can fix it).

Entry points: :func:`imbalance` (ranked per-op summary) and
:func:`plan` (the executor contract), both consumed by
``tools/wf_shard.py``.
"""

from __future__ import annotations

from typing import List, Optional

#: imbalance ratio (max shard load over mean) below which an operator
#: is considered balanced — no plan entry is emitted for it
DEFAULT_THRESHOLD = 1.25


def imbalance(shard_section: dict) -> List[dict]:
    """Ranked per-operator imbalance summary out of a live
    ``stats()["Shard"]`` section: worst first, keyed operators with a
    measured load only."""
    out = []
    for name, entry in (shard_section.get("per_op") or {}).items():
        load = entry.get("load")
        if not isinstance(load, dict):
            continue
        row = {
            "op": name,
            "parallelism": entry.get("parallelism"),
            "n_shards": load.get("n_shards"),
            "placement": load.get("placement"),
            "basis": load.get("basis"),
            "total_tuples": load.get("total_tuples", 0),
            "loads": load.get("tuples") or [],
            "imbalance_ratio": load.get("imbalance_ratio"),
            "hot_shard": load.get("hot_shard"),
            "hot_keys": load.get("hot_keys") or [],
            "hot_key_share": load.get("hot_key_share"),
            "lag_spread_usec": entry.get("lag_spread_usec"),
        }
        if entry.get("ici"):
            row["ici_bytes_per_tuple"] = \
                entry["ici"].get("ici_bytes_per_tuple")
        out.append(row)
    out.sort(key=lambda r: (r["imbalance_ratio"] or 0.0,
                            r["hot_key_share"] or 0.0), reverse=True)
    return out


def _project(loads: List[int], moves: List[dict]) -> Optional[float]:
    """Imbalance ratio after applying the move list to the load vector."""
    sim = list(loads)
    for m in moves:
        sim[m["from_shard"]] -= m["est_tuples"]
        sim[m["to_shard"]] += m["est_tuples"]
    total = sum(sim)
    if total <= 0 or not sim:
        return None
    return round(max(sim) / (total / len(sim)), 4)


def _rebalance_actions(row: dict, threshold: float) -> List[dict]:
    """Greedy move plan for one operator: shift the hottest KNOWN keys
    off overloaded shards onto the least loaded one until the projection
    balances (or the hot-key table runs dry — the ledger only knows the
    top-K, and an honest plan says what it could not place)."""
    loads = list(row["loads"])
    n = len(loads)
    total = sum(loads)
    if n < 2 or total <= 0:
        return []
    mean = total / n
    actions: List[dict] = []
    moves: List[dict] = []
    sim = list(loads)
    # hottest first; each key is movable once, to the then-coldest shard
    for hk in sorted(row["hot_keys"],
                     key=lambda h: h.get("est_tuples", 0), reverse=True):
        src = hk.get("shard")
        est = hk.get("est_tuples", 0)
        if src is None or not isinstance(src, int) or not est:
            continue
        if est > mean:
            # routing cannot balance a key hotter than a whole shard's
            # fair share: it needs a partial-aggregation split tier
            actions.append({
                "kind": "split_hot_key",
                "key": hk["key"],
                "est_tuples": est,
                "share": hk.get("share"),
                "note": "single key exceeds the mean per-shard load "
                        f"({est} > {mean:.0f}); moving it only moves "
                        "the hot spot — pre-aggregate it across shards",
            })
            continue
        if sim[src] / mean <= threshold:
            continue    # its shard is already within bounds
        dst = min(range(n), key=lambda i: sim[i])
        if dst == src:
            continue
        moves.append({"key": hk["key"], "from_shard": src,
                      "to_shard": dst, "est_tuples": est})
        sim[src] -= est
        sim[dst] += est
    if moves:
        actions.insert(0, {
            "kind": "move_keys",
            "moves": moves,
            # the executor contract: route these keys to the assigned
            # shard BEFORE the hash placement
            "override": {str(m["key"]): m["to_shard"] for m in moves},
            "projected_imbalance_ratio": _project(row["loads"], moves),
        })
    return actions


def rebalance_actions(row: dict, threshold: float = DEFAULT_THRESHOLD
                      ) -> List[dict]:
    """Public form of the per-operator action builder: given one
    :func:`imbalance` row (loads + hot-key table), emit the
    move_keys/split_hot_key actions — used by the reshard executor
    (windflow_tpu/serving) when its delta-window trigger fires before
    the cumulative ratio crosses the plan threshold."""
    return _rebalance_actions(row, threshold)


def plan(shard_section: dict, graph_name: Optional[str] = None,
         threshold: float = DEFAULT_THRESHOLD, top: int = 0) -> dict:
    """The reshard plan (the ``analysis/fusion.plan`` shape): keyed
    operators ranked worst-imbalance first, each with its measured loads
    and the rebalance actions a resharding executor would apply.
    ``threshold`` bounds what counts as imbalanced (max/mean);
    operators at or under it appear with an empty action list only when
    nothing else qualifies."""
    if not isinstance(shard_section, dict) \
            or not shard_section.get("enabled", True):
        return {"graph": graph_name, "threshold": threshold, "ops": []}
    rows = imbalance(shard_section)
    ops = []
    for row in rows:
        r = row.get("imbalance_ratio")
        actionable = isinstance(r, (int, float)) and r > threshold
        entry = dict(row)
        entry["actions"] = _rebalance_actions(row, threshold) \
            if actionable else []
        ops.append(entry)
    if top:
        ops = ops[:top]
    return {
        "graph": graph_name,
        "threshold": threshold,
        "ops": ops,
        "actionable": sum(1 for o in ops if o["actions"]),
    }
