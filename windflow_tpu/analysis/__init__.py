"""Static analysis subsystem: pre-flight graph checking, the wfverify
and wfir verifiers, the ``@hot_path`` lint contract, and the debug-mode
race detector.

Five coordinated passes share one :class:`Diagnostic` record type
(``WFxxx`` code, severity, graph node / file:line, fix hint):

* ``analysis.preflight`` — ``PipeGraph.check()``: abstract evaluation of
  the whole dataflow graph before any device dispatch (auto-run at
  ``start()`` under ``Config.preflight``);
* ``analysis.tracecheck`` — wfverify, the object-level verifier of the
  actual kernel/callback function objects: trace-safety (WF80x),
  recompile hazards (WF81x), donation safety (WF82x), replay
  determinism (WF61x) — folded into ``check()``, standalone as
  ``tools/wf_verify.py``;
* ``analysis.ir_audit`` — wfir, the WF9xx audit of every lowered
  program's StableHLO (collectives vs the aligned-ingest promise,
  host callbacks, 64-bit survivors, dynamic shapes, donation misses,
  D2H syncs, lost Mosaic custom calls) parsed off the compile watcher's
  existing first-compile capture — zero extra compiles; folded into
  ``check()`` as a dry-lower pass, standalone as ``tools/wf_ir.py``;
* ``analysis.hotpath`` — the ``@hot_path`` annotation enforced statically
  by ``tools/wf_lint.py``;
* ``analysis.debug_concurrency`` — ``WF_TPU_DEBUG_CONCURRENCY=1`` runtime
  race detection on the shared mutable structures.

``analysis.fusion`` builds on the pre-flight graph walk: maximal
fusible operator chains + projected savings, the planning layer behind
``tools/wf_advisor.py`` (docs/OBSERVABILITY.md "Sweep ledger & fusion
advisor").

See docs/ANALYSIS.md for the diagnostic code table and contracts.
"""

from windflow_tpu.analysis.debug_concurrency import (ConcurrencyViolation,
                                                     set_enabled)
from windflow_tpu.analysis.diagnostics import CODES, Diagnostic
from windflow_tpu.analysis.hotpath import hot_path


def check_graph(graph):
    """Run every pre-flight pass over an unstarted PipeGraph (lazy import:
    the pass pulls in jax and the operator modules; this package stays
    cheap for the hot-path consumers of ``hot_path``/``ENABLED``)."""
    from windflow_tpu.analysis.preflight import check_graph as _cg
    return _cg(graph)


def verify_graph(graph):
    """Run only the wfverify families over an unstarted PipeGraph and
    return the :class:`~windflow_tpu.analysis.tracecheck.VerifyReport`
    (lazy import, same stance as :func:`check_graph`)."""
    from windflow_tpu.analysis.tracecheck import verify_graph as _vg
    return _vg(graph)


__all__ = ["CODES", "ConcurrencyViolation", "Diagnostic", "check_graph",
           "hot_path", "set_enabled", "verify_graph"]
