"""Tenancy advisor: turn the tenant ledger's attribution into a
scheduler plan.

The tenant ledger (monitoring/tenant_ledger.py) *measures* — per-tenant
HBM/dispatch/byte/ICI attribution across every PipeGraph in the
process, plus the budget state machine; this module *plans*: given a
live ``stats()["Tenant"]`` section it ranks every tenant by budget
pressure and emits the concrete per-tenant action contract PR 20's
tenant scheduler executes — exactly the ledger→advisor→executor
progression of PRs 6/7 (fusion), 9/12 (resharding) and 17/18 (latency
sizing).

The plan's unit of work is a **tenant action**:

``throttle_admission``
    the tenant's OVER_BUDGET verdict is ACTIVE (sustained overage,
    latched) — stop admitting new work before shedding state; the
    throttle factor is the overage ratio rounded up, so admission slows
    at least as fast as the tenant is over.

``rescale_tenant``
    the tenant is over budget (pressure > 1) — shed resident device
    state: ``shed_bytes`` is the concrete overage the scheduler must
    reclaim (smaller window capacity, fewer max keys, or a budget
    renegotiation).

``drain_shards``
    an over-budget tenant whose heaviest op alone holds at least
    ``DRAIN_SHARE`` of the tenant's resident bytes — draining that
    operator's shards first reclaims the most per quiesce (the reshard
    executor's move primitive, applied for memory).

``rebalance_hot_tenant``
    a WITHIN-budget tenant consuming at least ``HOT_SHARE`` of the
    process's decomposed latency while other tenants co-reside — it is
    crowding the mesh without violating its own budget; rebalance its
    placement before its neighbours' SLOs pay for it.

Entry points: :func:`rank` (per-tenant summary, worst pressure first)
and :func:`plan` (the scheduler contract), both consumed by
``tools/wf_tenant.py``.  Pure stdlib — no jax, no numpy — so the CLI
keeps the ``wf_metrics``/``wf_doctor`` scrape-host stance.
"""

from __future__ import annotations

import math
from typing import List, Optional

#: heaviest-op share of the tenant's resident bytes above which the
#: plan names that op's shards as the first thing to drain
DRAIN_SHARE = 0.5

#: latency share above which a within-budget tenant is "hot" enough to
#: rebalance (only with co-resident tenants — a lone tenant owns 100%)
HOT_SHARE = 0.6


def rank(tenant_section: dict) -> List[dict]:
    """Ranked per-tenant summary out of a live ``stats()["Tenant"]``
    section: highest budget pressure first, budget-less tenants last
    (ordered by resident bytes)."""
    out = []
    for name, agg in (tenant_section.get("tenants") or {}).items():
        if not isinstance(agg, dict):
            continue
        budget = agg.get("budget") or {}
        per_op = agg.get("per_op") or {}
        heaviest = agg.get("heaviest_op")
        resident = agg.get("resident_state_bytes") or 0
        h_bytes = 0
        if heaviest and isinstance(per_op.get(heaviest), dict):
            h_bytes = per_op[heaviest].get("resident_bytes") or 0
        out.append({
            "tenant": name,
            "graphs": agg.get("graphs") or [],
            "pressure": budget.get("pressure"),
            "over_budget": bool(budget.get("active")),
            "budget_bytes": budget.get("budget_bytes") or 0,
            "hbm_bytes": resident,
            "heaviest_op": heaviest,
            "heaviest_op_bytes": h_bytes,
            "dispatches": agg.get("dispatches") or 0,
            "compile_ms": agg.get("compile_ms") or 0.0,
            "h2d_bytes": agg.get("h2d_bytes") or 0,
            "d2h_bytes": agg.get("d2h_bytes") or 0,
            "ici_bytes_per_tuple": agg.get("ici_bytes_per_tuple") or 0.0,
            "latency_share": agg.get("latency_share"),
            "verdict": budget.get("verdict") or budget.get("last_verdict"),
        })
    out.sort(key=lambda r: (-(r["pressure"] or -1.0), -r["hbm_bytes"],
                            r["tenant"]))
    return out


def _actions(row: dict, n_tenants: int) -> List[dict]:
    """Tenant actions for one ranked row (deterministic — the golden
    plan the tests pin and the PR-20 scheduler replays)."""
    acts: List[dict] = []
    pressure = row.get("pressure") or 0.0
    over = pressure > 1.0
    if over and row["over_budget"]:
        acts.append({
            "kind": "throttle_admission",
            "factor": int(math.ceil(pressure)),
            "note": f"OVER_BUDGET is latched at {pressure:.2f}x the "
                    f"budget — slow admission by the overage factor "
                    f"before shedding state",
        })
    if over:
        shed = max(0, row["hbm_bytes"] - row["budget_bytes"])
        acts.append({
            "kind": "rescale_tenant",
            "shed_bytes": shed,
            "note": f"resident state {row['hbm_bytes']} B exceeds the "
                    f"{row['budget_bytes']} B budget — shed {shed} B "
                    f"(smaller window capacity / fewer max keys, or "
                    f"renegotiate the budget)",
        })
        if row["hbm_bytes"] > 0 and row.get("heaviest_op") \
                and row["heaviest_op_bytes"] / row["hbm_bytes"] \
                >= DRAIN_SHARE:
            acts.append({
                "kind": "drain_shards",
                "op": row["heaviest_op"],
                "resident_bytes": row["heaviest_op_bytes"],
                "note": f"op '{row['heaviest_op']}' alone holds "
                        f"{row['heaviest_op_bytes']} B of the tenant's "
                        f"{row['hbm_bytes']} B — drain its shards "
                        f"first for the biggest reclaim per quiesce",
            })
    elif n_tenants > 1 and (row.get("latency_share") or 0.0) >= HOT_SHARE:
        acts.append({
            "kind": "rebalance_hot_tenant",
            "latency_share": row["latency_share"],
            "note": f"within budget but consuming "
                    f"{row['latency_share']:.0%} of the process's "
                    f"decomposed latency across {n_tenants} tenants — "
                    f"rebalance placement before neighbours' SLOs pay",
        })
    return acts


def plan(tenant_section: dict, top: int = 0) -> dict:
    """The PR-20 tenant-scheduler contract: ranked tenants, each with
    its actions, plus the process-level reconciliation the CI gate
    checks (``attributed.staged_fraction``)."""
    ranked = rank(tenant_section)
    n = len(ranked)
    tenants = []
    for row in ranked:
        row = dict(row)
        row["actions"] = _actions(row, n)
        tenants.append(row)
    if top:
        tenants = tenants[:top]
    over = [t["tenant"] for t in tenants if t["over_budget"]]
    worst = tenants[0]["pressure"] if tenants else None
    return {
        "advisor": "tenancy/1",
        "tenants_total": n,
        "over_budget_tenants": over,
        "worst_pressure": worst,
        "attributed": tenant_section.get("attributed") or {},
        "actionable": sum(1 for t in tenants if t["actions"]),
        "tenants": tenants,
    }
