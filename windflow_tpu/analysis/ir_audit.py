"""wfir: static audit of the LOWERED StableHLO of every wf_jit program.

The preflight checker (analysis/preflight.py) reasons about the composed
graph abstractly and wfverify (analysis/tracecheck.py) walks the Python
AST of the user kernels — neither ever inspects the module XLA actually
compiles.  The contracts that live *below* the source level — "the
aligned-ingest all_gather disappears", "no host callback hides in a
hot-path program", "the donated carry really aliases its output" — were
enforced only by runtime counters and structural models.  wfir closes
that gap: the compile watcher (monitoring/jit_registry.py) already calls
``Lowered = jit.lower(...)`` once per (op name, signature) for its cost
tables, and this module parses that SAME lowering's StableHLO text —
zero extra compiles, cold path only — into per-program **facts**
(collectives, callback custom calls, wide dtypes, dynamic shapes,
host transfers, aliased outputs, Mosaic custom calls), then interprets
the facts under graph context into the WF9xx diagnostics family
(analysis/diagnostics.py):

* **WF901** cross-chip collective on an edge the aligned-ingest plan
  promised (or would make) collective-free — the static twin of the
  shard ledger's modeled ICI drop;
* **WF902** host callback / infeed-outfeed inside a hot-path program;
* **WF903** f64/i64 surviving into a TPU-targeted program;
* **WF904** dynamic-shape ops (IR twin of wfverify's WF812);
* **WF905** donation miss at IR level: donated operands with zero
  input-output aliasing in the lowered module — cross-validated against
  the sweep ledger's runtime donation-miss counters;
* **WF906** mid-program device<->host transfer (scalar D2H sync);
* **WF907** a Pallas program that lost its Mosaic custom call on a
  compiled backend (the WF607 downgrade, proven on the IR).

Wired three ways like its sibling planes: ``stats()["IR_audit"]`` +
postmortem ``ir_audit.json`` (tools/wf_doctor.py renders it jax-free),
``PipeGraph.check()`` folds :func:`audit_graph` — including a dry-lower
of the user kernels over the preflight record specs — into the
preflight table, and ``tools/wf_ir.py --strict`` audits every shipped
graph in CI.  Kill switch ``Config.ir_audit`` / ``WF_TPU_IR_AUDIT=0``
leaves one flag check on the (already cold) first-compile path; capture
rides the cost-analysis lowering, so ``WF_TPU_COST_ANALYSIS=off`` also
disables it.  Suppression shares wfverify's inline syntax: a
``# wfverify: ok (reason)`` on (or two lines above) the kernel's
``def`` line suppresses that operator's wfir findings, counted in the
report like tracecheck's.

Detectors match on STABLE mnemonics (``stablehlo.all_gather``,
``custom_call @xla_python_cpu_callback``, ``tf.aliasing_output``,
``tpu_custom_call``) with golden-substring fixtures in
``tests/test_ir_audit.py`` pinning them against jaxlib text drift.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

from windflow_tpu.analysis.diagnostics import Diagnostic

#: process-wide kill switch (the registry hook's one flag check);
#: Config.ir_audit gates the per-graph reporting planes on top
ENABLED = os.environ.get("WF_TPU_IR_AUDIT", "1").lower() \
    not in ("0", "", "false", "off")


def enabled(config=None) -> bool:
    """The audit gate: the process switch AND (when a config is given)
    the graph's ``Config.ir_audit``."""
    if not ENABLED:
        return False
    if config is None:
        return True
    return bool(getattr(config, "ir_audit", True))


# ---------------------------------------------------------------------------
# fact extraction from StableHLO text
# ---------------------------------------------------------------------------

#: cross-chip collective mnemonics (stablehlo dialect)
_COLLECTIVES = ("all_gather", "all_reduce", "all_to_all",
                "collective_permute", "reduce_scatter",
                "collective_broadcast")
_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(" + "|".join(_COLLECTIVES) + r")\b")
#: custom_call target spellings (pretty @name form and the explicit
#: call_target_name attribute older/verbose printers emit)
_CUSTOM_CALL_RE = re.compile(
    r'custom_call\s*@(\w+)|call_target_name\s*=\s*"([^"]+)"')
#: a custom_call target that re-enters the host runtime
_CALLBACK_MARKERS = ("callback", "py_func", "host_func")
#: a custom_call target that is a Mosaic (Pallas TPU) kernel
_MOSAIC_MARKERS = ("tpu_custom_call", "mosaic")
#: ops that move data between device and host mid-program
_TRANSFER_RE = re.compile(r"stablehlo\.(send|recv)\b")
_INFEED_RE = re.compile(r"stablehlo\.(infeed|outfeed)\b")
#: dynamic-shape ops + unranked/dynamic dims in tensor types
_DYNAMIC_OP_RE = re.compile(
    r"stablehlo\.(dynamic_reshape|real_dynamic_slice|dynamic_pad|"
    r"dynamic_broadcast_in_dim|dynamic_gather|dynamic_iota|"
    r"dynamic_conv)\b")
_DYNAMIC_DIM_RE = re.compile(r"tensor<\?")
#: wide ELEMENT types of a tensor in a VALUE position: the type
#: signature after the last " : " of an op line (attribute tensors like
#: ``dense<0> : tensor<1xi64>`` live inside attr dicts mid-line, and
#: region-opening lines end "({" with only attribute types in tail)
_WIDE_RE = re.compile(r"tensor<[0-9x?]*?(f64|i64|ui64|c128)>")
#: input-output aliasing attributes jax emits for donated operands
_ALIAS_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")
#: per-collective detail: which devices talk (replica_groups) and how
#: much data moves (the operand tensor) — WF901 classifies with these
_GROUPS_RE = re.compile(r"replica_groups\s*=\s*dense<(\[\[.*?\]\])>")
_TENSOR_RE = re.compile(r"tensor<([0-9x?]*)[a-z]")


def _wide_dtypes(text: str) -> List[str]:
    found = set()
    for line in text.splitlines():
        head = line.lstrip()
        if head.startswith("func.func"):
            sig = line  # arg/result types are inline annotations
        elif line.rstrip().endswith("({"):
            continue  # region op: its type lives on the matching "})"
        elif head.startswith(("%", "return", "})")):
            # the op's own type signature follows the last " : ";
            # attribute tensors (dense<...> : tensor<1xi64>) stay in
            # the attr dict this slices away
            tail = line.rsplit(" : ", 1)
            sig = tail[1] if len(tail) == 2 else ""
        else:
            continue
        for m in _WIDE_RE.finditer(sig):
            found.add(m.group(1))
    return sorted(found)


def _collective_ops(text: str) -> List[dict]:
    """One entry per collective-bearing line: the mnemonic, the parsed
    replica groups (None when unprintable), and the operand element
    count (None when dynamic/unparseable) — the detail
    :func:`cross_key_collectives` classifies WF901 with."""
    out = []
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        entry = {"op": m.group(1), "groups": None, "numel": None}
        gm = _GROUPS_RE.search(line)
        if gm:
            try:
                entry["groups"] = json.loads(gm.group(1).replace(" ", ""))
            except ValueError:
                pass
        sig_line = line
        if line.rstrip().endswith("({"):
            # region-bearing collective (all_reduce / reduce_scatter
            # carry their combiner as a region): the op's own type
            # signature follows the region's closing "})" line — the
            # last " : " of the OPENING line is the replica_groups
            # attribute tensor, not the operand
            for j in range(i + 1, min(i + 64, len(lines))):
                if lines[j].lstrip().startswith("})"):
                    sig_line = lines[j]
                    break
            else:
                sig_line = ""
        sig = sig_line.rsplit(" : ", 1)
        if len(sig) == 2:
            tm = _TENSOR_RE.search(sig[1])
            if tm:
                dims = [d for d in tm.group(1).split("x") if d]
                if "?" not in dims:
                    n = 1
                    for d in dims:
                        n *= int(d)
                    entry["numel"] = n
        out.append(entry)
    return out


def cross_key_collectives(facts: dict, mesh=None) -> List[str]:
    """The collective mnemonics in ``facts`` that move NON-scalar data
    across ``mesh``'s key axis — the traffic aligned ingest eliminates,
    and the only collectives WF901 charges.  Excluded by design: scalar
    counter reduces (the drop-count psum telemetry every layout keeps)
    and within-column data-axis gathers (replica groups whose devices
    all share one key coordinate — aligned ingest shrinks them, never
    removes them).  Unparseable groups/operands classify conservatively
    as crossing."""
    ops = facts.get("collective_ops")
    if ops is None:
        return list(facts.get("collectives") or [])
    key_of = None
    if mesh is not None:
        try:
            import numpy as np
            from windflow_tpu.parallel.mesh import KEY_AXIS
            axis = mesh.axis_names.index(KEY_AXIS)
            key_of = {}
            for idx in np.ndindex(mesh.devices.shape):
                key_of[int(mesh.devices[idx].id)] = idx[axis]
        except Exception:  # lint: broad-except-ok (mesh introspection
            # over arbitrary Mesh objects; an unmappable mesh falls back
            # to the conservative no-coordinate classification)
            key_of = None
    out = set()
    for e in ops:
        numel = e.get("numel")
        if numel is not None and numel <= 1:
            continue
        groups = e.get("groups")
        if key_of is None or groups is None:
            out.add(e["op"])
            continue
        for grp in groups:
            if len({key_of.get(int(d)) for d in grp}) > 1:
                out.add(e["op"])
                break
    return sorted(out)


def extract_facts(text: str, donated_leaves: int = 0,
                  backend: Optional[str] = None) -> dict:
    """Parse one lowered module's StableHLO text into the context-free
    fact record every WF9xx interpretation reads.  Pure string work —
    no jax objects, so the same function runs over golden fixtures."""
    collectives = sorted({m.group(1)
                          for m in _COLLECTIVE_RE.finditer(text)})
    callbacks: List[str] = []
    mosaic_calls = 0
    for m in _CUSTOM_CALL_RE.finditer(text):
        target = (m.group(1) or m.group(2) or "").strip()
        low = target.lower()
        if any(s in low for s in _MOSAIC_MARKERS):
            mosaic_calls += 1
        elif any(s in low for s in _CALLBACK_MARKERS):
            if target not in callbacks:
                callbacks.append(target)
    infeed = sorted({m.group(1) for m in _INFEED_RE.finditer(text)})
    transfers = sorted({m.group(1) for m in _TRANSFER_RE.finditer(text)})
    dynamic = sorted({m.group(1) for m in _DYNAMIC_OP_RE.finditer(text)})
    if _DYNAMIC_DIM_RE.search(text):
        dynamic.append("dynamic_dimension")
    aliased = sum(text.count(marker) for marker in _ALIAS_MARKERS)
    return {
        "backend": backend,
        "collectives": collectives,
        "collective_ops": _collective_ops(text) if collectives else [],
        "callbacks": callbacks + infeed,
        "transfers": transfers,
        "wide_dtypes": _wide_dtypes(text),
        "dynamic": dynamic,
        "mosaic_calls": mosaic_calls,
        "aliased_outputs": aliased,
        "donated_leaves": int(donated_leaves),
    }


# ---------------------------------------------------------------------------
# the process-wide program store (fed by the registry's compile capture)
# ---------------------------------------------------------------------------

#: per-op cap on distinct recorded signatures — a recompile storm must
#: not grow the store unboundedly (the storm has its own tripwire)
MAX_SIGS_PER_OP = 16

_store: Dict[str, Dict[object, dict]] = {}
_store_lock = threading.Lock()


def record_lowered(op_name: str, sig, lowered) -> None:
    """Registry hook (``WfJit._capture_cost``): extract and store the
    facts of one just-lowered program.  Reuses the cost capture's
    ``Lowered`` — calling ``as_text()`` serializes the already-built
    module; nothing here compiles.  Raises propagate to the caller's
    guarded capture path (which warns once per op name)."""
    if not ENABLED:
        return
    import jax
    donated = 0
    try:
        for leaf in jax.tree_util.tree_leaves(lowered.args_info):
            if getattr(leaf, "donated", False):
                donated += 1
    except Exception:  # lint: broad-except-ok (args_info is a stages-API
        # detail that has drifted across jax versions; losing the donated
        # count only disarms WF905 for this program, never the capture)
        donated = 0
    facts = extract_facts(lowered.as_text(), donated_leaves=donated,
                          backend=jax.default_backend())
    with _store_lock:
        progs = _store.setdefault(op_name, {})
        if sig in progs or len(progs) < MAX_SIGS_PER_OP:
            progs[sig] = facts


def store_snapshot() -> Dict[str, List[dict]]:
    """op name -> recorded program facts (copy; tests and the process
    report read this)."""
    with _store_lock:
        return {name: list(progs.values())
                for name, progs in _store.items()}


def reset_store() -> None:
    """Drop every recorded program (tests)."""
    with _store_lock:
        _store.clear()


# ---------------------------------------------------------------------------
# fact -> diagnostic interpretation
# ---------------------------------------------------------------------------

def program_findings(op_name: str, facts: dict, *,
                     promised_collective_free: bool = False,
                     alignable_unaligned: bool = False,
                     expect_mosaic: bool = False,
                     cross_key: Optional[List[str]] = None
                     ) -> List[Diagnostic]:
    """WF9xx diagnostics for ONE program's facts under graph context.
    Context-free checks (WF902-WF906) always run; WF901/WF907 need the
    caller to say what the graph promised.  ``cross_key`` (from
    :func:`cross_key_collectives`) narrows WF901 to the collectives
    that actually cross the key axis; None falls back to every
    collective in the program."""
    out: List[Diagnostic] = []
    backend = facts.get("backend")
    coll = facts.get("collectives") if cross_key is None else cross_key
    if coll and (promised_collective_free or alignable_unaligned):
        what = ", ".join(coll)
        if promised_collective_free:
            msg = (f"program '{op_name}' lowered with cross-chip "
                   f"collective(s) [{what}] on an edge the aligned-"
                   "ingest plan promised collective-free")
            hint = ("the aligned sharded step regressed — the modeled "
                    "ICI drop (shard ledger) no longer holds on the "
                    "compiled IR")
        else:
            msg = (f"program '{op_name}' pays cross-chip collective(s) "
                   f"[{what}] on an edge aligned ingest would make "
                   "collective-free")
            hint = ("enable Config.key_aligned_ingest "
                    "(WF_TPU_KEY_ALIGNED=1) so the consumer takes "
                    "pre-placed lanes instead of the in-program gather")
        out.append(Diagnostic("WF901", msg, node=op_name, hint=hint))
    if facts.get("callbacks"):
        what = ", ".join(facts["callbacks"])
        out.append(Diagnostic(
            "WF902",
            f"program '{op_name}' re-enters the host mid-program: "
            f"callback/infeed custom call(s) [{what}] in the lowered "
            "module",
            node=op_name,
            hint="hot-path programs must stay on device; move the "
                 "callback to a sink/host operator or a sampled "
                 "diagnostic site"))
    if facts.get("wide_dtypes") and backend == "tpu":
        what = ", ".join(facts["wide_dtypes"])
        out.append(Diagnostic(
            "WF903",
            f"program '{op_name}' carries 64-bit values [{what}] on a "
            "TPU backend — past the compiled-dtype gates, these run "
            "emulated or force layout padding",
            node=op_name,
            hint="cast to f32/i32 before staging (the wire plane's "
                 "compiled-dtype gates do this for declared specs)"))
    if facts.get("dynamic"):
        what = ", ".join(facts["dynamic"])
        out.append(Diagnostic(
            "WF904",
            f"program '{op_name}' lowered dynamic-shape op(s) [{what}] "
            "— the compiled twin of a WF812 recompile hazard",
            node=op_name,
            hint="pad to fixed capacity; data-dependent shapes recompile "
                 "per batch or fail to trace on TPU"))
    if facts.get("donated_leaves", 0) > 0 \
            and facts.get("aliased_outputs", 0) == 0:
        out.append(Diagnostic(
            "WF905",
            f"program '{op_name}' declares {facts['donated_leaves']} "
            "donated operand leaf/leaves but the lowered module aliases "
            "none of them to an output — every donated buffer is "
            "copied, not reused",
            node=op_name,
            hint="donation needs matching shape/dtype between the "
                 "donated input and an output; the sweep ledger's "
                 "donation_miss counters show the bytes paid per batch"))
    if facts.get("transfers"):
        what = ", ".join(facts["transfers"])
        out.append(Diagnostic(
            "WF906",
            f"program '{op_name}' contains mid-program device<->host "
            f"transfer op(s) [{what}] — a scalar D2H sync serializes "
            "the dispatch pipeline",
            node=op_name,
            hint="return the scalar with the batch outputs and read it "
                 "at drain time instead"))
    if expect_mosaic and backend == "tpu" \
            and facts.get("mosaic_calls", 0) == 0:
        out.append(Diagnostic(
            "WF907",
            f"program '{op_name}' was built with Pallas kernels "
            "resolved ON but its lowered module contains no Mosaic "
            "custom call — the kernel fell back to interpret/lax on a "
            "compiled backend",
            node=op_name,
            hint="the WF607 downgrade, proven on the IR: check "
                 "Config.pallas_kernels and the kernel support gates "
                 "(windflow_tpu/kernels)"))
    return out


# ---------------------------------------------------------------------------
# graph-level report
# ---------------------------------------------------------------------------

class IRAuditReport:
    """One audit's result: programs audited, WF9xx diagnostics, the
    operators whose programs are not lowered yet, and the pass cost."""

    def __init__(self) -> None:
        self.programs_audited = 0
        self.dry_lowered = 0
        self.findings: List[Diagnostic] = []
        self.suppressed = 0
        self.pending: List[str] = []
        self.check_ms = 0.0
        #: every wf_jit op name claimed by this graph's wrappers —
        #: wf_ir's orphan sweep audits the store entries NO graph claims
        #: (framework programs: staging pack/unpack etc.)
        self.op_names: set = set()

    @property
    def diagnostics(self) -> List[Diagnostic]:
        return self.findings

    def to_json(self) -> dict:
        return {
            "programs_audited": self.programs_audited,
            "dry_lowered": self.dry_lowered,
            "findings": [d.to_json() for d in self.findings],
            "suppressed": self.suppressed,
            "pending": sorted(self.pending),
            "check_ms": round(self.check_ms, 3),
        }


def _graph_ops(graph) -> list:
    seen, out = set(), []
    for mp in graph._all_pipes():
        for op in mp.operators:
            if id(op) not in seen:
                seen.add(id(op))
                out.append(op)
    return out


def _collective_context(graph, op) -> tuple:
    """(promised, alignable_unaligned) for WF901: ``promised`` when the
    aligned-ingest plan stamped this consumer collective-free,
    ``alignable_unaligned`` when the consumer QUALIFIES for aligned
    ingest but runs without it (kill switch / downgrade) — the case
    where a collective in the IR is provably avoidable."""
    if getattr(graph.config, "mesh", None) is None:
        return False, False
    if getattr(op, "_ingest_mode", None) == "aligned":
        return True, False
    try:
        from windflow_tpu.basic import RoutingMode
        from windflow_tpu.parallel.mesh import _aligned_slot_bound
        alignable = (getattr(op, "is_tpu", False)
                     and _aligned_slot_bound(op) is not None
                     and op.routing == RoutingMode.KEYBY
                     and op.parallelism == 1)
    except Exception:  # lint: broad-except-ok (eligibility probes
        # arbitrary operator attrs; an unknown op kind is simply not
        # alignable, never an audit crash)
        alignable = False
    return False, alignable


def _expect_mosaic(op) -> bool:
    """True when this operator's step programs were built with compiled
    (non-interpret) Pallas kernels resolved on — the WF907 expectation.
    Conservative: only the kernel-bearing operator families, and only
    when the resolved mode is Mosaic (never the CPU interpreter)."""
    try:
        from windflow_tpu.kernels import resolve_pallas_for
        from windflow_tpu.ops.tpu import ReduceTPU
        from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
        if not isinstance(op, (FfatWindowsTPU, ReduceTPU)):
            return False
        mode = resolve_pallas_for(op)
        return mode is not None and not mode.interpret
    except Exception:  # lint: broad-except-ok (kernel-plane probe over
        # arbitrary operators; no expectation beats a crashed audit)
        return False


def _suppression_anchor(op):
    """(path, lineno) of the operator's primary user callable, or None —
    the site a ``# wfverify: ok (reason)`` suppresses wfir findings at
    (shared syntax with tracecheck)."""
    import inspect
    for attr in ("fn", "comb", "lift", "key_extractor", "gen_fn"):
        fn = getattr(op, attr, None)
        if not callable(fn):
            continue
        try:
            path = inspect.getsourcefile(fn)
            _, lineno = inspect.getsourcelines(fn)
        except (OSError, TypeError):
            continue
        if path:
            return path, lineno
    return None


def _apply_suppression(op, findings: List[Diagnostic],
                       report: IRAuditReport) -> List[Diagnostic]:
    if not findings:
        return findings
    anchor = _suppression_anchor(op)
    if anchor is None:
        return findings
    try:
        from windflow_tpu.analysis.tracecheck import suppression_at
        state = suppression_at(*anchor)
    except Exception:  # lint: broad-except-ok (suppression lookup reads
        # user source files; unreadable source means no suppression)
        state = None
    if state == "ok":
        report.suppressed += len(findings)
        return []
    return findings


def _op_program_rows(op):
    """(op_name, facts) rows for every program this operator's live
    wrappers have had captured — the sweep ledger's wrapper walk keyed
    into the process store."""
    from windflow_tpu.monitoring.sweep_ledger import _op_wrappers
    rows, missing, names = [], [], set()
    for w in _op_wrappers(op):
        names.add(w.op_name)
        with _store_lock:
            progs = _store.get(w.op_name)
            facts_list = list(progs.values()) if progs else []
        if facts_list:
            for facts in facts_list:
                rows.append((w.op_name, facts))
        elif getattr(w, "dispatches", 0) > 0:
            # this wrapper RAN but the store has no record: its capture
            # failed or was skipped — unaudited, not clean (the registry
            # warned once).  A zero-dispatch wrapper was merely fused
            # away / never exercised and is not pending.
            missing.append(w.op_name)
    return rows, missing, names


def _dry_lower_kernel(op, in_spec, cap: int):
    """Best-effort dry lower of the operator's USER kernel over the
    preflight record spec: ``jax.jit(jax.vmap(fn)).lower(abstract)`` —
    ShapeDtypeStruct args, client-side lowering only, nothing compiles
    and the registry is never touched.  Returns StableHLO text or
    None."""
    import jax
    fn = getattr(op, "fn", None)
    if fn is None or getattr(op, "batch_fn", False):
        return None
    batched = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((cap,) + tuple(s.shape), s.dtype),
        in_spec)
    try:
        return jax.jit(jax.vmap(fn)).lower(batched).as_text()
    except Exception:  # lint: broad-except-ok (the kernel pass already
        # reported un-evaluable kernels as WF101; the dry lower is an
        # extra lens, not a second reporter of the same failure)
        return None


def audit_graph(graph, dry_lower: bool = True) -> IRAuditReport:
    """Audit every program of ``graph``'s operators: captured lowerings
    from the process store (programs the registry compiled for these
    operators' wrappers), plus — for operators whose step programs are
    not built yet — a dry lower of the user kernels over the preflight
    record specs.  Cold path: call at check()/stats/postmortem cadence."""
    t0 = time.perf_counter()
    report = IRAuditReport()
    if not enabled(getattr(graph, "config", None)):
        report.check_ms = (time.perf_counter() - t0) * 1e3
        return report
    import jax
    backend = jax.default_backend()
    mesh = getattr(graph.config, "mesh", None)
    in_specs = None
    for op in _graph_ops(graph):
        promised, alignable = _collective_context(graph, op)
        expect = _expect_mosaic(op)
        rows, missing, names = _op_program_rows(op)
        report.op_names |= names
        findings: List[Diagnostic] = []
        for op_name, facts in rows:
            report.programs_audited += 1
            findings.extend(program_findings(
                op_name, facts, promised_collective_free=promised,
                alignable_unaligned=alignable, expect_mosaic=expect,
                cross_key=cross_key_collectives(facts, mesh)))
        if not rows and getattr(op, "is_tpu", False) and dry_lower:
            # composed-but-unstarted graph: lower the user kernel over
            # the record spec so check() still sees IR before any run
            if in_specs is None:
                from windflow_tpu.analysis.preflight import (_UNKNOWN,
                                                             propagate_specs)
                in_specs, _ = propagate_specs(graph)
                unknown = _UNKNOWN
            spec = in_specs.get(id(op), unknown)
            if spec is not unknown:
                cap = graph.config.default_batch_size or 1
                for up in _graph_ops(graph):
                    if getattr(up, "output_batch_size", 0):
                        cap = up.output_batch_size
                        break
                text = _dry_lower_kernel(op, spec, cap)
                if text is not None:
                    report.dry_lowered += 1
                    report.programs_audited += 1
                    facts = extract_facts(text, backend=backend)
                    findings.extend(program_findings(
                        f"{op.name} (dry-lowered kernel)", facts,
                        promised_collective_free=promised,
                        alignable_unaligned=alignable,
                        cross_key=cross_key_collectives(facts, mesh)))
        if missing and not rows:
            report.pending.append(op.name)
        report.findings.extend(
            _apply_suppression(op, findings, report))
    report.check_ms = (time.perf_counter() - t0) * 1e3
    return report


def audit_orphans(claimed) -> IRAuditReport:
    """Context-free audit of the store entries NO audited graph's
    wrappers claimed — the framework's own programs (staging pack /
    unpack, flush paths of operators fused away).  ``claimed`` is the
    union of :attr:`IRAuditReport.op_names` over the graphs already
    audited; wf_ir runs this sweep last so every program the process
    compiled is covered exactly once."""
    t0 = time.perf_counter()
    report = IRAuditReport()
    if not ENABLED:
        report.check_ms = (time.perf_counter() - t0) * 1e3
        return report
    claimed = set(claimed)
    for op_name, facts_list in sorted(store_snapshot().items()):
        if op_name in claimed:
            continue
        report.op_names.add(op_name)
        for facts in facts_list:
            report.programs_audited += 1
            report.findings.extend(program_findings(op_name, facts))
    report.check_ms = (time.perf_counter() - t0) * 1e3
    return report


def process_report() -> IRAuditReport:
    """Context-free audit of EVERY program captured in this process —
    the bench's "shipped programs audit clean" stat (WF902-WF906 only;
    WF901/WF907 need graph context the process store does not keep)."""
    t0 = time.perf_counter()
    report = IRAuditReport()
    if not ENABLED:
        report.check_ms = (time.perf_counter() - t0) * 1e3
        return report
    for op_name, facts_list in sorted(store_snapshot().items()):
        for facts in facts_list:
            report.programs_audited += 1
            report.findings.extend(program_findings(op_name, facts))
    report.check_ms = (time.perf_counter() - t0) * 1e3
    return report
