"""wfverify: object-level static verifier for kernels and jit sites.

The pre-flight checker (``analysis/preflight.py``) type-checks the
dataflow abstractly; the contracts that actually burn TPU runs — host
sync inside a traced kernel, recompile storms, unsafe buffer donation,
nondeterministic replay — were caught only *after* dispatch, by the
wf_jit watcher's recompile tripwire (PR 4), the sweep ledger's
donation-miss audit (PR 6), and the chaos harness's record diffs
(PR 8).  This module is their static twin: it analyzes the **actual
function objects** handed to the device operators (map/filter/flatmap
kernels, reduce combiners, FFAT lift/comb, key extractors, sink
callbacks) plus the framework's own wf_jit wrapper bodies, via
``inspect`` + AST with closure/``__globals__`` resolution and bounded
call-depth following — before any batch is staged.

Four pass families (codes in ``analysis/diagnostics.py``):

* **trace-safety (WF80x)** — host materialization of traced values
  (``float()``/``int()``/``.item()``/``np.asarray`` on parameters),
  Python ``if``/``while`` branching on traced values, mutation of
  closure/global/default-arg state inside traced code, bare ``print``.
* **recompile hazards (WF81x)** — trace-time reads that can vary per
  call (``len()`` of a mutable closure container, ``next()``, wall
  clock/RNG baked as constants) and data-dependent output shapes
  (``nonzero``/``unique``/one-arg ``where``/boolean-mask indexing).
* **donation safety (WF82x)** — operands handed to a
  ``donate_argnums`` program and read again after the dispatch on any
  path (the donated buffer is dead; XLA may have overwritten it).
* **determinism for replay (WF61x)** — RNG without an explicitly
  threaded key, wall-clock reads, ``id()``/``hash()`` identity, and
  set-iteration-order dependence in kernels and sink callbacks of a
  durability-enabled graph (docs/DURABILITY.md "Determinism
  requirements", mechanized).

Split of responsibilities: ``tools/wf_lint.py`` stays a pure-AST,
jax-free repo-wide lint; wfverify IMPORTS the graph and inspects the
live callables (closures resolved to their current values, donation
read off the real ``WfJit`` wrappers), so it sees exactly the objects
the runtime will trace.  Entry points: :func:`verify_graph` (wired into
``PipeGraph.check()``), :func:`verify_callable` (one function), and the
CLI ``tools/wf_verify.py``.

Inline suppression (mirrors the wf_lint broad-except convention): a
``# wfverify: ok (reason)`` comment on the flagged line or within the
two lines above suppresses the finding; the reason is mandatory — a
bare ``wfverify: ok`` is rejected and the finding reported with a note.
"""

from __future__ import annotations

import ast
import functools
import inspect
import linecache
import os
import re
import time
import types
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from windflow_tpu.analysis.diagnostics import Diagnostic

#: inline suppression token (reason mandatory, in parentheses)
SUPPRESS_TOKEN = "wfverify: ok"
_SUPPRESS_RE = re.compile(r"wfverify:\s*ok\s*\(\s*[^)\s][^)]*\)")

#: bounded interprocedural following: kernels calling helpers calling
#: helpers — beyond this depth the callee is treated as opaque
MAX_CALL_DEPTH = 3

#: attribute reads on a traced value that yield STATIC Python values
#: (legal to branch on / materialize under jit)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "itemsize",
                 "at", "aval", "weak_type", "sharding"}

#: builtins whose result is static even over traced arguments
_STATIC_FNS = {"len", "isinstance", "issubclass", "hasattr", "getattr",
               "callable", "type", "repr", "str", "format", "dir"}

#: receiver roots that are jax-side (materialization-safe: jnp.asarray
#: of a tracer stays abstract)
_JAX_ROOTS = {"jnp", "jax", "lax"}

#: method names that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "add", "discard", "update", "setdefault", "popitem",
             "appendleft", "extendleft", "sort", "reverse"}

#: data-dependent-shape producers (WF812) when fed traced data
_SHAPE_DYNAMIC = {"nonzero", "flatnonzero", "argwhere", "unique",
                  "compress", "extract"}

_WALLCLOCK_TIME_ATTRS = {"time", "time_ns", "monotonic", "monotonic_ns",
                         "perf_counter", "perf_counter_ns", "clock_gettime"}
_WALLCLOCK_DT_ATTRS = {"now", "utcnow", "today"}

_MUTABLE_CONTAINERS = (list, dict, set, bytearray)


# ---------------------------------------------------------------------------
# source / object resolution
# ---------------------------------------------------------------------------

_FILE_CACHE: Dict[str, Optional[Tuple[ast.Module, List[str]]]] = {}


def _file_ast(path: str):
    """Parsed module AST + source lines for a file, cached; None when the
    source is unavailable (builtins, C extensions, REPL frames)."""
    hit = _FILE_CACHE.get(path)
    if hit is not None or path in _FILE_CACHE:
        return hit
    lines = linecache.getlines(path)
    out = None
    if lines:
        try:
            out = (ast.parse("".join(lines), filename=path), lines)
        except SyntaxError:
            out = None
    _FILE_CACHE[path] = out
    return out


def _unwrap(fn):
    fn = inspect.unwrap(fn)
    if isinstance(fn, functools.partial):
        fn = inspect.unwrap(fn.func)
    return fn


def _callable_node(fn) -> Optional[Tuple[ast.AST, str]]:
    """``(function/lambda AST node, file path)`` of a live Python
    function, located by parsing its defining file and matching the code
    object's first line (robust for lambdas inside larger expressions,
    where ``inspect.getsource`` returns unparseable fragments)."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    path = code.co_filename
    parsed = _file_ast(path)
    if parsed is None:
        return None
    tree, _ = parsed
    name = getattr(fn, "__name__", "<lambda>")
    argnames = list(code.co_varnames[:code.co_argcount])
    fallback = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name != name:
                continue
            first = node.decorator_list[0].lineno if node.decorator_list \
                else node.lineno
            if first <= code.co_firstlineno <= node.lineno:
                return node, path
            fallback = fallback or (node, path)
        elif isinstance(node, ast.Lambda) and name == "<lambda>":
            if node.lineno == code.co_firstlineno \
                    and [a.arg for a in node.args.args] == argnames:
                return node, path
    return fallback


class _Env:
    """Name resolution for one function object: closure cells first, then
    ``__globals__``, then builtins — the 'object-level' half of the
    verifier (a closure over an actual ``set`` is provably
    iteration-order dependent; a pure-AST pass could only guess)."""

    def __init__(self, fn) -> None:
        self.closure: Dict[str, Any] = {}
        code = getattr(fn, "__code__", None)
        cells = getattr(fn, "__closure__", None)
        if code is not None and cells:
            for nm, cell in zip(code.co_freevars, cells):
                try:
                    self.closure[nm] = cell.cell_contents
                except ValueError:      # empty cell (still being built)
                    pass
        self.globals = getattr(fn, "__globals__", {}) or {}
        self.free = set(self.closure)

    def resolve(self, name: str) -> Tuple[bool, Any]:
        if name in self.closure:
            return True, self.closure[name]
        if name in self.globals:
            return True, self.globals[name]
        bi = self.globals.get("__builtins__")
        bi = bi.__dict__ if isinstance(bi, types.ModuleType) else (bi or {})
        if isinstance(bi, dict) and name in bi:
            return True, bi[name]
        return False, None

    def resolve_expr(self, node) -> Tuple[bool, Any]:
        """Resolve a Name / dotted-attribute chain to a live object."""
        if isinstance(node, ast.Name):
            return self.resolve(node.id)
        if isinstance(node, ast.Attribute):
            ok, base = self.resolve_expr(node.value)
            if ok:
                try:
                    return True, getattr(base, node.attr)
                except AttributeError:
                    return False, None
        return False, None


def _root_name(node) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _attr_chain(node) -> List[str]:
    """``a.b.c`` -> ["a", "b", "c"]; [] when not a pure dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


# ---------------------------------------------------------------------------
# suppression
# ---------------------------------------------------------------------------

def suppression_at(path: str, lineno: int) -> Optional[str]:
    """``"ok"`` when a justified ``# wfverify: ok (reason)`` covers the
    line (same line or the two above), ``"missing-reason"`` when the
    token is present without a parenthesized reason, else None."""
    lines = linecache.getlines(path)
    window = lines[max(0, lineno - 3):lineno]
    text = "".join(window)
    if SUPPRESS_TOKEN not in text:
        return None
    return "ok" if _SUPPRESS_RE.search(text) else "missing-reason"


# ---------------------------------------------------------------------------
# per-function verification
# ---------------------------------------------------------------------------

class _Finding:
    __slots__ = ("code", "message", "path", "lineno", "hint")

    def __init__(self, code, message, path, lineno, hint=None):
        self.code = code
        self.message = message
        self.path = path
        self.lineno = lineno
        self.hint = hint


class _FnCheck:
    """One function's walk.  ``traced``: the function is jit-traced
    (trace-safety + recompile families apply, parameters are traced
    values); ``durable``: the graph checkpoints (determinism family
    applies).  Findings collect as (code, message, file:line)."""

    def __init__(self, fn, node, path, *, traced: bool, durable: bool,
                 depth: int, findings: List[_Finding],
                 visited: Set[Tuple[Any, bool, bool]]) -> None:
        self.fn = fn
        self.node = node
        self.path = path
        self.traced = traced
        self.durable = durable
        self.depth = depth
        self.findings = findings
        self.visited = visited
        self.env = _Env(fn)
        args = node.args
        names = [a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        self.params = set(names)
        self.tainted: Set[str] = set(names) if traced else set()
        #: params with mutable defaults (shared across calls: mutating
        #: one inside traced code is cross-trace state)
        self.mutable_defaults: Set[str] = set()
        defaults = getattr(fn, "__defaults__", None) or ()
        pos = (args.posonlyargs + args.args)[-len(defaults):] \
            if defaults else []
        for a, d in zip(pos, defaults):
            if isinstance(d, _MUTABLE_CONTAINERS):
                self.mutable_defaults.add(a.arg)
        # local scope: every Store-ed name is local unless declared
        # global/nonlocal (Python scoping) — mutations of NON-locals are
        # the cross-trace state the WF803 pass hunts
        self.declared: Set[str] = set()
        self.locals: Set[str] = set(self.params)
        body = node.body if isinstance(node.body, list) else [node.body]
        for n in ast.walk(node):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                self.declared.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.locals.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.locals.add(n.name)
        self.locals -= self.declared
        #: inner ``def``s, followable when called or passed to jax HOFs
        self.local_defs = {
            n.name: n for n in ast.walk(node)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not node}
        #: (lineno, col) nodes the determinism pass claimed, so the
        #: recompile pass does not double-report the same call
        self._det_hits: Set[Tuple[int, int]] = set()
        self._body = body

    # -- taint ---------------------------------------------------------------
    def expr_tainted(self, e) -> bool:
        if e is None or isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(e.value)
        if isinstance(e, ast.Call):
            fname = e.func.id if isinstance(e.func, ast.Name) else None
            if fname in _STATIC_FNS:
                return False
            if self.expr_tainted(e.func):
                return True
            return any(self.expr_tainted(a) for a in e.args) \
                or any(self.expr_tainted(k.value) for k in e.keywords)
        if isinstance(e, ast.Lambda):
            return False
        for child in ast.iter_child_nodes(e):
            if isinstance(child, (ast.expr, ast.comprehension)) \
                    and self.expr_tainted(child):
                return True
            if isinstance(child, ast.comprehension) \
                    and self.expr_tainted(child.iter):
                return True
        return False

    def _taint_target(self, tgt, is_tainted: bool) -> None:
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                if is_tainted:
                    self.tainted.add(n.id)
                else:
                    self.tainted.discard(n.id)

    # -- findings ------------------------------------------------------------
    def emit(self, code: str, node, message: str,
             hint: Optional[str] = None) -> None:
        self.findings.append(_Finding(
            code, message, self.path, getattr(node, "lineno", 0), hint))

    # -- walk ----------------------------------------------------------------
    def run(self) -> None:
        for stmt in self._body:
            if isinstance(stmt, ast.stmt):
                self._stmt(stmt)
            else:       # lambda body: one bare expression
                self._expr(stmt)

    def _stmt(self, s) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return      # inner defs are analyzed when called/passed
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = s.value
            if value is not None:
                self._expr(value)
            tainted = self.expr_tainted(value) if value is not None \
                else False
            targets = s.targets if isinstance(s, ast.Assign) \
                else [s.target]
            for t in targets:
                self._check_store(t, s)
                if isinstance(s, ast.AugAssign):
                    tainted = tainted or self.expr_tainted(t)
                self._taint_target(t, tainted)
            return
        if isinstance(s, (ast.If, ast.While)):
            self._branch_test(s.test)
            self._expr(s.test)
            for b in s.body:
                self._stmt(b)
            for b in s.orelse:
                self._stmt(b)
            return
        if isinstance(s, ast.Assert):
            self._branch_test(s.test)
            self._expr(s.test)
            return
        if isinstance(s, ast.For):
            self._expr(s.iter)
            self._order_dep(s.iter)
            self._taint_target(s.target, self.expr_tainted(s.iter))
            for b in s.body + s.orelse:
                self._stmt(b)
            return
        if isinstance(s, ast.With):
            for item in s.items:
                self._expr(item.context_expr)
            for b in s.body:
                self._stmt(b)
            return
        if isinstance(s, ast.Try):
            for b in (s.body + s.orelse + s.finalbody):
                self._stmt(b)
            for h in s.handlers:
                for b in h.body:
                    self._stmt(b)
            return
        if isinstance(s, ast.Return) and s.value is not None:
            self._expr(s.value)
            return
        if isinstance(s, ast.Expr):
            self._expr(s.value)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._expr(child)

    # -- stores (WF803: mutation of non-local state) -------------------------
    def _check_store(self, tgt, stmt) -> None:
        if not self.traced:
            return
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store) \
                    and n.id in self.declared:
                self.emit(
                    "WF803", stmt,
                    f"assignment to '{n.id}' (declared global/nonlocal) "
                    "inside a jit-traced kernel — runs at trace time "
                    "only, then never again for cached dispatches",
                    hint="thread state through the function's inputs and "
                         "outputs instead")
            elif isinstance(n, ast.Subscript):
                root = _root_name(n.value)
                if root is not None and root not in self.locals \
                        and isinstance(n.ctx, ast.Store):
                    ok, val = self.env.resolve(root)
                    if ok and isinstance(val, _MUTABLE_CONTAINERS):
                        self.emit(
                            "WF803", stmt,
                            f"subscript write to closure/global "
                            f"'{root}' inside a jit-traced kernel — a "
                            "trace-time side effect, silently skipped "
                            "on cached dispatches",
                            hint="return the value instead of mutating "
                                 "enclosing state")

    # -- branch tests (WF802) ------------------------------------------------
    def _branch_test(self, test) -> None:
        if not self.traced:
            return
        bad = self._violating_test(test)
        if bad is not None:
            self.emit(
                "WF802", bad,
                "Python control flow branches on a traced value — jit "
                "tracing cannot concretize it "
                f"({ast.unparse(bad)[:60]!r})",
                hint="use jnp.where / lax.cond / lax.select, or lift the "
                     "decision to a static argument")

    def _violating_test(self, t):
        if isinstance(t, ast.BoolOp):
            for v in t.values:
                bad = self._violating_test(v)
                if bad is not None:
                    return bad
            return None
        if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
            return self._violating_test(t.operand)
        if isinstance(t, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in t.ops):
                return None     # identity/membership: Python-level checks
        if isinstance(t, ast.Call):
            fname = t.func.id if isinstance(t.func, ast.Name) else None
            if fname in _STATIC_FNS:
                return None
        return t if self.expr_tainted(t) else None

    # -- expressions ---------------------------------------------------------
    def _expr(self, e) -> None:
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._call(node)
            elif isinstance(node, ast.Subscript) and self.traced:
                self._subscript(node)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    self._order_dep(gen.iter)
            elif isinstance(node, ast.IfExp):
                self._branch_test(node.test)

    def _subscript(self, node: ast.Subscript) -> None:
        # boolean-mask indexing: x[mask] with a traced comparison mask
        # changes the output shape per batch content (WF812)
        sl = node.slice
        if isinstance(sl, ast.Compare) and self.expr_tainted(sl) \
                and self.expr_tainted(node.value):
            self.emit(
                "WF812", node,
                "boolean-mask indexing of a traced array "
                f"({ast.unparse(node)[:60]!r}) — the output shape "
                "depends on batch content; jit fails to trace it (or "
                "recompiles per survivor count)",
                hint="keep a fixed shape: jnp.where(mask, x, fill) or a "
                     "validity lane")

    # -- calls: the heart of every family ------------------------------------
    def _call(self, node: ast.Call) -> None:
        func = node.func
        fname = func.id if isinstance(func, ast.Name) else None
        attr = func.attr if isinstance(func, ast.Attribute) else None
        chain = _attr_chain(func) if isinstance(func, ast.Attribute) else []
        resolved, obj = self.env.resolve_expr(func) \
            if isinstance(func, (ast.Name, ast.Attribute)) else (False, None)

        if self.durable:
            self._determinism_call(node, fname, attr, chain, resolved, obj)
        if self.traced:
            self._trace_safety_call(node, fname, attr, chain, resolved, obj)
            self._recompile_call(node, fname, attr, chain, resolved, obj)
        self._maybe_follow(node, fname, resolved, obj)

    # .. trace-safety (WF80x) ................................................
    def _trace_safety_call(self, node, fname, attr, chain, resolved,
                           obj) -> None:
        args_tainted = any(self.expr_tainted(a) for a in node.args)
        if fname in ("float", "int", "bool", "complex") and args_tainted:
            self.emit(
                "WF801", node,
                f"{fname}() materializes a traced value on host — "
                "raises ConcretizationTypeError at the first batch",
                hint="stay in jnp (astype / jnp.asarray) or make the "
                     "value a static argument")
            return
        if attr in ("item", "tolist") \
                and self.expr_tainted(node.func.value):
            self.emit(
                "WF801", node,
                f".{attr}() pulls a traced value to host inside a "
                "jit-traced kernel",
                hint="keep the value on device; materialize outside jit")
            return
        if attr in ("asarray", "array") and chain and args_tainted:
            root = chain[0]
            ok, mod = self.env.resolve(root)
            is_np = (ok and getattr(mod, "__name__", "") == "numpy") \
                or (not ok and root in ("np", "numpy"))
            if is_np:
                self.emit(
                    "WF801", node,
                    f"{root}.{attr}() forces a traced value to a host "
                    "numpy array inside a jit-traced kernel",
                    hint="use jnp.asarray (stays abstract under trace)")
                return
        if (attr == "device_get" or attr == "block_until_ready") \
                and (args_tainted or (attr == "block_until_ready"
                                      and self.expr_tainted(
                                          node.func.value))):
            self.emit(
                "WF801", node,
                f"{attr} synchronizes the host on a traced value "
                "inside a jit-traced kernel", hint=None)
            return
        if fname == "print":
            self.emit(
                "WF804", node,
                "print() inside a jit-traced kernel runs at trace time "
                "only (once per compile), never per batch",
                hint="use jax.debug.print for per-dispatch output")

    # .. recompile hazards (WF81x) ...........................................
    def _recompile_call(self, node, fname, attr, chain, resolved,
                        obj) -> None:
        key = (node.lineno, node.col_offset)
        if key in self._det_hits:
            return      # the determinism pass already owns this call
        if fname == "len" and node.args:
            arg = node.args[0]
            if isinstance(arg, (ast.Name, ast.Attribute)):
                ok, val = self.env.resolve_expr(arg)
                root = _root_name(arg)
                if ok and isinstance(val, _MUTABLE_CONTAINERS) \
                        and root not in self.locals:
                    self.emit(
                        "WF811", node,
                        f"len({ast.unparse(arg)}) of a mutable "
                        f"closure/global {type(val).__name__} is baked "
                        "at trace time — growing it later silently "
                        "keeps the old value, or recompiles per length "
                        "in a shape position",
                        hint="freeze the container (tuple) or pass the "
                             "length as an explicit static argument")
            return
        if fname == "next":
            self.emit(
                "WF811", node,
                "next() advances host state at trace time — each "
                "re-trace reads a different value (baked constant / "
                "recompile driver)",
                hint="thread the value in as an argument")
            return
        if not self.durable:
            # wall clock / RNG in a NON-checkpointed traced kernel is
            # not a replay hazard but still a trace-time bake: the
            # determinism pass owns these under durability
            wall = self._wallclock_target(node, chain, resolved, obj)
            if wall:
                self.emit(
                    "WF811", node,
                    f"{wall} runs at trace time inside a jit-traced "
                    "kernel — its value is baked into the compiled "
                    "program as a constant (stale for every cached "
                    "dispatch)",
                    hint="compute it on host and pass it as an operand")
        if attr in _SHAPE_DYNAMIC:
            recv_root = chain[0] if chain else None
            recv_tainted = isinstance(node.func, ast.Attribute) \
                and self.expr_tainted(node.func.value)
            args_tainted = any(self.expr_tainted(a) for a in node.args)
            if (recv_root in _JAX_ROOTS and args_tainted) or recv_tainted:
                self.emit(
                    "WF812", node,
                    f"{attr}() has a data-dependent output shape — "
                    "fails under jit, or recompiles per distinct "
                    "result size",
                    hint="use the size= keyword (jnp.nonzero/unique) or "
                         "a masked fixed-shape formulation")
            return
        if attr == "where" and chain and chain[0] in _JAX_ROOTS \
                and len(node.args) == 1 \
                and self.expr_tainted(node.args[0]):
            self.emit(
                "WF812", node,
                "one-argument where() returns data-dependent-shape "
                "indices — fails under jit, or recompiles per batch",
                hint="use the three-argument jnp.where(cond, x, y)")

    def _wallclock_target(self, node, chain, resolved,
                          obj) -> Optional[str]:
        """Dotted name of a wall-clock read, or None.  Resolution is
        object-level first (the closure may alias ``import time as t``),
        name-based as a fallback."""
        if resolved and isinstance(obj, types.BuiltinFunctionType) \
                and getattr(obj, "__module__", "") == "time" \
                and obj.__name__ in _WALLCLOCK_TIME_ATTRS:
            return f"time.{obj.__name__}"
        if resolved and getattr(obj, "__name__", "") \
                in _WALLCLOCK_DT_ATTRS \
                and "datetime" in getattr(obj, "__qualname__", ""):
            return f"datetime.{obj.__name__}"
        if resolved and getattr(obj, "__name__", "") \
                == "current_time_usecs":
            return "current_time_usecs"
        if len(chain) >= 2:
            if chain[-2] == "time" and chain[-1] in _WALLCLOCK_TIME_ATTRS:
                return ".".join(chain)
            if chain[-2] in ("datetime", "date") \
                    and chain[-1] in _WALLCLOCK_DT_ATTRS:
                return ".".join(chain)
        return None

    # .. determinism (WF61x) .................................................
    def _determinism_call(self, node, fname, attr, chain, resolved,
                          obj) -> None:
        key = (node.lineno, node.col_offset)
        wall = self._wallclock_target(node, chain, resolved, obj)
        if wall:
            self._det_hits.add(key)
            self.emit(
                "WF612", node,
                f"{wall} read in a kernel/callback of a checkpointed "
                "graph — a replay re-reads a DIFFERENT clock, so the "
                "exactly-once fence dedupes records that no longer "
                "match (docs/DURABILITY.md determinism requirements)",
                hint="derive times from the record's event timestamp "
                     "lane, never the host clock")
            return
        if fname == "id":
            self._det_hits.add(key)
            self.emit(
                "WF613", node,
                "id() is a process-lifetime address — differs on every "
                "replay of a checkpointed graph", hint=None)
            return
        if fname == "hash":
            self._det_hits.add(key)
            self.emit(
                "WF613", node,
                "hash() of str/bytes is salted per process "
                "(PYTHONHASHSEED) — a restored run computes different "
                "hashes than the checkpointed one",
                hint="use a content hash (hashlib) or an integer key")
            return
        rng = self._rng_target(node, chain, resolved, obj)
        if rng:
            self._det_hits.add(key)
            self.emit(
                "WF611", node,
                f"{rng} draws from hidden RNG state in a "
                "kernel/callback of a checkpointed graph — replays "
                "diverge from the committed prefix",
                hint="thread a jax.random key derived from the record/"
                     "batch index, or a seeded generator captured in "
                     "the checkpoint")

    def _rng_target(self, node, chain, resolved, obj) -> Optional[str]:
        mod = (getattr(obj, "__module__", "") or "") if resolved else ""
        recv = getattr(obj, "__self__", None) if resolved else None
        if recv is not None:
            # bound methods of stdlib/numpy RNG objects (random.random is
            # a bound method of the module-level Random singleton, with
            # __module__ None — identify it by its receiver's type)
            rt = type(recv)
            rmod = getattr(rt, "__module__", "") or ""
            if rmod == "random" or rmod.startswith("numpy.random"):
                return f"{rmod}.{rt.__name__}." \
                       f"{getattr(obj, '__name__', '?')}"
        if resolved and (mod == "random" or mod.startswith("numpy.random")):
            return f"{mod}.{getattr(obj, '__name__', chain[-1] if chain else '?')}"
        if resolved and mod.startswith("jax.") and "random" in mod:
            # jax.random with the key THREADED from the function's
            # parameters is the explicitly-deterministic pattern;
            # PRNGKey(constant) is deterministic too
            name = getattr(obj, "__name__", "")
            if name in ("PRNGKey", "key"):
                if all(isinstance(a, ast.Constant) for a in node.args):
                    return None
                return f"jax.random.{name} seeded from a non-constant"
            if node.args and self.expr_tainted(node.args[0]):
                return None
            return f"jax.random.{name} with an unthreaded key"
        if not resolved and len(chain) >= 2 and "random" in chain[:-1]:
            if chain[0] == "jax":
                return None     # unresolvable jax.random: assume threaded
            return ".".join(chain)
        if isinstance(node.func, ast.Attribute):
            ok_recv, recv = self.env.resolve_expr(node.func.value)
            tn = type(recv).__name__ if ok_recv else ""
            if tn in ("Generator", "RandomState") and ok_recv \
                    and type(recv).__module__.startswith("numpy.random"):
                return f"numpy.random.{tn}.{node.func.attr}"
        return None

    # .. iteration order (WF614) .............................................
    def _order_dep(self, it) -> None:
        if not self.durable:
            return
        src = self._setish(it)
        if src is not None:
            self.emit(
                "WF614", it,
                f"iteration over a set ({src}) in a kernel/callback of "
                "a checkpointed graph — set order is salted per process "
                "(PYTHONHASHSEED), so a restored run emits a different "
                "order than the checkpointed one",
                hint="iterate sorted(...) or use a list/dict (insertion "
                     "order is deterministic)")

    def _setish(self, e) -> Optional[str]:
        if isinstance(e, (ast.Set, ast.SetComp)):
            return "set literal"
        if isinstance(e, ast.Call):
            fname = e.func.id if isinstance(e.func, ast.Name) else None
            if fname in ("set", "frozenset"):
                return f"{fname}(...)"
            if fname in ("vars", "globals", "locals"):
                return f"{fname}()"
            if fname in ("sorted", "min", "max", "sum", "list", "tuple",
                         "enumerate", "reversed"):
                # order-insensitive consumers are fine; list()/tuple()
                # PRESERVE the inner order, so look through them
                if fname in ("list", "tuple", "enumerate", "reversed") \
                        and e.args:
                    return self._setish(e.args[0])
                return None
        if isinstance(e, (ast.Name, ast.Attribute)):
            ok, val = self.env.resolve_expr(e)
            if ok and isinstance(val, (set, frozenset)):
                return f"'{ast.unparse(e)}' (a {type(val).__name__})"
        return None

    # .. mutation via method calls (WF803) + interprocedural follow ..........
    def _maybe_follow(self, node: ast.Call, fname, resolved, obj) -> None:
        func = node.func
        # closure/global container mutation through a method call
        if self.traced and isinstance(func, ast.Attribute) \
                and func.attr in _MUTATORS:
            root = _root_name(func.value)
            if root is not None and root not in self.locals \
                    and root not in self.params:
                ok, val = self.env.resolve(root)
                if (ok and isinstance(val, _MUTABLE_CONTAINERS)) \
                        or (not ok and root in self.env.free):
                    self.emit(
                        "WF803", node,
                        f"'{root}.{func.attr}()' mutates closure/global "
                        "state inside a jit-traced kernel — runs at "
                        "trace time only, silently skipped on every "
                        "cached dispatch",
                        hint="return the data instead of accumulating "
                             "into enclosing state")
            elif root in self.mutable_defaults:
                self.emit(
                    "WF803", node,
                    f"'{root}.{func.attr}()' mutates a mutable default "
                    "argument inside a jit-traced kernel — state shared "
                    "across calls, written only at trace time",
                    hint="default to None and construct per call")
        # bounded call-depth following
        if self.depth <= 0:
            return
        callee = None
        call_args = node.args
        if resolved and inspect.isfunction(_unwrap(obj)):
            callee = _unwrap(obj)
        elif fname in self.local_defs:
            self._follow_local(self.local_defs[fname], call_args)
            return
        elif isinstance(func, ast.Call):
            # jax higher-order wrappers: vmap(fn)(...) / tree.map-style —
            # the function ARGUMENT is what gets traced
            inner = func
            for a in inner.args:
                if isinstance(a, ast.Name) and a.id in self.local_defs:
                    self._follow_local(self.local_defs[a.id], call_args)
                elif isinstance(a, (ast.Name, ast.Attribute)):
                    ok, f = self.env.resolve_expr(a)
                    if ok and inspect.isfunction(_unwrap(f)):
                        _verify_into(_unwrap(f), traced=self.traced,
                                     durable=self.durable,
                                     depth=self.depth - 1,
                                     findings=self.findings,
                                     visited=self.visited,
                                     taint_all=True)
            return
        if callee is None and isinstance(func, (ast.Name, ast.Attribute)):
            # fn passed as argument to a HOF (jax.vmap(self.fn) handled
            # above); plain calls with function-valued args: follow them
            for a in node.args:
                if isinstance(a, ast.Name) and a.id in self.local_defs:
                    self._follow_local(self.local_defs[a.id], [])
                elif isinstance(a, (ast.Name, ast.Attribute)):
                    ok, f = self.env.resolve_expr(a)
                    if ok and inspect.isfunction(_unwrap(f)) \
                            and _followable(_unwrap(f)):
                        _verify_into(_unwrap(f), traced=self.traced,
                                     durable=self.durable,
                                     depth=self.depth - 1,
                                     findings=self.findings,
                                     visited=self.visited, taint_all=True)
        if callee is not None and _followable(callee):
            any_taint = any(self.expr_tainted(a) for a in call_args) \
                or not self.traced
            _verify_into(callee, traced=self.traced,
                         durable=self.durable, depth=self.depth - 1,
                         findings=self.findings, visited=self.visited,
                         taint_all=any_taint)

    def _follow_local(self, defnode, call_args) -> None:
        """Analyze an inner ``def`` with this function's environment
        (approximation: inner defs close over our scope)."""
        sub = _FnCheck(self.fn, defnode, self.path, traced=self.traced,
                       durable=self.durable, depth=self.depth - 1,
                       findings=self.findings, visited=self.visited)
        key = (defnode, self.traced, self.durable)
        if key in self.visited:
            return
        self.visited.add(key)
        sub.run()


def _followable(fn) -> bool:
    """Follow user/package functions; treat jax/numpy/stdlib as opaque
    (their internals are not the user's kernel code)."""
    mod = getattr(fn, "__module__", "") or ""
    if mod.startswith(("jax", "numpy", "scipy", "builtins", "functools",
                       "itertools", "threading", "json", "math")):
        return False
    return getattr(fn, "__code__", None) is not None


def _verify_into(fn, *, traced: bool, durable: bool, depth: int,
                 findings: List[_Finding], visited: Set,
                 taint_all: bool = True) -> None:
    fn = _unwrap(fn)
    code = getattr(fn, "__code__", None)
    if code is None:
        return
    key = (code, traced, durable)
    if key in visited:
        return
    visited.add(key)
    located = _callable_node(fn)
    if located is None:
        return
    node, path = located
    chk = _FnCheck(fn, node, path, traced=traced and taint_all,
                   durable=durable, depth=depth, findings=findings,
                   visited=visited)
    chk.run()


# ---------------------------------------------------------------------------
# donation pass (WF82x)
# ---------------------------------------------------------------------------

def _possible_tuples(node, assigns: Dict[str, list]) -> Set[tuple]:
    """Every tuple of ints a ``donate_argnums`` expression may evaluate
    to, over literal tuples, conditional expressions, concatenation and
    single-assignment names — conservative union ("may be donated")."""
    if isinstance(node, ast.Tuple):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return set()
        return {tuple(vals)}
    if isinstance(node, ast.Constant):
        if isinstance(node.value, int):
            return {(node.value,)}
        return set()
    if isinstance(node, ast.IfExp):
        return _possible_tuples(node.body, assigns) \
            | _possible_tuples(node.orelse, assigns)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _possible_tuples(node.left, assigns)
        right = _possible_tuples(node.right, assigns)
        return {a + b for a in left for b in right}
    if isinstance(node, ast.Name):
        out: Set[tuple] = set()
        for v in assigns.get(node.id, []):
            out |= _possible_tuples(v, assigns)
        return out
    return set()


def _donating_positions_in_source(fnode: ast.AST) -> Set[int]:
    """Union of argument positions a function's ``wf_jit``/``jax.jit``
    calls MAY donate, resolved from literals and local assignments."""
    assigns: Dict[str, list] = {}
    for n in ast.walk(fnode):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            assigns.setdefault(n.targets[0].id, []).append(n.value)
    positions: Set[int] = set()
    for n in ast.walk(fnode):
        if not isinstance(n, ast.Call):
            continue
        fname = n.func.id if isinstance(n.func, ast.Name) \
            else (n.func.attr if isinstance(n.func, ast.Attribute)
                  else None)
        if fname not in ("wf_jit", "jit"):
            continue
        for kw in n.keywords:
            if kw.arg == "donate_argnums":
                for tup in _possible_tuples(kw.value, assigns):
                    positions.update(tup)
    return positions


_CLASS_DONATION_CACHE: Dict[type, Dict[str, Set[int]]] = {}


def _class_donation_map(cls: type) -> Dict[str, Set[int]]:
    """attr/method name -> positions it may donate, for one operator
    class: a method whose body creates a ``donate_argnums`` jit donates
    those positions when called-then-called (``self._get_step(c)(...)``),
    and an attribute assigned from such a method (``self._jit_step =
    self._build_step(...)``) donates them when dispatched directly."""
    hit = _CLASS_DONATION_CACHE.get(cls)
    if hit is not None:
        return hit
    out: Dict[str, Set[int]] = {}
    for klass in cls.__mro__:
        if klass in (object,):
            continue
        try:
            src = textwrap_dedent_source(klass)
        except (OSError, TypeError):
            continue
        if src is None:
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        cnode = next((n for n in ast.walk(tree)
                      if isinstance(n, ast.ClassDef)), None)
        if cnode is None:
            continue
        method_pos: Dict[str, Set[int]] = {}
        for m in cnode.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pos = _donating_positions_in_source(m)
                if pos:
                    method_pos[m.name] = pos
        for name, pos in method_pos.items():
            out.setdefault(name, set()).update(pos)
        # self.ATTR = self.METHOD(...) anywhere in the class
        for n in ast.walk(cnode):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                vchain = _attr_chain(n.value.func)
                if len(vchain) == 2 and vchain[0] == "self" \
                        and vchain[1] in method_pos:
                    for t in n.targets:
                        tchain = _attr_chain(t)
                        if len(tchain) == 2 and tchain[0] == "self":
                            out.setdefault(tchain[1], set()).update(
                                method_pos[vchain[1]])
    _CLASS_DONATION_CACHE[cls] = out
    return out


def textwrap_dedent_source(obj) -> Optional[str]:
    import textwrap
    try:
        return textwrap.dedent(inspect.getsource(obj))
    except (OSError, TypeError):
        return None


class _DonationCheck:
    """Abstract interpretation of one dispatcher function: donated
    operand expressions go live at each donating call and are flagged
    when read again on any later path (branch analysis unions the
    per-path live sets; a store to the expression kills it)."""

    def __init__(self, fn, node, path, owner, findings: List[_Finding],
                 env: Optional[_Env] = None) -> None:
        self.fn = fn
        self.node = node
        self.path = path
        self.owner = owner          # object bound to the first parameter
        self.findings = findings
        self.env = env or _Env(fn)
        args = node.args
        self.self_name = args.args[0].arg if args.args else None
        #: local jit names: X = wf_jit(..., donate_argnums=L) in-body
        self.local_donors = self._local_donors(node)
        self.class_map = _class_donation_map(type(owner)) \
            if owner is not None else {}

    @staticmethod
    def _local_donors(fnode) -> Dict[str, Set[int]]:
        assigns: Dict[str, list] = {}
        for n in ast.walk(fnode):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                assigns.setdefault(n.targets[0].id, []).append(n.value)
        out: Dict[str, Set[int]] = {}
        for name, values in assigns.items():
            for v in values:
                if isinstance(v, ast.Call):
                    fname = v.func.id if isinstance(v.func, ast.Name) \
                        else (v.func.attr
                              if isinstance(v.func, ast.Attribute)
                              else None)
                    if fname in ("wf_jit", "jit"):
                        for kw in v.keywords:
                            if kw.arg == "donate_argnums":
                                for tup in _possible_tuples(kw.value,
                                                            assigns):
                                    out.setdefault(name, set()).update(tup)
        return out

    def donated_positions(self, call: ast.Call) -> Set[int]:
        func = call.func
        # 1. object-level: the callee resolves to a live WfJit wrapper
        if isinstance(func, (ast.Name, ast.Attribute)):
            obj = None
            chain = _attr_chain(func)
            if chain and chain[0] == self.self_name \
                    and self.owner is not None:
                obj = self.owner
                for part in chain[1:]:
                    obj = getattr(obj, part, None)
                    if obj is None:
                        break
            else:
                ok, obj = self.env.resolve_expr(func)
                if not ok:
                    obj = None
            donate = getattr(obj, "_donate", None)
            if donate:
                return set(donate)
            # 2. class-level: self.<attr> assigned from a donating method
            if chain and len(chain) == 2 and chain[0] == self.self_name:
                pos = self.class_map.get(chain[1])
                if pos:
                    return set(pos)
            # 3. in-body: X = wf_jit(..., donate_argnums=...)
            if isinstance(func, ast.Name) \
                    and func.id in self.local_donors:
                return set(self.local_donors[func.id])
        # 4. call-of-call: self._get_step(...)(args) — the inner method
        #    builds and returns the donating jit
        if isinstance(func, ast.Call):
            ichain = _attr_chain(func.func)
            if len(ichain) == 2 and ichain[0] == self.self_name:
                pos = self.class_map.get(ichain[1])
                if pos:
                    return set(pos)
            if len(ichain) == 2 and ichain[0] == self.self_name \
                    and self.owner is not None:
                meth = getattr(type(self.owner), ichain[1], None)
                if meth is not None:
                    msrc = textwrap_dedent_source(meth)
                    if msrc:
                        try:
                            pos = _donating_positions_in_source(
                                ast.parse(msrc))
                        except SyntaxError:
                            pos = set()
                        if pos:
                            return pos
        return set()

    @staticmethod
    def _trackable(e) -> Optional[str]:
        """Stable unparse of a donated operand expression (names and
        attribute/subscript chains only — a computed operand cannot be
        'read again' syntactically)."""
        n = e
        while isinstance(n, (ast.Attribute, ast.Subscript)):
            if isinstance(n, ast.Subscript) \
                    and not isinstance(n.slice, (ast.Name, ast.Constant)):
                return None
            n = n.value
        if isinstance(n, ast.Name):
            return ast.unparse(e)
        return None

    # -- abstract interpretation over statements ----------------------------
    def run(self) -> None:
        self._block(self.node.body, {})

    def _block(self, stmts, live: Dict[str, ast.AST]) -> Dict[str, ast.AST]:
        for s in stmts:
            live = self._stmt(s, live)
        return live

    def _stmt(self, s, live) -> Dict[str, ast.AST]:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return live
        if isinstance(s, ast.If):
            cond_live = dict(live)
            self._events(s.test, cond_live)
            a = self._block(s.body, dict(cond_live))
            b = self._block(s.orelse, dict(cond_live))
            return {**a, **b}
        if isinstance(s, (ast.For, ast.While)):
            if isinstance(s, ast.For):
                self._events(s.iter, live)
            else:
                self._events(s.test, live)
            once = self._block(s.body, dict(live))
            # second pass with the post-body state folded in: a donate
            # late in the body is read by an early statement on the
            # NEXT iteration
            twice = self._block(s.body, {**live, **once})
            merged = {**live, **once, **twice}
            return self._block(s.orelse, merged)
        if isinstance(s, ast.Try):
            out = self._block(s.body, dict(live))
            for h in s.handlers:
                out = {**out, **self._block(h.body, dict(live))}
            out = self._block(s.orelse, out)
            return self._block(s.finalbody, out)
        if isinstance(s, ast.With):
            for item in s.items:
                self._events(item.context_expr, live)
            return self._block(s.body, live)
        # straight-line statement: evaluate value side (loads + calls in
        # positional order), then apply stores
        value_exprs = []
        targets = []
        if isinstance(s, ast.Assign):
            value_exprs = [s.value]
            targets = s.targets
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            if s.value is not None:
                value_exprs = [s.value]
            targets = [s.target]
            if isinstance(s, ast.AugAssign):
                self._events(s.target, live)    # aug reads before write
        elif isinstance(s, ast.Return):
            if s.value is not None:
                value_exprs = [s.value]
        elif isinstance(s, ast.Expr):
            value_exprs = [s.value]
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    value_exprs.append(child)
        for e in value_exprs:
            self._events(e, live)
        for t in targets:
            self._kill(t, live)
        return live

    def _events(self, e, live: Dict[str, ast.AST]) -> None:
        """Process one expression tree in approximate evaluation order:
        loads of live donated exprs are violations; donating calls make
        their operands live."""
        if e is None:
            return
        for node in self._ordered(e):
            if isinstance(node, ast.Call):
                donated = self.donated_positions(node)
                if donated:
                    for i, a in enumerate(node.args):
                        if i in donated:
                            expr = self._trackable(a)
                            if expr is not None:
                                live[expr] = node
            elif isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                key = None
                try:
                    key = ast.unparse(node)
                except Exception:  # noqa: BLE001 - lint: broad-except-ok
                    # (unparse of synthetic/odd nodes must never break
                    # verification; an unprintable expr is untrackable)
                    key = None
                if key is not None and key in live:
                    call = live[key]
                    self.findings.append(_Finding(
                        "WF821",
                        f"'{key}' was donated to the compiled program "
                        f"at line {call.lineno} and read again after "
                        "the dispatch — the donated buffer is dead "
                        "(XLA may already have overwritten it in "
                        "place)",
                        self.path, node.lineno,
                        hint="read every needed value BEFORE the "
                             "donating call, or drop it from "
                             "donate_argnums"))
                    del live[key]   # one report per donate/read pair

    def _ordered(self, e) -> list:
        """Nodes of an expression in (lineno, col) order — approximate
        left-to-right evaluation order; nested loads inside a donating
        call's own arguments are NOT post-dispatch reads, so calls mask
        their own subtree's loads."""
        calls = [n for n in ast.walk(e) if isinstance(n, ast.Call)
                 and self.donated_positions(n)]
        masked = set()
        for c in calls:
            for sub in ast.walk(c):
                if sub is not c:
                    masked.add(id(sub))
        out = [n for n in ast.walk(e) if id(n) not in masked]
        return sorted(out, key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0)))

    def _kill(self, t, live: Dict[str, ast.AST]) -> None:
        for n in ast.walk(t):
            if isinstance(n, (ast.Name, ast.Attribute, ast.Subscript)) \
                    and isinstance(getattr(n, "ctx", None), ast.Store):
                try:
                    key = ast.unparse(n)
                except Exception:  # noqa: BLE001 - lint: broad-except-ok
                    # (same stance as the load side: unprintable target
                    # just kills nothing)
                    continue
                live.pop(key, None)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

_KERNEL_CACHE: Dict[Tuple[Any, bool, bool], List[_Finding]] = {}


def verify_callable(fn, *, traced: bool, durable: bool = False,
                    depth: int = MAX_CALL_DEPTH) -> List[_Finding]:
    """Raw findings (pre-suppression) for one function object, cached by
    code object so graphs rebuilt with the same kernels re-pay nothing.
    Functions WITH closure cells are never cached: the findings depend
    on the cell values (a framework step closure resolves ``self.fn`` to
    a different user kernel per operator instance), and one code object
    is shared by every instance."""
    fn = _unwrap(fn)
    code = getattr(fn, "__code__", None)
    if code is None:
        return []
    cacheable = not getattr(fn, "__closure__", None)
    key = (code, traced, durable)
    if cacheable:
        hit = _KERNEL_CACHE.get(key)
        if hit is not None:
            return hit
    findings: List[_Finding] = []
    _verify_into(fn, traced=traced, durable=durable, depth=depth,
                 findings=findings, visited=set())
    if cacheable:
        _KERNEL_CACHE[key] = findings
    return findings


def verify_dispatcher(fn, owner=None) -> List[_Finding]:
    """Donation pass (WF82x) over one dispatcher function/method —
    ``owner`` binds the first parameter so ``self.X`` resolves on the
    live object (WfJit ``_donate`` sets, lazily-built step tables)."""
    fn = _unwrap(fn)
    located = _callable_node(fn)
    if located is None:
        return []
    node, path = located
    if isinstance(node, ast.Lambda):
        return []
    findings: List[_Finding] = []
    _DonationCheck(fn, node, path, owner, findings).run()
    return findings


class VerifyReport:
    """Outcome of :func:`verify_graph`: reportable diagnostics,
    suppressed findings (justified inline), and the wall cost."""

    def __init__(self) -> None:
        self.diagnostics: List[Diagnostic] = []
        self.suppressed: List[Diagnostic] = []
        self.checked = 0
        self.check_ms = 0.0

    def to_json(self) -> dict:
        return {
            "checked_callables": self.checked,
            "check_ms": self.check_ms,
            "findings": len(self.diagnostics),
            "suppressed": len(self.suppressed),
            "diagnostics": [d.to_json() for d in self.diagnostics],
            "suppressed_diagnostics": [d.to_json()
                                       for d in self.suppressed],
        }


def _graph_callables(graph):
    """Yield ``(fn, op_name, role, traced)`` for every user callable the
    runtime will invoke: device kernels (traced) and host callbacks
    (determinism surface).  Degrades per-attribute: unknown operator
    types contribute whatever standard attributes they carry."""
    from windflow_tpu.ops.chained import ChainedHost, ChainedTPU
    seen: Set[int] = set()

    def one(fn, name, role, traced):
        if fn is None or not callable(fn) or id(fn) in seen:
            return None
        seen.add(id(fn))
        return (fn, name, role, traced)

    for op in graph._topo_operators():
        is_tpu = getattr(op, "is_tpu", False)
        if isinstance(op, (ChainedTPU, ChainedHost)):
            for kind, fn in op.specs:
                got = one(fn, op.name, f"{kind} stage", is_tpu)
                if got:
                    yield got
        for attr, role in (("fn", "kernel"), ("comb", "combiner"),
                           ("lift", "window lift"),
                           ("batch_fn", "batch generator"),
                           ("ts_fn", "timestamp kernel"),
                           ("gen_fn", "generator"),
                           ("deser_fn", "deserializer"),
                           ("ser_fn", "serializer"),
                           ("wm_fn", "watermark fn"),
                           ("ts_extractor", "timestamp extractor"),
                           ("closing_func", "closing callback")):
            fn = getattr(op, attr, None)
            traced = is_tpu and attr in ("fn", "comb", "lift",
                                         "batch_fn", "ts_fn")
            got = one(fn, op.name, role, traced)
            if got:
                yield got
        kx = getattr(op, "key_extractor", None)
        got = one(kx, op.name, "key extractor", is_tpu)
        if got:
            yield got


def _framework_traced_bodies(graph):
    """The framework's own wf_jit wrapper bodies reachable from the
    graph's operators RIGHT NOW (pre-start): the functions held by live
    ``WfJit`` wrappers.  Lazily-built step programs (reduce/ffat/
    stateful) close over the same user kernels verified directly."""
    out = []
    seen: Set[int] = set()
    for op in graph._topo_operators():
        for holder in (getattr(op, "_jit_step", None),
                       *(getattr(op, "_jit_steps", {}) or {}).values()):
            fn = getattr(holder, "_fn", None)
            if fn is not None and id(fn) not in seen:
                seen.add(id(fn))
                out.append((fn, op.name))
        chain = getattr(op, "_chain", None)
        if chain is not None:
            fn = getattr(getattr(chain, "_jit", None), "_fn", None)
            if fn is not None and id(fn) not in seen:
                seen.add(id(fn))
                out.append((fn, op.name))
    return out


def _dispatch_methods(graph):
    """Per-operator dispatcher bodies for the donation pass: the class
    ``_step`` methods that hand operands to donating programs."""
    out = []
    seen: Set[Tuple[type, str]] = set()
    for op in graph._topo_operators():
        cls = type(op)
        for mname in ("_step",):
            meth = getattr(cls, mname, None)
            if meth is None or (cls, mname) in seen:
                continue
            seen.add((cls, mname))
            out.append((meth, op, op.name))
    return out


_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _apply_suppressions(findings: List[_Finding], op_name: Optional[str],
                        report: VerifyReport,
                        seen: Optional[Set[Tuple]] = None) -> None:
    for f in findings:
        if seen is not None:
            key = (f.code, f.path, f.lineno)
            if key in seen:
                continue    # one report per site: a kernel reached both
                #             directly and through a wrapper body's
                #             closure counts once
            seen.add(key)
        sup = suppression_at(f.path, f.lineno)
        path = f.path
        if path.startswith(_REPO + os.sep):
            path = os.path.relpath(path, _REPO)
        d = Diagnostic(f.code, f.message, node=op_name,
                       location=f"{path}:{f.lineno}", hint=f.hint)
        if sup == "ok":
            report.suppressed.append(d)
        elif sup == "missing-reason":
            d.message += (" [a 'wfverify: ok' suppression without a "
                          "(reason) was ignored — justify it]")
            report.diagnostics.append(d)
        else:
            report.diagnostics.append(d)


def verify_graph(graph) -> VerifyReport:
    """Run all four wfverify families over a composed PipeGraph's live
    callables.  The determinism family (WF61x) activates when the
    graph's config enables durability; trace-safety/recompile apply to
    device-traced kernels; the donation pass covers every operator's
    dispatcher.  ``PipeGraph.check()`` folds the resulting diagnostics
    into the preflight list (severity policy follows
    ``Config.preflight`` exactly like the WF1xx-WF6xx codes)."""
    t0 = time.perf_counter()
    report = VerifyReport()
    seen: Set[Tuple] = set()
    durable = bool(getattr(graph.config, "durability", ""))
    for fn, op_name, role, traced in _graph_callables(graph):
        findings = verify_callable(fn, traced=traced, durable=durable)
        report.checked += 1
        _apply_suppressions(findings, op_name, report, seen)
    for fn, op_name in _framework_traced_bodies(graph):
        findings = verify_callable(fn, traced=True, durable=durable)
        report.checked += 1
        _apply_suppressions(findings, op_name, report, seen)
    for meth, owner, op_name in _dispatch_methods(graph):
        findings = verify_dispatcher(meth, owner)
        report.checked += 1
        _apply_suppressions(findings, op_name, report, seen)
    report.check_ms = round((time.perf_counter() - t0) * 1e3, 3)
    return report
