"""Fusion advisor core: maximal fusible operator chains + projected
savings, the planning layer for whole-chain fusion (ROADMAP item 1).

Every operator hop in the PipeGraph sweep is its own jitted dispatch
that round-trips HBM; the sweep ledger (monitoring/sweep_ledger.py)
measures what each hop costs, and this module says which hops could
stop existing: it reuses the pre-flight graph walk
(analysis/preflight.py) to find **maximal fusible chains** — runs of
adjacent TPU operators whose routing and batch contracts let one XLA
program replace the whole run — and ranks them by projected bytes- and
dispatches-saved per batch.  ``ops/chained.py`` proves the pairwise
case today (``MultiPipe.chain`` fuses map/filter pairs into one
program); the chains found here generalize that to arbitrary runs,
window-lift/combine tails included, emitter/collector boundaries
permitting.

Two link strengths:

* ``chainable`` — both ends satisfy ``ops.chained.tpu_chainable`` and
  the edge is FORWARD at equal parallelism: today's ``chain()`` could
  already fuse them (a plan entry here is a missed call site).
* ``whole_chain`` — the edge needs the whole-chain-fusion refactor:
  a window/reduce/stateful tail, or a single-replica KEYBY edge whose
  key extraction already runs inside the compiled program (the keyby
  emitter is then a pure relay a fused program can absorb).

Entry point: :func:`plan` (used by ``tools/wf_advisor.py`` and the
tests); pass a ``stats()["Sweep"]`` section to rank by MEASURED per-hop
numbers instead of spec-based projections.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from windflow_tpu.basic import RoutingMode


def _chain_boundary(a, b, fanout: Dict[int, int],
                    fanin: Dict[int, int]) -> Optional[str]:
    """Why the edge ``a -> b`` cannot join one fused program; ``None``
    when it can (the link reasons :func:`fusible_chains` records)."""
    from windflow_tpu.ops.source import Source
    if not a.is_tpu or isinstance(a, Source):
        return "upstream is not a TPU stage"
    if not b.is_tpu:
        return "downstream leaves the device (host stage / sink)"
    if fanout.get(id(a), 0) != 1:
        return "upstream fans out (split / multi-consumer)"
    if fanin.get(id(b), 0) != 1:
        return "downstream merges several inputs"
    if a.parallelism != b.parallelism:
        return "parallelism changes across the edge"
    if b.routing == RoutingMode.FORWARD:
        return None
    if b.routing == RoutingMode.KEYBY:
        if b.parallelism != 1:
            return "keyby edge re-partitions across replicas"
        if b.key_extractor is None:
            return "keyby edge without a device key extractor"
        return None     # single-replica keyby: the emitter is a relay
    return f"{b.routing.value} routing breaks the device chain"


def _terminal(op) -> bool:
    """Ops that end a fused chain even when linkable: their output is a
    different stream (window results, reduced batches), so fusing PAST
    them changes the program contract, not just its launch count."""
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    return isinstance(op, (ReduceTPU, FfatWindowsTPU, _StatefulTPUBase))


def fusible_chains(graph) -> List[dict]:
    """Maximal fusible chains over a composed (built or unbuilt)
    PipeGraph: ``[{"ops": [op, ...], "links": [kind, ...],
    "tail_boundary": why-the-chain-ends}, ...]``, length >= 2 only."""
    from windflow_tpu.ops.chained import tpu_chainable
    edges = graph._edges()
    fanout: Dict[int, int] = {}
    fanin: Dict[int, int] = {}
    succ: Dict[int, object] = {}
    op_edges = []
    for edge in edges:
        if edge[0] == "op":
            _, a, b = edge
            op_edges.append((a, b))
            fanout[id(a)] = fanout.get(id(a), 0) + 1
            fanin[id(b)] = fanin.get(id(b), 0) + 1
        else:   # split point: the source op fans out by construction
            _, mp = edge
            src = mp.operators[-1]
            fanout[id(src)] = fanout.get(id(src), 0) + len(mp.split_children)
    links: Dict[int, tuple] = {}
    linked_in = set()
    for a, b in op_edges:
        boundary = _chain_boundary(a, b, fanout, fanin)
        if boundary is None and not _terminal(a):
            kind = ("chainable" if tpu_chainable(a) and tpu_chainable(b)
                    and b.routing == RoutingMode.FORWARD else "whole_chain")
            links[id(a)] = (b, kind)
            linked_in.add(id(b))
    chains = []
    seen = set()
    for a, _ in op_edges:
        if id(a) in seen or id(a) in linked_in or id(a) not in links:
            continue
        ops = [a]
        kinds = []
        cur = a
        while id(cur) in links:
            nxt, kind = links[id(cur)]
            ops.append(nxt)
            kinds.append(kind)
            seen.add(id(cur))
            cur = nxt
        seen.add(id(cur))
        tail = None
        for b2 in (b for x, b in op_edges if x is cur):
            tail = _chain_boundary(cur, b2, fanout, fanin) \
                or ("chain tail is a window/reduce/stateful stage"
                    if _terminal(cur) else None)
        chains.append({"ops": ops, "links": kinds, "tail_boundary": tail})
    return chains


def _batched_bytes(spec_bytes: Optional[int],
                   capacity: Optional[int]) -> Optional[int]:
    from windflow_tpu.monitoring.sweep_ledger import LANE_BYTES_PER_TUPLE
    if spec_bytes is None or not capacity:
        return None
    return (spec_bytes + LANE_BYTES_PER_TUPLE) * capacity


def plan(graph, sweep: Optional[dict] = None, top: int = 0) -> dict:
    """The concrete fusion plan: chains from :func:`fusible_chains`
    ranked by projected bytes-saved per batch (interior hop boundaries a
    fused program never materializes in HBM — write + re-read — plus
    the members' donation-miss copies), then by dispatches-saved.

    ``sweep`` — a live ``stats()["Sweep"]`` section — upgrades the
    projection to MEASURED dispatch counts and boundary bytes; without
    it, dispatches default to one per member and boundary bytes come
    from the pre-flight record specs."""
    from windflow_tpu.analysis.preflight import (_upstream_map,
                                                 _effective_caps,
                                                 propagate_specs,
                                                 record_nbytes)
    edges = graph._edges()
    upstreams = _upstream_map(edges)
    try:
        _, out_specs = propagate_specs(graph, edges=edges,
                                       upstreams=upstreams)
    except Exception:  # lint: broad-except-ok (advisor must still rank
        # by dispatch counts when a user kernel defeats abstract eval)
        out_specs = {}
    per_hop = (sweep or {}).get("per_hop") or {}
    out = []
    for chain in fusible_chains(graph):
        ops = chain["ops"]
        names = [op.name for op in ops]
        disp_now = 0.0
        bytes_saved = 0.0
        donation_bytes = 0.0
        measured = True
        for op in ops:
            h = per_hop.get(op.name) or {}
            d = h.get("dispatches_per_batch")
            if d is None:
                d = 1.0
                measured = False
            disp_now += d
            miss = (h.get("donation_miss") or {}).get("bytes_per_batch")
            if miss:
                donation_bytes += miss
        for op in ops[:-1]:     # interior boundaries only
            h = per_hop.get(op.name) or {}
            bb = h.get("fusion_fuel_bytes_per_batch")
            if bb is None:
                caps = sorted(c for c in _effective_caps(op, upstreams)
                              if c)
                bb = _batched_bytes(record_nbytes(out_specs.get(id(op))),
                                    caps[0] if caps else None)
                measured = False
            if bb:
                # the producing hop writes the boundary batch to HBM and
                # the consuming hop reads it back: both sides vanish
                # when the chain lowers into one program
                bytes_saved += 2 * bb
        out.append({
            "ops": names,
            "links": chain["links"],
            "provable_now": all(k == "chainable" for k in chain["links"]),
            "tail_boundary": chain["tail_boundary"],
            "dispatches_per_batch_now": round(disp_now, 3),
            "dispatches_saved_per_batch": round(disp_now - 1.0, 3),
            "projected_bytes_saved_per_batch": round(bytes_saved, 1),
            "donation_miss_bytes_per_batch": round(donation_bytes, 1),
            "basis": "measured" if (measured and per_hop) else "projected",
        })
    out.sort(key=lambda c: (c["projected_bytes_saved_per_batch"]
                            + c["donation_miss_bytes_per_batch"],
                            c["dispatches_saved_per_batch"]),
             reverse=True)
    if top:
        out = out[:top]
    return {"graph": graph.name, "chains": out}
