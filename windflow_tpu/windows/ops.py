"""Host window operators: Keyed_Windows, Parallel_Windows, Paned_Windows,
MapReduce_Windows (reference ``keyed_windows.hpp``, ``parallel_windows.hpp``,
``paned_windows.hpp``, ``mapreduce_windows.hpp``).

All are thin operator shells around :class:`windflow_tpu.windows.engine
.WindowEngine`, exactly as the reference builds every window operator around
``Window_Replica``.  The compound operators are *composites*: like the
reference, which appends PLQ+WLQ / MAP+REDUCE as two pipeline stages
(``multipipe.hpp:965-999``), ``MultiPipe.add`` expands their ``stages()``.

Window results flow downstream as :class:`WindowResult` records carrying the
key, the global window id and the user value (the reference stamps key/gwid
onto the user's result type via ``setResultParameters``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

from windflow_tpu.basic import (RoutingMode, WindFlowError,
                                WindowRole, WinType)
from windflow_tpu.batch import WM_NONE
from windflow_tpu.ops.base import Operator, Replica
from windflow_tpu.parallel.emitters import stable_hash
from windflow_tpu.windows.engine import WindowEngine, WindowSpec


@dataclasses.dataclass
class WindowResult:
    key: Any
    wid: int
    value: Any


class _WindowReplicaBase(Replica):
    """Shared replica plumbing: feed the engine, forward watermarks, flush at
    EOS."""

    def __init__(self, op, index):
        super().__init__(op, index)
        self.engine: Optional[WindowEngine] = None  # built lazily (needs mode)

    def _ensure_engine(self):
        if self.engine is None:
            self.engine = self.op._make_engine(self)
        return self.engine

    def _emit_result(self, key, gwid, ts, value):
        self.stats.outputs_sent += 1
        # Output watermark is held back to the result timestamp: the operator
        # may still emit results for windows ending at/after this one, so the
        # input watermark would over-promise (see WindowEngine.on_watermark).
        wm = ts if self.current_wm == WM_NONE else min(self.current_wm, ts)
        self.emitter.emit(WindowResult(key, gwid, value), ts, wm)

    def process_single(self, item, ts, wm):
        eng = self._ensure_engine()
        key = self.op.key_of(item)
        eng.on_tuple(key, item, ts, wm)

    def on_watermark(self, wm):
        if self.engine is not None:
            self.engine.on_watermark(wm)

    def on_eos(self):
        self._ensure_engine().on_eos()


class _WindowOpBase(Operator):
    replica_class = _WindowReplicaBase
    # host window engines hold open-window state the durability plane
    # cannot snapshot yet (WF603 surfaces the gap at preflight)
    checkpoint_opaque = True

    def __init__(self, fn: Callable, spec: WindowSpec, *, name: str,
                 parallelism: int, routing: RoutingMode,
                 key_extractor: Optional[Callable],
                 incremental: bool, role: WindowRole,
                 output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.fn = fn
        self.spec = spec
        self.incremental = incremental
        self.role = role

    def key_of(self, item):
        from windflow_tpu.basic import EMPTY_KEY
        if self.key_extractor is None:
            return EMPTY_KEY
        return self.key_extractor(item)

    def _engine_kwargs(self, replica):
        return {}

    def _make_engine(self, replica) -> WindowEngine:
        return WindowEngine(
            self.spec, self.fn, self.incremental, self.role,
            self.parallelism, replica.index, replica.mode,
            emit=replica._emit_result, stats=replica.stats,
            **self._engine_kwargs(replica))


class KeyedWindows(_WindowOpBase):
    """Keyed windows: KEYBY routing, each replica owns whole keys (reference
    ``keyed_windows.hpp:65,198``)."""

    def __init__(self, fn, spec, *, name="keyed_windows", parallelism=1,
                 key_extractor=None, incremental=False,
                 output_batch_size=0):
        routing = (RoutingMode.KEYBY if key_extractor is not None
                   else RoutingMode.FORWARD)
        if key_extractor is None and parallelism > 1:
            raise WindFlowError(
                "Keyed_Windows with parallelism > 1 requires a key extractor")
        super().__init__(fn, spec, name=name, parallelism=parallelism,
                         routing=routing, key_extractor=key_extractor,
                         incremental=incremental, role=WindowRole.SEQ,
                         output_batch_size=output_batch_size)


class ParallelWindows(_WindowOpBase):
    """Parallel windows: BROADCAST routing; replicas own windows round-robin
    by gwid (reference ``parallel_windows.hpp:66,194``)."""

    def __init__(self, fn, spec, *, name="parallel_windows", parallelism=1,
                 key_extractor=None, incremental=False, role=WindowRole.PLQ,
                 output_batch_size=0):
        super().__init__(fn, spec, name=name, parallelism=parallelism,
                         routing=RoutingMode.BROADCAST,
                         key_extractor=key_extractor,
                         incremental=incremental, role=role,
                         output_batch_size=output_batch_size)


class _WLQWindows(_WindowOpBase):
    """Second stage of Paned_Windows: windows of panes, in the pane-id
    domain (reference WLQ role, ``paned_windows.hpp:67``)."""

    def __init__(self, fn, spec, *, pane_len: int, parent_win_type: WinType,
                 name, parallelism, key_extractor, incremental,
                 output_batch_size=0):
        super().__init__(fn, spec, name=name, parallelism=parallelism,
                         routing=RoutingMode.BROADCAST,
                         key_extractor=key_extractor,
                         incremental=incremental, role=WindowRole.WLQ,
                         output_batch_size=output_batch_size)
        self.pane_len = pane_len
        self.parent_win_type = parent_win_type

    def key_of(self, item: WindowResult):
        return item.key

    def _engine_kwargs(self, replica):
        kw = {"domain_fn": lambda r: r.wid}
        if self.parent_win_type == WinType.TB:
            kw["wm_to_domain"] = lambda wm: wm // self.pane_len
        else:
            kw["count_complete"] = True
        return kw


class PanedWindows:
    """Composite: PLQ (tumbling panes of gcd(win, slide)) + WLQ (windows of
    panes) — reference ``paned_windows.hpp``, two ``Parallel_Windows`` stages.
    The user supplies a pane-level function and a window-level function, as in
    the reference builder."""

    def __init__(self, plq_fn, wlq_fn, spec: WindowSpec, *, name="paned_windows",
                 plq_parallelism=1, wlq_parallelism=1, key_extractor=None,
                 plq_incremental=False, wlq_incremental=False,
                 output_batch_size=0):
        pane_len = math.gcd(spec.win_len, spec.slide)
        if pane_len == 0:
            raise WindFlowError("window length and slide must be > 0")
        self.name = name
        pane_spec = WindowSpec(spec.win_type, pane_len, pane_len)
        self.plq = ParallelWindows(
            plq_fn, pane_spec, name=f"{name}_plq",
            parallelism=plq_parallelism, key_extractor=key_extractor,
            incremental=plq_incremental, role=WindowRole.PLQ)
        # WLQ windows live in the pane-id domain: R panes per window, sliding
        # by D panes.
        wlq_spec = WindowSpec(spec.win_type, spec.win_len // pane_len,
                              spec.slide // pane_len)
        wrapped = _wrap_result_fn(wlq_fn, wlq_incremental)
        self.wlq = _WLQWindows(
            wrapped, wlq_spec, pane_len=pane_len,
            parent_win_type=spec.win_type, name=f"{name}_wlq",
            parallelism=wlq_parallelism, key_extractor=None,
            incremental=wlq_incremental,
            output_batch_size=output_batch_size)

    def stages(self):
        return [self.plq, self.wlq]


class _WindowMergeReplica(Replica):
    """REDUCE stage of MapReduce_Windows: combine the ``p`` per-replica
    partials of each (key, gwid) window (reference REDUCE role +
    id-ordering, ``mapreduce_windows.hpp:130-141``)."""

    def __init__(self, op, index):
        super().__init__(op, index)
        self._pending = {}

    def process_single(self, item: WindowResult, ts, wm):
        k = (item.key, item.wid)
        bucket = self._pending.setdefault(k, [])
        bucket.append((item, ts))
        if len(bucket) == self.op.num_partials:
            self._flush_window(k)

    def _flush_window(self, k):
        bucket = self._pending.pop(k)
        items = [it for it, _ in bucket]
        ts = max(t for _, t in bucket)
        if self.op.incremental:
            acc = None
            for it in items:
                if it.value is not None:
                    acc = self.op.fn(it.value, acc)
            value = acc
        else:
            value = self.op.fn([it.value for it in items
                                if it.value is not None])
        self.stats.outputs_sent += 1
        wm = ts if self.current_wm == WM_NONE else min(self.current_wm, ts)
        self.emitter.emit(WindowResult(k[0], k[1], value), ts, wm)

    def on_eos(self):
        for k in sorted(self._pending, key=lambda kk: (stable_hash(kk[0]),
                                                       kk[1])):
            self._flush_window(k)


class _WindowMerge(Operator):
    replica_class = _WindowMergeReplica

    def __init__(self, fn, num_partials, *, name, parallelism, incremental,
                 output_batch_size=0):
        super().__init__(
            name, parallelism, routing=RoutingMode.KEYBY,
            output_batch_size=output_batch_size,
            key_extractor=lambda r: (stable_hash(r.key), r.wid))
        self.fn = fn
        self.num_partials = num_partials
        self.incremental = incremental


class MapReduceWindows:
    """Composite: MAP (each replica folds its share of every window's tuples)
    + REDUCE (merge the partials per window) — reference
    ``mapreduce_windows.hpp:67,130-141``."""

    def __init__(self, map_fn, reduce_fn, spec: WindowSpec, *,
                 name="mapreduce_windows", map_parallelism=1,
                 reduce_parallelism=1, key_extractor=None,
                 map_incremental=False, reduce_incremental=False,
                 output_batch_size=0):
        self.name = name
        self.map = ParallelWindows(
            map_fn, spec, name=f"{name}_map", parallelism=map_parallelism,
            key_extractor=key_extractor, incremental=map_incremental,
            role=WindowRole.MAP)
        self.reduce = _WindowMerge(
            reduce_fn, map_parallelism, name=f"{name}_reduce",
            parallelism=reduce_parallelism, incremental=reduce_incremental,
            output_batch_size=output_batch_size)

    def stages(self):
        return [self.map, self.reduce]


def _wrap_result_fn(fn, incremental):
    """WLQ user functions see pane *values*, not WindowResult wrappers."""
    if incremental:
        return lambda r, acc: fn(r.value, acc)
    return lambda results: fn([r.value for r in results])
