"""Ffat_Windows: incremental sliding/tumbling window aggregation over a
lift/combine pair, powered by per-key FlatFAT trees (reference
``/root/reference/wf/ffat_windows.hpp:63``, replica ``ffat_replica.hpp:59``).

* CB windows: one leaf per tuple (lifted); window [w*slide, w*slide+win)
  queried over tuple indices.
* TB windows: leaves are *quantum panes* of length gcd(win, slide) µs — the
  reference's TB path uses the same quantization (``ffat_replica.hpp`` TB
  quantum panes).  Tuples fold into their pane leaf; firing is gated by the
  watermark (+lateness) in DEFAULT mode and by the timestamp frontier in the
  ordered modes.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

from windflow_tpu.basic import (EMPTY_KEY, ExecutionMode, RoutingMode,
                                WindFlowError, WinType)
from windflow_tpu.batch import WM_NONE
from windflow_tpu.ops.base import Operator, Replica
from windflow_tpu.windows.engine import WindowSpec
from windflow_tpu.windows.flatfat import FlatFAT, next_pow2
from windflow_tpu.windows.ops import WindowResult


class _FfatKeyState:
    __slots__ = ("fat", "next_pos", "next_win", "max_ts", "started")

    def __init__(self, fat: FlatFAT):
        self.fat = fat
        self.next_pos = 0       # CB: next tuple index; TB: unused
        self.next_win = None    # next gwid to fire (None until first tuple)
        self.max_ts = 0
        self.started = False


class FfatWindowsReplica(Replica):
    def __init__(self, op: "FfatWindows", index: int) -> None:
        super().__init__(op, index)
        self._keys: Dict[Any, _FfatKeyState] = {}
        spec = op.spec
        if spec.win_type == WinType.CB:
            self._domain_win = spec.win_len
            self._domain_slide = spec.slide
            self._quantum = 1
        else:
            # TB: operate in the pane domain (quantum = gcd(win, slide) µs)
            self._quantum = math.gcd(spec.win_len, spec.slide)
            self._domain_win = spec.win_len // self._quantum
            self._domain_slide = spec.slide // self._quantum
        # ring must hold every pane of any unfired window, plus lateness slack
        slack = (op.lateness // self._quantum + 1
                 if op.spec.win_type == WinType.TB else 2)
        self._cap = next_pow2(self._domain_win + self._domain_slide + slack)

    # -- helpers -------------------------------------------------------------
    def _state(self, key) -> _FfatKeyState:
        st = self._keys.get(key)
        if st is None:
            st = self._keys[key] = _FfatKeyState(
                FlatFAT(self.op.comb, self._cap))
        return st

    def _win_start(self, w: int) -> int:
        return w * self._domain_slide

    def _win_end(self, w: int) -> int:
        return w * self._domain_slide + self._domain_win

    def _first_window_of(self, d: int) -> int:
        return max(0, -(-(d - self._domain_win + 1) // self._domain_slide))

    # -- ingestion -----------------------------------------------------------
    def process_single(self, item, ts, wm):
        op = self.op
        key = op.key_of(item)
        st = self._state(key)
        lifted = op.lift(item)
        if op.spec.win_type == WinType.CB:
            pos = st.next_pos
            st.next_pos += 1
            if not st.started:
                st.started = True
                st.next_win = 0
            st.fat.update(pos, lifted)
            st.max_ts = max(st.max_ts, ts)
            # fire every window completed by this tuple
            while self._win_end(st.next_win) <= st.next_pos:
                self._fire(key, st, st.next_win)
                st.next_win += 1
        else:
            pane = ts // self._quantum
            if self._domain_slide > self._domain_win \
                    and pane % self._domain_slide >= self._domain_win:
                # hopping windows with gaps (slide > win): panes in the
                # inter-window gap belong to NO window — never write them
                # into the ring (they would linger unevicted and fold into
                # whatever pane wraps onto their slot; the device kernel
                # masks these lanes the same way, ffat_kernels.py)
                return
            if not st.started:
                st.started = True
                st.next_win = self._first_window_of(pane)
            if st.next_win is not None \
                    and pane < self._win_start(st.next_win):
                self.stats.inputs_ignored += 1   # late beyond fired windows
                return
            # grow the ring if the watermark lag has widened the live span
            # beyond capacity (unfired windows pin old panes while new panes
            # keep arriving)
            span = pane - self._win_start(st.next_win) + self._domain_win
            if span >= st.fat.capacity:
                old = st.fat
                st.fat = FlatFAT(op.comb, next_pow2(span + 2))
                for p, v in old.live_items():
                    st.fat.update(p, v)
            st.fat.update(pane, lifted, fold=op.comb)
            st.max_ts = max(st.max_ts, ts)
            if self.mode != ExecutionMode.DEFAULT:
                # ordered input: fire windows ending at or before this
                # timestamp — equal timestamps may still arrive (legal ties),
                # so a window ending at ts+1 must NOT fire yet
                self._fire_tb(key, st, ts)

    def on_watermark(self, wm):
        if self.op.spec.win_type != WinType.TB or wm == WM_NONE \
                or self.mode != ExecutionMode.DEFAULT:
            return
        limit = wm - self.op.lateness
        # global window-end order across keys keeps output watermarks
        # monotone (see WindowEngine.on_watermark)
        ready = []
        for key, st in self._keys.items():
            if not st.started:
                continue
            w = st.next_win
            while self._win_end(w) * self._quantum <= limit:
                ready.append((self._win_end(w), key, w))
                w += 1
        ready.sort()
        for _, key, w in ready:
            st = self._keys[key]
            self._fire(key, st, w)
            st.next_win = w + 1

    def _fire_tb(self, key, st: _FfatKeyState, time_limit: int) -> None:
        # fire windows whose end time <= time_limit (ordered-mode eager path)
        while self._win_end(st.next_win) * self._quantum <= time_limit:
            self._fire(key, st, st.next_win)
            st.next_win += 1

    def _fire(self, key, st: _FfatKeyState, gwid: int,
              partial_end: Optional[int] = None) -> None:
        lo = self._win_start(gwid)
        hi = partial_end if partial_end is not None else self._win_end(gwid)
        value = st.fat.query(lo, hi)
        if value is not None:
            # windows are only materialized by the tuples they contain; empty
            # time windows emit nothing (reference: windows open on arrival)
            if self.op.spec.win_type == WinType.TB:
                ts = self._win_end(gwid) * self._quantum - 1
            else:
                ts = st.max_ts
            self.stats.outputs_sent += 1
            wm = ts if self.current_wm == WM_NONE \
                else min(self.current_wm, ts)
            self.emitter.emit(WindowResult(key, gwid, value), ts, wm)
        # evict leaves no longer needed by any future window
        next_lo = self._win_start(gwid + 1)
        for pos in range(lo, min(hi, next_lo)):
            st.fat.evict(pos)

    def on_eos(self):
        # flush remaining windows that have content (reference EOS flush)
        for key, st in self._keys.items():
            if not st.started:
                continue
            if self.op.spec.win_type == WinType.CB:
                last = st.next_pos  # exclusive
                while self._win_start(st.next_win) < last:
                    self._fire(key, st, st.next_win,
                               partial_end=min(self._win_end(st.next_win),
                                               last))
                    st.next_win += 1
            else:
                last_pane = st.max_ts // self._quantum + 1
                while self._win_start(st.next_win) < last_pane:
                    self._fire(key, st, st.next_win,
                               partial_end=min(self._win_end(st.next_win),
                                               last_pane))
                    st.next_win += 1


class FfatWindows(Operator):
    # host FlatFAT trees are not snapshot-capable yet (WF603)
    checkpoint_opaque = True
    """Keyed FlatFAT windows (reference ``Ffat_Windows``): KEYBY routing like
    Keyed_Windows, incremental lift/combine logic."""

    replica_class = FfatWindowsReplica

    def __init__(self, lift: Callable[[Any], Any],
                 comb: Callable[[Any, Any], Any], spec: WindowSpec, *,
                 name: str = "ffat_windows", parallelism: int = 1,
                 key_extractor: Optional[Callable] = None,
                 lateness: int = 0, output_batch_size: int = 0) -> None:
        routing = (RoutingMode.KEYBY if key_extractor is not None
                   else RoutingMode.FORWARD)
        if key_extractor is None and parallelism > 1:
            raise WindFlowError(
                "Ffat_Windows with parallelism > 1 requires a key extractor")
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.lift = lift
        self.comb = comb
        if lateness:
            import dataclasses
            spec = dataclasses.replace(spec, lateness=lateness)
        self.spec = spec

    @property
    def lateness(self) -> int:
        # single source of truth: the WindowSpec
        return self.spec.lateness

    def key_of(self, item):
        if self.key_extractor is None:
            return EMPTY_KEY
        return self.key_extractor(item)
