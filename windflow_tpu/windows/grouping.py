"""Stable O(n) dense-key grouping permutations (no comparison sort).

The FFAT steps group a batch by key (count-based) or by (key, pane)
(time-based) before folding runs.  The reference pays a comparison sort
for the same grouping (``thrust::sort_by_key``: ``flatfat_gpu.hpp`` via
``keyby_emitter_gpu.hpp:519-583``); this module replaces it with a stable
counting sort that exploits the dense-key contract (keys are ints in
``[0, K)``, enforced at the operator boundary):

1. a lane's rank *within its ``CHUNK``-lane chunk* among equal ids is
   ``CHUNK - 1`` shifted equality compares over the flat lane array —
   pure VPU work, no sort, no [C, C] pairwise tensor;
2. per-chunk bucket histograms (one O(n) scatter-add), exclusive-scanned
   across chunks (log-depth ``associative_scan`` — measured 3.5x faster
   than ``jnp.cumsum``'s lowering on CPU) to give each lane its
   cross-chunk offset, and across buckets to give each bucket its start;
3. ``dest = bucket_start[id] + cross_chunk[chunk, id] + within`` is then
   a *permutation* — one scatter of iota inverts it into gather indices.

Total work is O(n*C + (n/C)*nbuckets) element ops — O(n) for fixed
chunk/bucket sizes, minimized at C ~ sqrt(nbuckets) — versus the
O(n log n) comparison sort XLA lowers ``argsort`` to, with constants
that measure 3x+ worse on CPU (and bitonic O(n log^2 n) passes on TPU).
Bucket spaces wider than one digit (time-based pane ids) compose by LSD
radix over base-``DIGIT`` digits, each pass a stable single-digit
counting sort.

The permutation is bit-identical to ``jnp.argsort(ids, stable=True)``:
both order by (id, arrival position).  ``ffat_kernels`` keeps the argsort
path selectable (``Config.ffat_grouping``) so the equivalence is testable
on every platform.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

#: within-chunk width: within-rank costs (CHUNK-1) shifted compares per
#: lane, the cross-chunk prefix table costs (n/CHUNK)*nbuckets — 32 sits
#: at the measured CPU optimum for the 256-bucket digit below.
CHUNK = 32
#: radix base: buckets per counting pass (+1 padding bucket per pass).
DIGIT = 256


def dense_rank(ids, nbuckets: int):
    """Per-lane rank among equal ids in arrival order, plus bucket counts.

    ``rank[i]`` = number of earlier lanes with the same id; ``counts[b]`` =
    occurrences of id ``b``.  The O(n) core shared by the permutation below
    and the scatter-add fast path (``make_ffat_step`` with a declared-sum
    combiner, which needs each tuple's position within its key but never a
    sorted layout).  Returns ``(rank, counts, idsp, pos)`` where ``rank``,
    ``idsp`` and ``pos`` are chunk-padded to length ``Bp >= B`` (padding
    lanes rank 0.. in their own bucket past the real ones); callers slice
    ``[:B]``."""
    B = ids.shape[0]
    C = CHUNK
    Bp = ((B + C - 1) // C) * C
    # padding lanes count into a dedicated bucket after every real one
    nb = nbuckets + 1
    idsp = ids.astype(jnp.int32)
    if Bp != B:
        idsp = jnp.concatenate(
            [idsp, jnp.full(Bp - B, nbuckets, jnp.int32)])
    NB = Bp // C
    pos = jnp.arange(Bp, dtype=jnp.int32)
    lane = pos % C

    # 1. within-chunk rank among equal ids (arrival order): count equal
    # ids in the C-1 earlier lanes of the same chunk
    within = jnp.zeros(Bp, jnp.int32)
    for d in range(1, C):
        shifted = jnp.pad(idsp, (d, 0))[:Bp]
        within = within + ((idsp == shifted) & (lane >= d))

    # 2. per-chunk histograms + exclusive scan across chunks
    flat = (pos // C) * nb + idsp
    hist = jnp.zeros(NB * nb, jnp.int32).at[flat].add(1).reshape(NB, nb)
    cross = lax.associative_scan(jnp.add, hist, axis=0) - hist
    counts = jnp.sum(hist, axis=0)
    rank = within + cross.reshape(-1)[flat]
    return rank, counts[:nbuckets], idsp, pos


def _single_digit_order(ids, nbuckets: int):
    """Stable counting-sort permutation for ids in ``[0, nbuckets)``,
    ``nbuckets`` one digit wide.  Returns gather indices ``order`` with
    ``ids[order]`` sorted, ties in arrival order."""
    order, _ = _single_digit_order_counts(ids, nbuckets)
    return order


def _single_digit_order_counts(ids, nbuckets: int):
    """``_single_digit_order`` plus the ``[nbuckets]`` histogram of ids —
    the ``dense_rank`` byproduct callers would otherwise recompute with a
    second full-length scatter-add."""
    B = ids.shape[0]
    rank, counts, idsp, pos = dense_rank(ids, nbuckets)
    Bp = pos.shape[0]
    # padding lanes went to the bucket AFTER every real one; being the
    # last-arriving members of the last bucket they occupy the tail of
    # the permutation, so ``order[:B]`` contains exactly the real lanes
    allc = jnp.concatenate(
        [counts, jnp.asarray([Bp - B], jnp.int32)])
    start = lax.associative_scan(jnp.add, allc) - allc

    # 3. dest is a permutation of [0, Bp): invert by scattering iota
    dest = start[idsp] + rank
    order = jnp.zeros(Bp, jnp.int32).at[dest].set(pos, unique_indices=True)
    return order[:B], counts


def invert_perm(order):
    """Invert a permutation in O(n): ``inv[order[i]] = i`` via one scatter
    of iota — replaces the ``argsort(order)`` idiom (a full comparison
    sort of something already known to be a permutation)."""
    n = order.shape[0]
    return jnp.zeros(n, order.dtype).at[order].set(
        jnp.arange(n, dtype=order.dtype), unique_indices=True)


def auto_order(ids, nbuckets: int):
    """Stable grouping permutation with an automatic algorithm choice:
    the O(n) counting permutation while it needs at most two radix passes
    (bucket spaces up to ``DIGIT^2``), the comparison argsort beyond —
    at 3+ passes the counting constant catches the O(n log n) sort's.
    Bit-identical either way (both order by (id, arrival))."""
    if nbuckets <= DIGIT * DIGIT:
        return counting_order(ids, nbuckets)
    return jnp.argsort(ids, stable=True)


def order_and_hist(ids, nbuckets: int):
    """``auto_order`` plus the ``[nbuckets]`` histogram of ids.  On the
    single-counting-pass path the histogram is the ``dense_rank``
    byproduct — free; the radix and argsort paths pay one O(n)
    scatter-add (the per-digit passes count digit buckets, never the
    full id space, so there is nothing to reuse there)."""
    if nbuckets <= DIGIT + 1:
        return _single_digit_order_counts(ids, nbuckets)
    order = auto_order(ids, nbuckets)
    hist = jnp.zeros(nbuckets, jnp.int32).at[ids.astype(jnp.int32)].add(1)
    return order, hist


def counting_order(ids, nbuckets: int):
    """Stable grouping permutation over dense int ids in ``[0, nbuckets)``
    (out-of-range ids must already be clamped by the caller — the FFAT
    steps map invalid lanes to bucket ``nbuckets - 1``).

    Equivalent to ``jnp.argsort(ids, stable=True)`` for such ids, in O(n):
    single counting pass up to ``DIGIT + 1`` buckets, LSD radix over
    base-``DIGIT`` digits beyond (each pass stable, so the composition
    orders by the full id, then arrival)."""
    if nbuckets <= DIGIT + 1:
        return _single_digit_order(ids, nbuckets)
    ids = ids.astype(jnp.int32)
    order = None
    div = 1
    span = nbuckets
    while span > 1:
        cur = ids if order is None else ids[order]
        o = _single_digit_order((cur // div) % DIGIT, DIGIT)
        order = o if order is None else order[o]
        div *= DIGIT
        span = -(-span // DIGIT)
    return order
