"""FfatWindowsTPU: incremental sliding-window aggregation on TPU.

Device equivalent of the reference's ``Ffat_Windows_GPU``
(``/root/reference/wf/ffat_replica_gpu.hpp:424``, ``flatfat_gpu.hpp:143``),
re-designed for XLA rather than translated from CUDA:

* The reference lifts tuples into pane aggregates with per-key kernels
  (``ffat_replica_gpu.hpp:92-216`` lift, ``Aggregate_Panes_Kernel``); here the
  whole batch is sorted by key once and panes are built with a segmented
  ``associative_scan`` — the XLA expression of the same reduction.
* The reference maintains a per-key FlatFAT tree on device and computes
  ``numWinsPerBatch`` window results per launch (``flatfat_gpu.hpp:60-139``).
  Here per-key state is **dense over a static key space** [0, max_keys): a
  carry ring of the trailing R-1 pane aggregates per key plus the current
  partial pane.  Window results gather their R panes and reduce them with a
  log-depth scan, for every key and every fired window in one fused program —
  the "batch many windows per launch" trick (``builders_gpu.hpp:576``
  ``withNumWinPerBatch``) taken to its TPU conclusion: *all* windows a batch
  completes, across *all* keys, in one launch.
* Count-based windows of length W sliding by S decompose into panes of
  P = gcd(W, S) (same decomposition as the reference's pane logic): R = W/P
  panes per window, fired every D = S/P panes.

Invariants/contract:
* key extractor is JAX-traceable and returns ints in [0, max_keys);
  out-of-range keys are dropped (masked), as are invalid lanes.
* ``lift`` maps a record pytree to an aggregate pytree; ``comb`` is an
  associative combiner of aggregates.  No identity element is required.
* One step processes one fixed-capacity batch; all shapes are static, so the
  program compiles exactly once per batch capacity.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from windflow_tpu.basic import RoutingMode, WindFlowError, WinType
from windflow_tpu.batch import DeviceBatch
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.tpu import _TPUReplica
from windflow_tpu.windows.engine import WindowSpec


def _seg_scan(comb, flags, values):
    """Inclusive segmented scan: within each flagged segment, fold ``comb``.
    ``values`` is a pytree of [B, ...] leaves; ``flags`` [B] marks segment
    starts."""
    def op(a, b):
        fa, va = a
        fb, vb = b
        combined = comb(va, vb)
        v = jax.tree.map(
            lambda c, nb: jnp.where(_b(fb, c), nb, c), combined, vb)
        return (fa | fb, v)

    _, scanned = jax.lax.associative_scan(op, (flags, values))
    return scanned


def _masked_reduce_last(comb, flags, values, axis):
    """Reduce ``values`` along ``axis`` with ``comb``, skipping entries whose
    flag is False; returns (any_flag, reduction).  Flag-aware monoid:
    associative, no identity needed."""
    def op(a, b):
        fa, va = a
        fb, vb = b
        both = comb(va, vb)
        v = jax.tree.map(
            lambda c, xa, xb: jnp.where(_b(fb, c), jnp.where(_b(fa, c), c, xb),
                                        xa), both, va, vb)
        return (fa | fb, v)

    f, v = jax.lax.associative_scan(op, (flags, values), axis=axis)
    take = lambda x: jax.lax.index_in_dim(x, x.shape[axis] - 1, axis,
                                          keepdims=False)
    return take(f), jax.tree.map(take, v)


def _b(mask, ref):
    """Broadcast a bool mask against a leaf with trailing dims."""
    return mask.reshape(mask.shape + (1,) * (ref.ndim - mask.ndim))


class FfatTPUReplica(_TPUReplica):
    def on_eos(self):
        out = self.op._flush()
        if out is not None:
            self.stats.device_programs_launched += 1
            self.emitter.emit_device_batch(out)


class FfatWindowsTPU(Operator):
    replica_class = FfatTPUReplica

    def __init__(self, lift: Callable, comb: Callable, spec: WindowSpec, *,
                 max_keys: int, name: str = "ffat_windows_tpu",
                 parallelism: int = 1,
                 key_extractor: Optional[Callable] = None) -> None:
        if spec.win_type != WinType.CB:
            raise WindFlowError(
                "FfatWindowsTPU currently supports count-based windows "
                "(time-based via quantum panes is planned; use the host "
                "Ffat_Windows for TB)")
        routing = (RoutingMode.KEYBY if key_extractor is not None
                   else RoutingMode.FORWARD)
        super().__init__(name, parallelism, routing=routing, is_tpu=True,
                         key_extractor=key_extractor)
        self.lift = lift
        self.comb = comb
        self.spec = spec
        self.max_keys = max_keys
        self.P = math.gcd(spec.win_len, spec.slide)
        self.R = spec.win_len // self.P
        self.D = spec.slide // self.P
        self._state = None          # device state, created on first batch
        self._jit_step = None
        self._jit_flush = None
        self._capacity = None
        self._flushed = False

    # -- state layout --------------------------------------------------------
    def _init_state(self, agg_spec):
        K, R = self.max_keys, self.R
        zeros = lambda shape: jax.tree.map(
            lambda s: jnp.zeros(shape + s.shape, s.dtype), agg_spec)
        return {
            "carry": zeros((K, R - 1)),               # trailing R-1 panes
            "carry_valid": jnp.zeros((K, R - 1), bool),
            "cur": zeros((K,)),                       # partial pane aggregate
            "cur_valid": jnp.zeros((K,), bool),
            "cur_fill": jnp.zeros((K,), jnp.int32),   # tuples in partial pane
            "pane_base": jnp.zeros((K,), jnp.int64),  # completed panes
            "win_next": jnp.full((K,), self.R, jnp.int64),  # next end pane
        }

    # -- per-batch program ---------------------------------------------------
    def _build_step(self, capacity: int):
        K, P, R, D = self.max_keys, self.P, self.R, self.D
        NP1 = capacity // P + 2           # pane cells incl. continuation cell
        MW = (capacity // P) // D + 2     # max windows fired per batch
        lift, comb, key_fn = self.lift, self.comb, self.key_extractor

        def step(state, payload, ts, valid):
            B = capacity
            keys = jax.vmap(key_fn)(payload).astype(jnp.int32) \
                if key_fn is not None else jnp.zeros(B, jnp.int32)
            ok = valid & (keys >= 0) & (keys < K)
            skey_for_sort = jnp.where(ok, keys, K)
            order = jnp.argsort(skey_for_sort, stable=True)
            sk = skey_for_sort[order]
            slift = jax.tree.map(lambda a: a[order],
                                 jax.vmap(lift)(payload))
            pos = jnp.arange(B)
            starts = jnp.concatenate([jnp.array([True]), sk[1:] != sk[:-1]])
            seg_start_pos = jax.lax.associative_scan(
                jnp.maximum, jnp.where(starts, pos, 0))
            rank = pos - seg_start_pos

            n_k = jax.ops.segment_sum(ok[order].astype(jnp.int32), sk,
                                      num_segments=K + 1)[:K]
            fill0 = state["cur_fill"][jnp.minimum(sk, K - 1)]
            pane_rel = ((fill0 + rank) // P).astype(jnp.int32)

            # pane partials: segmented scan over (key, pane) runs
            pane_starts = starts | jnp.concatenate(
                [jnp.array([True]), pane_rel[1:] != pane_rel[:-1]])
            scanned = _seg_scan(comb, pane_starts, slift)
            ends = jnp.concatenate(
                [(sk[1:] != sk[:-1]) | (pane_rel[1:] != pane_rel[:-1]),
                 jnp.array([True])])
            # scatter segment-end partials into dense [K+1, NP1] cells
            row = jnp.where(ends, sk, K)
            col = jnp.where(ends, pane_rel, 0)
            def scat(leaf):
                buf = jnp.zeros((K + 1, NP1) + leaf.shape[1:], leaf.dtype)
                return buf.at[row, col].set(
                    jnp.where(_b(ends, leaf), leaf, 0))[:K]
            cells = jax.tree.map(scat, scanned)
            cell_has = jnp.zeros((K + 1, NP1), bool) \
                .at[row, col].set(ends)[:K]

            # merge continuation cell with the carried partial pane
            def merge0(cur_leaf, cell_leaf):
                both = comb(cur_leaf, cell_leaf[:, 0])
                use_cur = state["cur_valid"]
                use_cell = cell_has[:, 0]
                v = jnp.where(_b(use_cur & use_cell, both), both,
                              jnp.where(_b(use_cur, both), cur_leaf,
                                        cell_leaf[:, 0]))
                return cell_leaf.at[:, 0].set(v)
            cells = jax.tree.map(
                lambda cur_leaf, cell_leaf: merge0(cur_leaf, cell_leaf),
                state["cur"], cells)

            m_k = ((state["cur_fill"] + n_k) // P).astype(jnp.int32)
            new_fill = ((state["cur_fill"] + n_k) % P).astype(jnp.int32)

            # full pane sequence: carry (R-1 trailing) + this batch's panes
            full = jax.tree.map(
                lambda c, p: jnp.concatenate([c, p], axis=1),
                state["carry"], cells)
            col_ix = jnp.arange(NP1)[None, :]
            pane_valid = col_ix < m_k[:, None]
            full_valid = jnp.concatenate([state["carry_valid"], pane_valid],
                                         axis=1)

            # fire windows: end panes e = win_next + j*D while e <= done
            done = state["pane_base"] + m_k
            j = jnp.arange(MW, dtype=jnp.int64)
            e = state["win_next"][:, None] + j[None, :] * D        # [K, MW]
            fired = e <= done[:, None]
            local_end = (e - state["pane_base"][:, None]
                         + (R - 1)).astype(jnp.int32)              # exclusive
            gidx = jnp.clip(local_end[:, :, None] - R
                            + jnp.arange(R)[None, None, :],
                            0, R - 1 + NP1 - 1)                    # [K,MW,R]

            def gather_leaf(a):
                # a: [K, R-1+NP1, ...] -> [K, MW, R, ...]
                expanded = jnp.broadcast_to(
                    a[:, None], (K, MW) + a.shape[1:])
                idx = gidx.reshape(K, MW, R, *([1] * (a.ndim - 2)))
                idx = jnp.broadcast_to(idx, (K, MW, R) + a.shape[2:])
                return jnp.take_along_axis(expanded, idx, axis=2)
            wpanes = jax.tree.map(gather_leaf, full)
            _, wvals = _masked_reduce_last(
                comb, jnp.ones((K, MW, R), bool), wpanes, axis=2)

            n_fired = jnp.where(
                fired[:, 0],
                ((done - state["win_next"]) // D + 1), 0)
            new_win_next = state["win_next"] + n_fired * D

            # new carry: panes [pane_base+m_k-(R-1), pane_base+m_k)
            cidx = m_k[:, None] + jnp.arange(R - 1)[None, :]       # [K, R-1]
            def carry_leaf(a):
                idx = cidx.reshape(K, R - 1, *([1] * (a.ndim - 2)))
                idx = jnp.broadcast_to(idx, (K, R - 1) + a.shape[2:])
                return jnp.take_along_axis(a, idx, axis=1)
            new_carry = jax.tree.map(carry_leaf, full)
            new_carry_valid = jnp.take_along_axis(full_valid, cidx, axis=1)

            def cur_leaf(cell_leaf):
                idx = m_k.reshape(K, 1, *([1] * (cell_leaf.ndim - 2)))
                idx = jnp.broadcast_to(idx, (K, 1) + cell_leaf.shape[2:])
                return jnp.take_along_axis(cell_leaf, idx, axis=1)[:, 0]
            new_cur = jax.tree.map(cur_leaf, cells)
            new_cur_valid = new_fill > 0

            new_state = {
                "carry": new_carry,
                "carry_valid": new_carry_valid,
                "cur": new_cur,
                "cur_valid": new_cur_valid,
                "cur_fill": new_fill,
                "pane_base": done,
                "win_next": new_win_next,
            }

            # output batch: one row per (key, window-slot)
            wid = (e - R) // D
            out_keys = jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None], (K, MW))
            out_ts = jnp.broadcast_to(
                jnp.max(jnp.where(valid, ts, 0)), (K, MW))
            out = {
                "key": out_keys.reshape(-1),
                "wid": wid.reshape(-1),
                "value": jax.tree.map(
                    lambda a: a.reshape((K * MW,) + a.shape[2:]), wvals),
            }
            return new_state, out, fired.reshape(-1), out_ts.reshape(-1)

        return jax.jit(step, donate_argnums=(0,))

    # -- operator plumbing ---------------------------------------------------
    def _ensure(self, batch: DeviceBatch):
        if self._state is None:
            one = jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape[1:],
                                                              a.dtype),
                               batch.payload)
            agg_spec = jax.eval_shape(self.lift, one)
            agg_spec = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), agg_spec)
            self._state = self._init_state(agg_spec)
            self._capacity = batch.capacity
            self._jit_step = self._build_step(batch.capacity)
        elif batch.capacity != self._capacity:
            raise WindFlowError(
                "FfatWindowsTPU requires a fixed upstream batch capacity "
                f"({self._capacity}), got {batch.capacity}")

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        self._ensure(batch)
        self._state, out, fired, out_ts = self._jit_step(
            self._state, batch.payload, batch.ts, batch.valid)
        return DeviceBatch(out, out_ts, fired, keys=out["key"],
                           watermark=batch.watermark, size=None)

    def _flush(self) -> Optional[DeviceBatch]:
        """EOS: fire remaining partial windows (reference EOS flush of open
        windows).  Runs a dedicated flush program over the carried state.
        State is operator-level (one logical device table regardless of
        replica count), so only the first replica to reach EOS flushes."""
        if self._state is None or self._flushed:
            return None
        self._flushed = True
        if self._jit_flush is None:
            self._jit_flush = self._build_flush()
        out, fired, ts = self._jit_flush(self._state)
        return DeviceBatch(out, ts, fired, keys=out["key"], watermark=0,
                           size=None)

    def _build_flush(self):
        K, P, R, D = self.max_keys, self.P, self.R, self.D
        MWF = R // D + 2
        comb = self.comb

        def flush(state):
            # total panes including the partial pane
            has_cur = state["cur_valid"]
            total = state["pane_base"] + has_cur.astype(jnp.int64)
            # available pane history: carry (R-1) + cur  -> [K, R]
            hist = jax.tree.map(
                lambda c, cur: jnp.concatenate([c, cur[:, None]], axis=1),
                state["carry"], state["cur"])
            hist_valid = jnp.concatenate(
                [state["carry_valid"], has_cur[:, None]], axis=1)
            # hist column i holds pane (pane_base - (R-1) + i)
            j = jnp.arange(MWF, dtype=jnp.int64)
            e = state["win_next"][:, None] + j[None, :] * D
            start = e - R
            fire = start < total[:, None]
            # gather window panes from hist: local = pane - pane_base + R-1
            lidx = (start[:, :, None] + jnp.arange(R)[None, None, :]
                    - state["pane_base"][:, None, None] + (R - 1))
            inb = (lidx >= 0) & (lidx < R)
            lidx_c = jnp.clip(lidx, 0, R - 1).astype(jnp.int32)
            pane_ok = jnp.take_along_axis(
                jnp.broadcast_to(hist_valid[:, None], (K, MWF, R)),
                lidx_c, axis=2) & inb
            # panes must also be < total (cur counts once)
            pane_abs = start[:, :, None] + jnp.arange(R)[None, None, :]
            pane_ok = pane_ok & (pane_abs < total[:, None, None]) \
                & (pane_abs >= 0)
            def gather_leaf(a):
                expanded = jnp.broadcast_to(a[:, None], (K, MWF) + a.shape[1:])
                idx = lidx_c.reshape(K, MWF, R, *([1] * (a.ndim - 2)))
                idx = jnp.broadcast_to(idx, (K, MWF, R) + a.shape[2:])
                return jnp.take_along_axis(expanded, idx, axis=2)
            wpanes = jax.tree.map(gather_leaf, hist)
            any_ok, wvals = _masked_reduce_last(comb, pane_ok, wpanes, axis=2)
            fired = fire & any_ok
            wid = (e - R) // D
            out = {
                "key": jnp.broadcast_to(
                    jnp.arange(K, dtype=jnp.int32)[:, None],
                    (K, MWF)).reshape(-1),
                "wid": wid.reshape(-1),
                "value": jax.tree.map(
                    lambda a: a.reshape((K * MWF,) + a.shape[2:]), wvals),
            }
            ts = jnp.zeros((K * MWF,), jnp.int64)
            return out, fired.reshape(-1), ts

        return jax.jit(flush)
