"""FfatWindowsTPU: incremental sliding-window aggregation on TPU.

Device equivalent of the reference's ``Ffat_Windows_GPU``
(``/root/reference/wf/ffat_replica_gpu.hpp:424``, ``flatfat_gpu.hpp:143``),
re-designed for XLA rather than translated from CUDA:

* The reference lifts tuples into pane aggregates with per-key kernels
  (``ffat_replica_gpu.hpp:92-216`` lift, ``Aggregate_Panes_Kernel``); here the
  whole batch is sorted by key once and panes are built with a segmented
  ``associative_scan`` — the XLA expression of the same reduction.
* The reference maintains a per-key FlatFAT tree on device and computes
  ``numWinsPerBatch`` window results per launch (``flatfat_gpu.hpp:60-139``).
  Here per-key state is **dense over a static key space** [0, max_keys): a
  carry ring of the trailing R-1 pane aggregates per key plus the current
  partial pane.  Window results gather their R panes and reduce them with a
  log-depth scan, for every key and every fired window in one fused program —
  the "batch many windows per launch" trick (``builders_gpu.hpp:576``
  ``withNumWinPerBatch``) taken to its TPU conclusion: *all* windows a batch
  completes, across *all* keys, in one launch.
* Count-based windows of length W sliding by S decompose into panes of
  P = gcd(W, S) (same decomposition as the reference's pane logic): R = W/P
  panes per window, fired every D = S/P panes.

Invariants/contract:
* key extractor is JAX-traceable and returns ints in [0, max_keys);
  out-of-range keys are dropped (masked), as are invalid lanes.
* ``lift`` maps a record pytree to an aggregate pytree; ``comb`` is an
  associative combiner of aggregates.  No identity element is required.
* One step processes one fixed-capacity batch; all shapes are static, so the
  program compiles exactly once per batch capacity.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from windflow_tpu.basic import RoutingMode, WindFlowError, WinType
from windflow_tpu.batch import WM_NONE, DeviceBatch
from windflow_tpu.monitoring.jit_registry import wf_jit
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.tpu import _TPUReplica
from windflow_tpu.windows.engine import WindowSpec
from windflow_tpu.windows.ffat_kernels import (agg_spec_for,
                                               make_ffat_flush,
                                               make_ffat_state,
                                               make_ffat_step,
                                               make_ffat_tb_state,
                                               make_ffat_tb_step,
                                               resolve_monoid)


class FfatTPUReplica(_TPUReplica):
    def _op_step(self, batch):
        return self.op._step(batch, self.index)

    def on_eos(self):
        if self.op.is_tb and self.op._per_replica_state:
            # Keyed TB state is PER REPLICA (each replica owns its key
            # partition's pane ring and clock — independent partitions'
            # watermark frontiers must never advance each other's rings),
            # so every replica flushes its own state at its own EOS.
            outs = self.op._flush_tb(self.index)
        elif self.op.is_tb:
            # FORWARD-routed TB: batches round-robin over replicas into ONE
            # shared state (no key partition exists to split it by), so the
            # last replica to terminate flushes it once.
            self.op._eos_replicas += 1
            if self.op._eos_replicas < self.op.parallelism:
                return
            outs = self.op._flush_tb(0)
        else:
            # CB state is operator-level (per-key clock lanes make the one
            # dense table safe under key partitioning); only the LAST
            # replica to terminate may flush it — earlier-terminating
            # siblings' peers might still hold queued data batches whose
            # tuples belong in the open windows.
            self.op._eos_replicas += 1
            if self.op._eos_replicas < self.op.parallelism:
                return
            outs = self.op._flush()
        for out in outs:
            self.stats.device_programs_launched += 1
            # flush outputs carry size=None; .size counts the fired mask
            # (one device sync each — EOS only, never the hot path)
            self.stats.outputs_sent += out.size
            self.emitter.emit_device_batch(out)


class FfatWindowsTPU(Operator):
    """Count-based windows use the rank/pane decomposition
    (``make_ffat_step``); time-based windows use quantum panes — pane =
    ``ts // gcd(win, slide)`` — over a rolling per-key pane ring with
    watermark-driven firing (``make_ffat_tb_step``; reference TB lift
    kernels, ``ffat_replica_gpu.hpp:92-216``)."""

    replica_class = FfatTPUReplica
    fixed_capacity_label = "FfatWindowsTPU"

    #: compacted key space (parallel/compaction.py): True when the graph
    #: build attached a KeyCompactor — ``max_keys`` then bounds the SLOT
    #: space, not the user's (arbitrary int32) key space
    _compact_keys = False

    def __init__(self, lift: Callable, comb: Callable, spec: WindowSpec, *,
                 max_keys: Optional[int], name: str = "ffat_windows_tpu",
                 parallelism: int = 1,
                 key_extractor: Optional[Callable] = None,
                 pane_capacity: Optional[int] = None,
                 overflow_policy: str = "drop",
                 sum_like: bool = False,
                 monoid: Optional[str] = None) -> None:
        routing = (RoutingMode.KEYBY if key_extractor is not None
                   else RoutingMode.FORWARD)
        super().__init__(name, parallelism, routing=routing, is_tpu=True,
                         key_extractor=key_extractor)
        self.lift = lift
        self.comb = comb
        self.spec = spec
        #: None = compacted key space (withCompactedKeys): the graph
        #: build assigns the slot bound via enable_compaction; running
        #: without it (kill switch / no graph) fails at the first batch
        #: with a clear message (see _ensure)
        self.max_keys = max_keys
        if max_keys is None and key_extractor is None:
            raise WindFlowError(
                f"FfatWindowsTPU '{name}': a compacted key space "
                "(withCompactedKeys) requires withKeyBy — non-keyed "
                "windows use withMaxKeys(1)")
        self._cstats = None
        self.P = math.gcd(spec.win_len, spec.slide)
        self.R = spec.win_len // self.P
        self.D = spec.slide // self.P
        self.is_tb = spec.win_type == WinType.TB
        # TB pane ring contract: the ring must cover the window span, plus
        # the time spread of any single batch (including idle gaps *inside*
        # a batch — gaps between batches cost nothing, pre-gap windows fire
        # before the ring rolls), plus the lateness allowance in panes
        # (lateness holds windows open, so their panes stay pinned in the
        # ring).  Exceeding it is overload: panes are evicted and counted
        # (n_evicted).  When not set via withPaneCapacity, the ring is
        # auto-sized at the first batch to one batch's worth of panes
        # (capped at 8192) — keyed partitioning concentrates one key's
        # tuples, so a partition batch of C tuples can span C panes.
        self.NP = pane_capacity
        if self.is_tb and pane_capacity is not None                 and pane_capacity < 2 * self.R:
            # >= 2R also guarantees the step's two pre-place fire passes
            # reach every window over in-ring data (ffat_kernels docstring)
            raise WindFlowError(
                "pane_capacity must be at least 2*win/gcd panes")
        if self.is_tb and key_extractor is None and parallelism > 1:
            # FORWARD round-robin at parallelism > 1 would interleave
            # batches into the shared ring in replica-drain order, not
            # arrival order — a later-frontier batch on one replica could
            # fire windows before an earlier batch on a sibling is placed.
            # Keyed routing (withKeyBy) is the scaling path, exactly as the
            # reference scales windows by key partitioning.
            raise WindFlowError(
                "non-keyed time-based FfatWindowsTPU requires "
                "parallelism == 1; use withKeyBy to scale")
        if overflow_policy not in ("drop", "count", "error"):
            raise WindFlowError(
                f"unknown overflow policy '{overflow_policy}' "
                "(drop | count | error)")
        #: TB ring-overflow policy: "drop" (default) suppresses windows
        #: that lost data panes and counts them; "count" fires them over
        #: the surviving panes only (wrong aggregates, n_evicted counts);
        #: "error" raises at the next host checkpoint.  The reference never
        #: fires a wrong window (its FlatFAT grows instead).
        self.overflow_policy = overflow_policy
        #: declared leafwise-monoid combiner ("sum" | "max" | "min";
        #: withSumCombiner == monoid "sum", withMonoidCombiner for the
        #: rest): CB drops the fold's flag lane and skips the grouping
        #: permutation (scatter-combine pane cells); TB skips grouping
        #: entirely — pane placement is timestamp arithmetic, lifts
        #: scatter-combine into the ring.  The declaration must match the
        #: combiner exactly (declaring "sum" for a max combiner silently
        #: computes sums).
        try:
            self.monoid = resolve_monoid(sum_like, monoid)
        except ValueError as e:
            raise WindFlowError(str(e)) from None
        self._overflow_steps = 0
        self._auto_np = False          # NP chosen by the span estimator
        self._np_ceil = None
        self._evicted_seen = 0         # n_evicted at the last regrow check
        self._pending_evct = None      # lazy counter read (one cadence old)
        self._evicted_base = 0         # evictions excused as regrow pains
        self._error_armed = False      # error policy live (post-transient)
        self._clean_checks = 0
        self._dirty_checks = 0
        # data-ts extrema observed while the multi-channel watermark fold
        # is still unresolved (frontier == WM_NONE): nothing fires in that
        # phase, so every placed pane stays live and the ring must cover
        # exactly this spread (see _regrow_for_span)
        self._unres_lo = None
        self._unres_hi = None
        # True once a step ran with a RESOLVED frontier: before that
        # nothing has fired, so the ring may be REBASED down to re-cover
        # panes the capacity roll slid past while a sibling channel was
        # still unheard (see _rebase_ring); after it, panes below the
        # fired frontier are closed and only upward growth is safe
        self._fold_stepped = False
        # Device state, created on first batch.  CB: one shared table (key
        # 0) — per-key clock lanes make it partition-safe.  TB: one state
        # PER REPLICA index — the ring clocks are shared across a state's
        # keys, so each key partition needs its own.
        self._states = {}
        self._jit_step = None
        self._jit_flush = None
        self._capacity = None
        self._payload_zero = None   # all-invalid batch for TB EOS flush
        self._flushed = False
        self._eos_replicas = 0

    def enable_compaction(self, comp) -> None:
        """Attach a pinned KeyCompactor (graph build): arbitrary int32
        keys map to stable dense slots through the device-resident remap
        table, and ``max_keys`` becomes the SLOT bound — the pane rings
        stay dense over [0, slots) exactly as under withMaxKeys.
        Unmapped keys (host admission never saw them: device-born
        streams before a reseed catches up) are masked invalid and
        counted, the operator's existing out-of-range contract."""
        self._compactor = comp
        self._compact_keys = True
        self.max_keys = comp.slots
        comp.register_device_stats(lambda: self._cstats)

    # -- state layout --------------------------------------------------------
    def _init_state(self, agg_spec):
        if self.mesh is not None:
            from windflow_tpu.parallel.mesh import (
                make_sharded_ffat_state, make_sharded_ffat_tb_state)
            if self.is_tb:
                return make_sharded_ffat_tb_state(
                    agg_spec, self.max_keys, self.NP, self.mesh)
            return make_sharded_ffat_state(agg_spec, self.max_keys, self.R,
                                           self.mesh)
        if self.is_tb:
            return make_ffat_tb_state(agg_spec, self.max_keys, self.NP)
        return make_ffat_state(agg_spec, self.max_keys, self.R)

    # -- per-batch program ---------------------------------------------------
    def _build_step(self, capacity: int):
        if self.mesh is not None:
            # Multi-chip: key-sharded state, data-sharded batches riding an
            # all_gather over ICI (parallel/mesh.py make_sharded_ffat_step).
            # Config.mesh is how the graph API reaches the sharded kernels.
            from windflow_tpu.parallel.mesh import (make_sharded_ffat_step,
                                                    make_sharded_ffat_tb_step)
            # multi-process graphs stage batches fully sharded over
            # (data, key) — the only layout each process can assemble from
            # the lanes IT ingested — so the step gathers over both axes
            # (mesh.py _ffat_shard_layout "flat").  "aligned" is set by
            # the graph build (Config.key_aligned_ingest) when every
            # feeding edge is a host staging edge routed through the
            # key-aligned emitter: the host pre-places each tuple on its
            # key-owner column, so the step skips the all_gather that
            # dominates the modeled ICI bytes (parallel/emitters.
            # AlignedMeshStageEmitter; docs/OBSERVABILITY.md wire plane).
            ingest = getattr(self, "_ingest_mode", None) \
                or ("flat" if jax.process_count() > 1 else "data")
            if self.is_tb:
                return make_sharded_ffat_tb_step(
                    self.mesh, capacity, self.max_keys, self.P, self.R,
                    self.D, self.NP, self.lift, self.comb,
                    self.key_extractor,
                    drop_tainted=self.overflow_policy == "drop",
                    grouping=self._grouping(), ingest=ingest,
                    monoid=self.monoid, op_name=f"{self.name}.mesh")
            return make_sharded_ffat_step(
                self.mesh, capacity, self.max_keys, self.P, self.R, self.D,
                self.lift, self.comb, self.key_extractor,
                monoid=self.monoid, grouping=self._grouping(),
                ingest=ingest, op_name=f"{self.name}.mesh")
        # Pallas kernel selection (windflow_tpu/kernels): resolved once
        # per program build against Config.pallas_kernels + the runtime
        # backend; the kernels trace into this same wf_jit program, so
        # fused preludes, regrow rebuilds, and restore all keep them.
        # Mesh programs above stay on the lax path (kernels inside
        # shard_map are a future round).
        pallas = self._pallas_mode()
        comp = self._compactor
        if comp is None:
            lift, key_fn = self.lift, self.key_extractor
        else:
            # compacted key space: the kernel sees {"rec": record,
            # "slot": dense id} lanes — the slot lane is resolved by the
            # remap lookup in the wrapper below, inside this SAME program
            user_lift = self.lift
            lift = lambda r: user_lift(r["rec"])  # noqa: E731
            key_fn = lambda r: r["slot"]          # noqa: E731
        if self.is_tb:
            step = make_ffat_tb_step(capacity, self.max_keys, self.P,
                                     self.R, self.D, self.NP,
                                     lift, self.comb,
                                     key_fn,
                                     drop_tainted=self.overflow_policy
                                     == "drop",
                                     grouping=self._grouping(),
                                     monoid=self.monoid, pallas=pallas)
        else:
            step = make_ffat_step(capacity, self.max_keys, self.P, self.R,
                                  self.D, lift, self.comb,
                                  key_fn,
                                  monoid=self.monoid,
                                  grouping=self._grouping(),
                                  pallas=pallas)
        if comp is not None:
            from windflow_tpu.parallel import compaction
            kernel = step
            user_key = self.key_extractor

            def step(state, payload, ts, valid, *rest):
                # remap operands ride as (table_keys, table_slots, cstats)
                # appended after the kernel's own args; cstats is the
                # donated hit/miss/candidate state (zero extra dispatches)
                *kargs, tk, tsl, cst = rest
                raw = jax.vmap(user_key)(payload).astype(jnp.int32)
                slots, hit = compaction.lookup_slots(tk, tsl, raw, valid)
                cst = compaction.cstats_update(cst, raw, hit,
                                               valid & ~hit)
                outs = kernel(state, {"rec": payload, "slot": slots}, ts,
                              valid & hit, *kargs)
                out = dict(outs[1])
                out["key"] = compaction.slots_to_user_keys(
                    out["key"], tk, tsl)
                outs = (outs[0], out) + tuple(outs[2:])
                return (*outs, cst)
        prelude = self._fused_prelude
        if prelude is not None:
            # Whole-chain fusion (windflow_tpu/fusion): the fused
            # segment's stateless members run INSIDE this program, so
            # the map/filter hop boundaries the sweep ledger priced
            # never materialize in HBM and the chain pays this single
            # dispatch.  Ring regrowth rebuilds the step through this
            # same path, so a regrown program keeps its prelude.
            inner = step

            def step(state, payload, ts, valid, *rest):
                payload, valid = prelude(payload, valid)
                return inner(state, payload, ts, valid, *rest)
        # State-only donation, fused or not: the ring is the program's
        # one input whose buffers an output aliases (window results have
        # their own shapes — batch-lane donation would elide nothing and
        # XLA warns about unusable donations).  Compacted steps also
        # donate the cstats operand (the sketch pattern).
        donate = (0,)
        if comp is not None:
            donate = (0, 7 if self.is_tb else 6)
        return wf_jit(step, op_name=self._fused_name or self.name,
                      donate_argnums=donate)

    def _pallas_mode(self):
        """Resolved Pallas gate for this operator's compiled programs
        (windflow_tpu/kernels; None = lax path)."""
        from windflow_tpu.kernels import resolve_pallas_for
        return resolve_pallas_for(self)

    def _grouping(self) -> str:
        """Batch-grouping algorithm from the graph config (rank_scatter |
        argsort — Config.ffat_grouping), validated at step-build time."""
        mode = getattr(self.config, "ffat_grouping", "rank_scatter")
        if mode not in ("rank_scatter", "argsort"):
            raise WindFlowError(
                f"unknown ffat_grouping '{mode}' (rank_scatter | argsort)")
        return mode

    # -- operator plumbing ---------------------------------------------------
    @property
    def _per_replica_state(self) -> bool:
        # TB ring clocks are shared across a state's keys, so KEYBY
        # partitions (disjoint keys, independent watermark frontiers) need
        # one state per replica; FORWARD round-robin feeds every replica
        # the same keys and must share one state.
        return self.is_tb and self.routing == RoutingMode.KEYBY             and self.parallelism > 1

    def _sidx(self, ridx: int) -> int:
        return ridx if self._per_replica_state else 0

    def _run_step(self, sidx: int, payload, ts, valid, *kargs):
        """Dispatch the compiled step, appending the compaction operands
        (remap tables + donated cstats) when a compactor is attached;
        updates the state (and cstats) and returns the kernel's
        remaining outputs.  The un-compacted path pays one check."""
        comp = self._compactor
        if comp is None:
            outs = self._jit_step(self._states[sidx], payload, ts, valid,
                                  *kargs)
            self._states[sidx] = outs[0]
            return outs[1:]
        if not comp.active:
            # unlike the stateful plane there is NO lossless fallback
            # for a compacted window (max_keys bounds the SLOT space):
            # running on would silently mask every not-yet-admitted
            # key's records forever, so fail loudly instead
            raise WindFlowError(
                f"FfatWindowsTPU '{self.name}': the compacted key space "
                "lost its host admission path (the key extractor failed "
                "on the staging probe, or admission errored) — declare "
                "withMaxKeys or make the extractor batch-applicable")
        from windflow_tpu.parallel import compaction
        comp.on_batch()
        if self._cstats is None:
            self._cstats = compaction.cstats_init()
        tk, tsl = comp.tables()
        outs = self._jit_step(self._states[sidx], payload, ts, valid,
                              *kargs, tk, tsl, self._cstats)
        self._states[sidx] = outs[0]
        self._cstats = outs[-1]
        return outs[1:-1]

    def _ensure(self, batch: DeviceBatch, sidx: int):
        if self._capacity is None:
            if self.max_keys is None:
                raise WindFlowError(
                    f"FfatWindowsTPU '{self.name}': compacted key space "
                    "(withCompactedKeys) needs Config.key_compaction on "
                    "and a graph build to assign slots; declare "
                    "withMaxKeys to run without compaction")
            self._capacity = batch.capacity
            cap_by_mem = max(64, (1 << 23) // max(1, self.max_keys))
            # ceiling: purely the MEMORY bound on the dense [max_keys,
            # NP] state (plus the NP-proportional window-output grid).
            # It deliberately does NOT clamp to the single-batch span
            # (one batch of C tuples spans <= C panes, but the ring must
            # hold UNFIRED panes across MANY batches when the min-folded
            # watermark lags the frontier — a batch-capacity ceiling made
            # the ring ungrowable exactly when multi-channel lag needed
            # it, found by the r5 5000-tuple fuzz soak).  The lateness
            # allowance is ADDED — lateness pins panes in the ring by
            # contract, so clamping it away would make the grown ring
            # permanently too small for high-lateness specs
            lat_panes = (self.spec.lateness // self.P + 1) if self.is_tb \
                else 0
            self._np_ceil = max(2 * self.R, self.R + 64,
                                self.R + lat_panes
                                + min(8192, cap_by_mem) + 2)
            if self.NP is None and self.is_tb:
                # Auto-size from the FIRST batch's observed time spread
                # (one host sync, once): 8x margin over its pane span plus
                # the lateness allowance, floored at 2R / R+64 and capped
                # at the ceiling.  A first batch unrepresentative of the
                # steady state cannot silently lose windows: ring overflow
                # is detected on a cadence and the ring REGROWS toward the
                # ceiling (see _maybe_regrow — the device form of the host
                # FlatFAT's growth, ffat_op.py).
                tmin = int(jnp.min(jnp.where(batch.valid, batch.ts,
                                             jnp.int64(1) << 62)))
                tmax = int(jnp.max(jnp.where(batch.valid, batch.ts,
                                             -(jnp.int64(1) << 62))))
                span = (tmax - tmin) // self.P + 1 if tmax >= tmin else 1
                lat_panes = self.spec.lateness // self.P + 1
                est = 8 * span + lat_panes + self.R + 2
                self.NP = max(2 * self.R, self.R + 64,
                              min(est, self._np_ceil))
                self._auto_np = True
            elif self.NP is None:
                self.NP = self._np_ceil
            self._jit_step = self._build_step(batch.capacity)
            if self.is_tb:
                self._payload_zero = jax.tree.map(jnp.zeros_like,
                                                  batch.payload)
        elif batch.capacity != self._capacity:
            raise WindFlowError(
                "FfatWindowsTPU requires a fixed upstream batch capacity "
                f"({self._capacity}), got {batch.capacity}")
        if sidx not in self._states:
            payload = batch.payload
            if self._fused_prelude is not None:
                # fused chain: the lift sees the chain's OUTPUT records —
                # size the aggregate state from the post-prelude spec
                # (abstract eval, zero device work)
                from windflow_tpu.fusion.executor import prelude_out_spec
                payload = prelude_out_spec(self._fused_prelude,
                                           batch.payload, batch.valid)
            self._states[sidx] = self._init_state(
                agg_spec_for(self.lift, payload))

    def _wm_pane(self, wm: int) -> int:
        """Lateness-adjusted watermark in pane units (the host-side firing
        frontier the device program compares window ends against)."""
        if wm == WM_NONE:
            return -(1 << 60)
        return (wm - self.spec.lateness) // self.P

    def _step(self, batch: DeviceBatch, ridx: int = 0) -> DeviceBatch:
        sidx = self._sidx(ridx)
        self._ensure(batch, sidx)
        if self.is_tb:
            if self._auto_np:
                # no NP < ceiling gate: at the ceiling growth no-ops in
                # _grow_ring, but extrema tracking and the pre-fold
                # _rebase_ring (a pure position shift, no growth) must
                # still run or a lagging channel's below-base panes are
                # unrecoverable on ceiling-size rings
                self._regrow_for_span(batch)
            if batch.frontier != WM_NONE:
                # this step fires: pre-fold rebasing closes (see
                # _rebase_ring) — read BEFORE the flag below by
                # _regrow_for_span, so the first resolved batch itself
                # still rebases ahead of its own placement
                self._fold_stepped = True
            # Fire on the batch's staging-time frontier, not the min-folded
            # propagated stamp: the step places every tuple of the batch
            # before firing, so the newest frontier is safe here and saves
            # one batch of firing lag (batch.py DeviceBatch.frontier).
            out, fired, out_ts, _ = self._run_step(
                sidx, batch.payload, batch.ts, batch.valid,
                jnp.int64(self._wm_pane(batch.frontier)))
            # periodic host checkpoint (one sync every 32 steps, and at
            # EOS): an auto-sized ring REGROWS on overflow before the
            # error policy would fail loudly
            self._overflow_steps += 1
            if self._overflow_steps % 32 == 0:
                if self._auto_np:
                    self._maybe_regrow()
                if self.overflow_policy == "error":
                    self._check_overflow()
        else:
            out, fired, out_ts = self._run_step(
                sidx, batch.payload, batch.ts, batch.valid)
        # fired-window results inherit the input batch's flight-recorder
        # trace: the staged→sunk span then covers the whole window path
        return DeviceBatch(out, out_ts, fired,
                           watermark=batch.watermark, size=None,
                           trace=batch.trace)

    def _flush(self) -> list:
        """EOS flush of the CB shared state: fire remaining partial windows
        (reference EOS flush of open windows).  Called once, by the last
        replica to terminate."""
        if not self._states or self._flushed:
            return []
        self._flushed = True
        if self._jit_flush is None:
            self._jit_flush = self._build_flush()
        if self._compactor is not None:
            out, fired, ts = self._jit_flush(self._states[0],
                                             *self._compactor.tables())
        else:
            out, fired, ts = self._jit_flush(self._states[0])
        return [DeviceBatch(out, ts, fired, watermark=0, size=None)]

    def _flush_tb(self, ridx: int) -> list:
        """EOS flush of one TB state: iterate the normal step with an empty
        batch and an infinite watermark — each pass fires the windows whose
        ends the ring roll has brought into range, until the window
        frontier stops advancing.  Keyed TB flushes per replica; FORWARD TB
        flushes the shared state once (guarded by the caller)."""
        import numpy as np
        sidx = self._sidx(ridx)
        if sidx not in self._states:
            return []
        if self.overflow_policy == "error":
            self._check_overflow()
        cap = self._capacity
        ts0 = jnp.zeros(cap, jnp.int64)
        invalid = jnp.zeros(cap, bool)
        outs = []
        while True:
            out, fired, out_ts, n_adv = self._run_step(
                sidx, self._payload_zero, ts0, invalid,
                jnp.int64(1 << 60))
            if bool(np.asarray(fired).any()):
                outs.append(DeviceBatch(out, out_ts, fired, watermark=0,
                                        size=None))
            # loop on ADVANCE, not emission: windows beyond an empty gap
            # in the pane sequence would stall behind a no-emission pass
            if int(n_adv) == 0:
                break
        return outs

    def _maybe_regrow(self):
        """Self-healing for the span-estimated ring: if panes were evicted
        since the last check, double the ring (up to the tuple-count
        ceiling), padding the live state with invalid columns — the device
        form of the host FlatFAT's growth-on-span (ffat_op.py).  Already-
        evicted panes are gone (their windows were suppressed and counted
        by the overflow policy); growth stops further loss.

        The eviction counter is read one checkpoint LATE: each call
        enqueues the (lazy, un-awaited) device sum and inspects the one
        enqueued 32 steps ago — by then dispatch has executed it, so the
        healthy path never blocks on a device sync."""
        if self.NP >= self._np_ceil or not self._states:
            return
        prev = self._pending_evct
        self._pending_evct = sum(
            jnp.sum(st["n_evicted"]) for st in self._states.values())
        if prev is None:
            return
        ev = int(prev)
        if ev <= self._evicted_seen:
            return
        self._evicted_seen = ev
        # x4 per event: the lazy read grows at most once per two
        # checkpoints, so convergence to the ceiling must be steep
        self._grow_ring(min(self._np_ceil, max(self.NP * 4, self.NP + 64)))

    def _grow_ring(self, new_np: int) -> None:
        """Pad every live ring to ``new_np`` panes (invalid columns) and
        rebuild the step program — shared by the eviction-cadence regrow
        above and the preemptive span regrow below."""
        pad = new_np - self.NP
        if pad <= 0:
            return

        def grow(st):
            out = dict(st)
            out["cells"] = jax.tree.map(
                lambda a: jnp.pad(
                    a, [(0, 0), (0, pad)] + [(0, 0)] * (a.ndim - 2)),
                st["cells"])
            out["cell_valid"] = jnp.pad(st["cell_valid"],
                                        ((0, 0), (0, pad)))
            if self.mesh is not None:
                from windflow_tpu.parallel.mesh import state_sharding
                sh = state_sharding(self.mesh)
                for k in ("cells", "cell_valid"):
                    out[k] = jax.tree.map(
                        lambda a: jax.device_put(a, sh), out[k])
            return out

        self._states = {k: grow(st) for k, st in self._states.items()}
        self.NP = new_np
        self._pending_evct = None
        self._jit_step = self._build_step(self._capacity)
        if self.NP >= self._np_ceil:
            # ceiling reached: evictions up to here were the estimator's
            # growing pains, not the stream violating a user-sized ring —
            # the 'error' policy only counts evictions past this point
            self._evicted_base = self._tb_counter("n_evicted")

    def _rebase_ring(self, lo_pane: int, hi_pane: int) -> None:
        """Move the ring window DOWN to ``lo_pane`` so panes the capacity
        roll slid past while the watermark fold was unresolved become
        placeable again (a lagging sibling channel's first data lives
        BELOW everything placed so far; growth alone pads the ring's top
        and cannot help).  Safe exactly while nothing has fired
        (``_fold_stepped`` False): the slid-past columns are empty — the
        roll found nothing to evict — and ``win_next``/``max_seen``/
        ``horizon`` are absolute pane stamps unaffected by where the ring
        window sits.  Shifting wraps top columns to the bottom; they are
        invalid by the ``hi_pane < new_base + NP`` clamp, and invalid
        cells' values are masked at merge (kernels).  Costs one host read
        of ``base`` per state — growth cadence only, never steady-state."""
        if self._fold_stepped:
            return
        for sidx, st in self._states.items():
            # rare host sync (see docstring); on a mesh "base" is a
            # [key-shards] lane whose per-shard clocks advance in
            # lockstep from the same gathered batches — read shard 0,
            # the elementwise shift below keeps every shard consistent
            base = int(np.asarray(st["base"]).reshape(-1)[0])
            new_base = max(lo_pane, hi_pane - self.NP + 1)
            delta = base - new_base
            if delta <= 0:
                continue
            out = dict(st)
            out["cells"] = jax.tree.map(
                lambda a: jnp.roll(a, delta, axis=1), st["cells"])
            out["cell_valid"] = jnp.roll(st["cell_valid"], delta, axis=1)
            out["base"] = st["base"] - delta
            if self.mesh is not None:
                from windflow_tpu.parallel.mesh import state_sharding
                sh = state_sharding(self.mesh)
                for k in ("cells", "cell_valid"):
                    out[k] = jax.tree.map(
                        lambda a: jax.device_put(a, sh), out[k])
            self._states[sidx] = out

    def _regrow_for_span(self, batch) -> None:
        """PREEMPTIVE ring growth from the host-known watermark lag (r5;
        found by the 5000-tuple fuzz soak: two seeds evicted a handful of
        panes — and suppressed their windows — under configurations whose
        multi-replica host stages let the min-folded watermark lag the
        staging frontier further than the first-batch span estimate).

        By the watermark contract, no future tuple is older than the
        propagated watermark, so the ring only ever needs the panes in
        ``(wm_adj, ts_max]`` plus ``R-1`` of window history — ``ts_max``
        is the batch's max DATA timestamp (attached host-side at staging
        and carried through mask-only stages), which can run arbitrarily
        far ahead of any watermark when a sibling channel lags.  Both
        stamps are host metadata — the bound costs ZERO device syncs —
        and growing to it BEFORE the step means the capacity roll never
        evicts non-late data; the eviction-cadence regrow remains as the
        backstop for device-born batches (no ``ts_max``) and streams
        whose true span exceeds the memory ceiling.

        The ring must also cover the BATCH'S OWN pane spread even when
        every pane is fireable: one step's fire passes advance at most
        ``3 * (NP // D + 2)`` windows, so a batch spanning far more
        panes than the ring holds would force the capacity roll to evict
        panes the passes could not fire in time — the
        ``ts_max - ts_min`` spread bound (the operator's documented ring
        contract, previously estimated from the FIRST batch only) now
        updates from every staged batch.

        While the multi-channel watermark fold is unresolved
        (``frontier == WM_NONE``) NOTHING fires, so every placed pane
        stays live and the ring must cover exactly the OBSERVED data
        spread — it grows (geometrically) to that, not to the memory
        ceiling (ADVICE r5: the former eager ceiling commit permanently
        charged tiny-span streams a ceiling-size ring plus a step
        recompile before their first resolved frontier).  The extrema
        seen during the unresolved phase keep bounding ``hi`` after the
        fold resolves, until the watermark passes them — the pre-fold
        panes are still unfired and must not be rolled out.

        Multi-host meshes skip the span regrow entirely: each process
        observes different local extrema, and divergent per-process
        growth decisions would desynchronize the sharded ring shapes
        (ADVICE r5 medium; staging also stops attaching process-local
        extrema, batch.py _stage_soa).  The eviction-cadence regrow is
        SPMD-consistent and remains the growth path there."""
        if batch.ts_max is None:
            return
        if jax.process_count() > 1:
            return
        wm = batch.frontier             # newest safe stamp: firing uses it
        if wm == WM_NONE:
            lo = batch.ts_min if batch.ts_min is not None else batch.ts_max
            prev_lo = self._unres_lo
            if self._unres_lo is None or lo < self._unres_lo:
                self._unres_lo = lo
            if self._unres_hi is None or batch.ts_max > self._unres_hi:
                self._unres_hi = batch.ts_max
            needed = int(self._unres_hi - self._unres_lo) // self.P \
                + self.R + 2
            if needed > self.NP:
                self._grow_ring(min(self._np_ceil,
                                    max(needed, self.NP * 2)))
            if prev_lo is not None and lo < prev_lo:
                # a lagging channel opened panes BELOW everything placed:
                # leading batches may already have rolled base past them
                self._rebase_ring(self._unres_lo // self.P,
                                  self._unres_hi // self.P)
            return
        lo = self._wm_pane(wm)          # oldest pane still open for data
        hi = batch.ts_max // self.P     # newest pane this batch touches
        if self._unres_hi is not None:
            if lo > self._unres_hi // self.P:
                # watermark passed the pre-fold data: stop tracking it
                self._unres_lo = self._unres_hi = None
            else:
                hi = max(hi, self._unres_hi // self.P)
        rebase_lo = None
        if not self._fold_stepped:
            # FIRST resolved batch (nothing fired yet): its own rows and
            # the pre-fold extrema may all reach below the rolled base —
            # the ring must re-cover down to the oldest of them before
            # this step places (the step fires AFTER placement, so panes
            # under the watermark still emit their windows normally)
            cand = [lo]
            if batch.ts_min is not None:
                cand.append(batch.ts_min // self.P)
            if self._unres_lo is not None:
                cand.append(self._unres_lo // self.P)
            rebase_lo = min(cand)
        needed = int(hi - lo) + self.R + 2
        if batch.ts_min is not None:
            spread = (batch.ts_max - batch.ts_min) // self.P + 1
            needed = max(needed, int(spread) + self.R + 2)
        if rebase_lo is not None:
            needed = max(needed, int(hi - rebase_lo) + self.R + 2)
        if needed > self.NP:
            # at least double: each growth recompiles the step, so
            # convergence under a widening lag must be geometric
            self._grow_ring(min(self._np_ceil,
                                max(needed, self.NP * 2)))
        if rebase_lo is not None:
            self._rebase_ring(rebase_lo, hi)

    # -- durable state (windflow_tpu/durability) -----------------------------
    def snapshot_state(self):
        """All cross-batch state: the dense pane rings/tables per state
        index (device -> host numpy), the compiled-capacity/ring-size
        pair the step program is rebuilt from, and the regrow/overflow
        estimator bookkeeping — so a restored ring neither re-learns its
        span nor re-arms a stale error grace.  Fused chains need nothing
        extra here: the tail operator owns the merged state, and restore
        rebuilds the step through ``_build_step``, which re-inlines the
        fused prelude."""
        if not self._states:
            return None     # never stepped: nothing to restore
        return {
            "kind": "ffat_tpu",
            "states": {k: jax.tree.map(np.asarray, st)
                       for k, st in self._states.items()},
            "capacity": self._capacity,
            "NP": self.NP,
            "auto_np": self._auto_np,
            "np_ceil": self._np_ceil,
            "overflow_steps": self._overflow_steps,
            "evicted_seen": self._evicted_seen,
            "evicted_base": self._evicted_base,
            "error_armed": self._error_armed,
            "clean_checks": self._clean_checks,
            "dirty_checks": self._dirty_checks,
            "unres_lo": self._unres_lo,
            "unres_hi": self._unres_hi,
            "fold_stepped": self._fold_stepped,
            "flushed": self._flushed,
            "eos_replicas": self._eos_replicas,
            "payload_zero": (jax.tree.map(np.asarray, self._payload_zero)
                            if self._payload_zero is not None else None),
            # compacted key space: the remap table is the key→pane-ring
            # half of per-key state — snapshot it so a restored ring's
            # rows keep meaning the same user keys
            "compactor": (self._compactor.snapshot()
                          if self._compactor is not None else None),
        }

    def restore_state(self, blob):
        self.NP = blob["NP"]
        self._auto_np = blob["auto_np"]
        self._np_ceil = blob["np_ceil"]
        self._overflow_steps = blob["overflow_steps"]
        self._evicted_seen = blob["evicted_seen"]
        self._evicted_base = blob["evicted_base"]
        self._error_armed = blob["error_armed"]
        self._clean_checks = blob["clean_checks"]
        self._dirty_checks = blob["dirty_checks"]
        self._unres_lo = blob["unres_lo"]
        self._unres_hi = blob["unres_hi"]
        self._fold_stepped = blob["fold_stepped"]
        self._flushed = blob["flushed"]
        self._eos_replicas = blob["eos_replicas"]
        self._pending_evct = None   # lazy device read: re-primed on step
        if self.mesh is not None:
            # multi-chip restore: re-place the host blobs in the
            # key-sharded layout the sharded step consumes (axis 0 of
            # every leaf is the key/shard dimension: cells, horizon,
            # and the per-key-shard TB scalar lanes alike).  The blob
            # was re-bucketed for THIS mesh shape by the durability
            # plane (durability/rebucket.py) before reaching here.
            from windflow_tpu.parallel.mesh import state_sharding
            sh = state_sharding(self.mesh)
            place = lambda a: jax.device_put(jnp.asarray(a), sh)
        else:
            place = jnp.asarray
        self._states = {k: jax.tree.map(place, st)
                        for k, st in blob["states"].items()}
        if blob["payload_zero"] is not None:
            self._payload_zero = jax.tree.map(jnp.asarray,
                                              blob["payload_zero"])
        if blob.get("compactor") is not None \
                and self._compactor is not None:
            self._compactor.restore(blob["compactor"])
        self._capacity = blob["capacity"]
        self._jit_step = self._build_step(self._capacity)

    def _check_overflow(self):
        # operator-wide: counters and the excused-eviction base
        # are summed over every replica state
        if self._auto_np and self.NP < self._np_ceil:
            return   # still growing: regrow, don't error, on overflow
        ev = self._tb_counter("n_evicted")
        if self._auto_np and not self._error_armed:
            # the undersized phase leaves a window-firing backlog whose
            # drain still evicts briefly after growth; arm the error only
            # after TWO consecutive clean checkpoints (the grow checkpoint
            # itself is trivially clean — its base was just snapshotted).
            # The grace is BOUNDED: persistent overflow at the ceiling is
            # the stream violating the ring contract, and re-basing
            # forever would silently defeat the 'error' policy.
            if ev > self._evicted_base:
                self._dirty_checks += 1
                if self._dirty_checks <= 4:
                    self._evicted_base = ev
                    self._clean_checks = 0
                    return
                self._error_armed = True
            else:
                self._clean_checks += 1
                if self._clean_checks < 2:
                    return
                self._error_armed = True
        if ev > self._evicted_base:
            raise WindFlowError(
                f"{self.name}: TB pane ring overflow (pane_capacity="
                f"{self.NP} < window span + batch time spread + lateness "
                "panes); increase withPaneCapacity or choose overflow "
                "policy 'drop'/'count'")

    def _tb_counter(self, name: str) -> int:
        # one device sync at read time, never on the step path; summed over
        # replica states (and over key-shard lanes on a mesh)
        return sum(int(jnp.sum(st[name])) for st in self._states.values())

    def key_space(self):
        # keys-lane plumbing for the shard ledger: the dense pane state
        # bounds the key space exactly where the compiled step does.
        # Compacted key spaces are unbounded to ROUTING (the sketch sees
        # raw keys; only the state is slot-dense), so they report None.
        if self._compact_keys:
            return None
        return self.max_keys if self.key_extractor is not None else None

    def num_dropped_tuples(self) -> int:
        if self.is_tb and self._states:
            return self._tb_counter("n_late")
        return 0

    def dump_stats(self) -> dict:
        n_late = None
        if self.is_tb and self._states:
            n_late = self._tb_counter("n_late")
            if self.replicas:
                self.replicas[0].stats.inputs_ignored = n_late
        st = super().dump_stats()
        if self._compactor is not None:
            st["Key_compaction"] = self._compactor.summary()
        if n_late is not None:
            st["Late_tuples_dropped"] = n_late
            st["Pane_cells_evicted"] = self._tb_counter("n_evicted")
            st["Windows_dropped_on_overflow"] = \
                self._tb_counter("n_win_dropped")
        return st

    def _build_flush(self):
        if self.mesh is not None:
            from windflow_tpu.parallel.mesh import make_sharded_ffat_flush
            return make_sharded_ffat_flush(self.mesh, self.max_keys,
                                           self.P, self.R, self.D,
                                           self.comb,
                                           op_name=f"{self.name}.flush")
        flush = make_ffat_flush(self.max_keys, self.P, self.R,
                                self.D, self.comb)
        if self._compactor is not None:
            # compacted key space: partial-window records fired at EOS
            # carry SLOT ids too — map them back through the same
            # inverse table as the step's fired records
            inner = flush

            def flush(state, tk, tsl):
                from windflow_tpu.parallel import compaction
                out, fired, ts = inner(state)
                out = dict(out)
                out["key"] = compaction.slots_to_user_keys(
                    out["key"], tk, tsl)
                return out, fired, ts
        return wf_jit(flush, op_name=f"{self.name}.flush")
