"""Pure FFAT device-program builders (no operator-layer dependencies).

The segmented-scan / pane / window-firing programs shared by the single-chip
operator (``windows/ffat_tpu.py``) and the multi-chip sharded path
(``parallel/mesh.py``).  Kept free of ``ops``/``graph`` imports so the
distribution layer can use them without cycles.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from windflow_tpu.utils.dtypes import cast_state_update
from windflow_tpu.windows.grouping import (auto_order, dense_rank,
                                           order_and_hist)


def _group_order(ids, nbuckets: int, grouping: str, pallas=None):
    """Stable grouping permutation: ``rank_scatter`` is the O(n) dense-key
    counting sort (grouping.py; beyond two radix passes — TB (key, pane)
    spaces past DIGIT^2 buckets — auto_order falls back to the sort, where
    the counting constant no longer wins), ``argsort`` the comparison-sort
    baseline.  Bit-identical either way (both order by (id, arrival)).

    ``pallas`` (a resolved :class:`windflow_tpu.kernels.PallasMode`)
    routes the counting grouping through the single-pass Pallas kernel
    where its gates hold (windflow_tpu/kernels) — same permutation,
    traced into the same program."""
    if grouping == "rank_scatter":
        if pallas is not None:
            from windflow_tpu import kernels as pk
            if pk.grouping_supported(int(ids.shape[0]), nbuckets):
                return pk.order_hist(ids, nbuckets, pallas.interpret)[0]
        return auto_order(ids, nbuckets)
    return jnp.argsort(ids, stable=True)


def _group_order_hist(ids, nbuckets: int, grouping: str, pallas=None):
    """``_group_order`` plus the ``[nbuckets]`` histogram of ids — on the
    single-counting-pass grouping the histogram is the ``dense_rank``
    byproduct, so the CB step's rank arithmetic costs no extra pass.
    On the Pallas path both come out of the one fused kernel."""
    if grouping == "rank_scatter":
        if pallas is not None:
            from windflow_tpu import kernels as pk
            if pk.grouping_supported(int(ids.shape[0]), nbuckets):
                return pk.order_hist(ids, nbuckets, pallas.interpret)
        return order_and_hist(ids, nbuckets)
    order = jnp.argsort(ids, stable=True)
    return order, jnp.zeros(nbuckets, jnp.int32) \
        .at[ids.astype(jnp.int32)].add(1)


def _seg_scan(comb, flags, values):
    """Inclusive segmented scan: within each flagged segment, fold ``comb``.
    ``values`` is a pytree of [B, ...] leaves; ``flags`` [B] marks segment
    starts."""
    def op(a, b):
        fa, va = a
        fb, vb = b
        combined = comb(va, vb)
        v = jax.tree.map(
            lambda c, nb: jnp.where(_b(fb, c), nb, c), combined, vb)
        return (fa | fb, v)

    _, scanned = jax.lax.associative_scan(op, (flags, values))
    return scanned


def _masked_reduce_last(comb, flags, values, axis):
    """Reduce ``values`` along ``axis`` with ``comb``, skipping entries whose
    flag is False; returns (any_flag, reduction).  Flag-aware monoid:
    associative, no identity needed."""
    fc = _flag_comb(comb)

    def op(a, b):
        return fc(*a, *b)

    f, v = jax.lax.associative_scan(op, (flags, values), axis=axis)
    take = lambda x: jax.lax.index_in_dim(x, x.shape[axis] - 1, axis,
                                          keepdims=False)
    return take(f), jax.tree.map(take, v)


def _b(mask, ref):
    """Broadcast a bool mask against a leaf with trailing dims."""
    return mask.reshape(mask.shape + (1,) * (ref.ndim - mask.ndim))


def _shift_leaf(a, k: int, axis: int, fill=0):
    """Shift one leaf along ``axis`` by ``k`` toward higher indices,
    filling the vacated slots with ``fill`` (0/False by default; the
    declared-monoid fold passes the monoid identity)."""
    pad = [(0, 0)] * a.ndim
    pad[axis] = (k, 0)
    s = [slice(None)] * a.ndim
    s[axis] = slice(0, a.shape[axis])
    return jnp.pad(a, pad, constant_values=fill)[tuple(s)]


def _shift_right(flags, values, k: int, axis: int):
    """Shift along ``axis`` by ``k`` positions (toward higher indices),
    filling vacated slots with invalid entries (bool pads False)."""
    if k == 0:
        return flags, values
    return (_shift_leaf(flags, k, axis),
            jax.tree.map(lambda a: _shift_leaf(a, k, axis), values))


def _flag_comb(comb):
    """Flag-aware combine: invalid operands are skipped (associative monoid
    without needing an identity element)."""
    def op(fa, va, fb, vb):
        both = comb(va, vb)
        v = jax.tree.map(
            lambda c, xa, xb: jnp.where(_b(fb, c),
                                        jnp.where(_b(fa, c), c, xb), xa),
            both, va, vb)
        return fa | fb, v
    return op


def _sliding_reduce(comb, flags, values, R: int, axis: int):
    """``out[i] = fold(comb)`` over the valid entries among positions
    ``[i-R+1, i]`` along ``axis``.  Dilated doubling: ``log2(R)`` combines
    build power-of-two window aggregates, then the binary decomposition of
    ``R`` stitches them — the log-depth trick of the reference's FlatFAT
    levels (``flatfat_gpu.hpp:60-139``) expressed as shifts instead of a
    tree, so nothing larger than the pane sequence is ever materialized."""
    op = _flag_comb(comb)
    # pow2[j] aggregates windows of width 2^j ending at each position
    pow2 = [(flags, values)]
    width = 1
    while width * 2 <= R:
        f, v = pow2[-1]
        fs, vs = _shift_right(f, v, width, axis)
        pow2.append(op(fs, vs, f, v))
        width *= 2
    # stitch R = sum of powers, walking from the window's newest end
    # backward; each added chunk sits *before* the accumulated suffix, so
    # it is the left operand of comb (order matters for non-commutative
    # combiners)
    res = None
    offset = 0
    for j in range(len(pow2) - 1, -1, -1):
        w = 1 << j
        if R & w:
            f, v = _shift_right(*pow2[j], offset, axis)
            res = (f, v) if res is None else op(f, v, *res)
            offset += w
    return res


#: declared combiner monoids (withMonoidCombiner): one source of truth
#: mapping kind -> (``.at[]`` scatter method, elementwise combine, mesh
#: reduce collective); the contract is ``comb(x, identity) == x``
#: leafwise (identity per dtype from :func:`_monoid_identity`), so
#: identity-filled slots are absorbed without a has-mask.  A new kind
#: goes here + ``_monoid_identity``.
_MONOID_OPS = {
    "sum": ("add", jnp.add, jax.lax.psum),
    "max": ("max", jnp.maximum, jax.lax.pmax),
    "min": ("min", jnp.minimum, jax.lax.pmin),
}
_MONOID_KINDS = tuple(_MONOID_OPS)


def monoid_collective(kind: str):
    """The mesh reduce collective (psum/pmax/pmin) for a monoid kind."""
    return _MONOID_OPS[kind][2]


def resolve_monoid(sum_like: bool, monoid):
    """Normalize the legacy ``sum_like`` flag into a monoid kind and
    validate it — the single gatekeeper shared by both kernel builders
    and the operator layer."""
    if sum_like and monoid is None:
        monoid = "sum"
    if monoid is not None and monoid not in _MONOID_OPS:
        raise ValueError(f"unknown monoid {monoid!r}; "
                         f"expected one of {_MONOID_KINDS}")
    return monoid


def _monoid_identity(kind: str, dtype):
    """The absorbing identity of a declared monoid for one leaf dtype."""
    dt = jnp.dtype(dtype)
    if kind == "sum":
        return jnp.zeros((), dt)
    if dt == jnp.bool_:
        # max over bool == any (ident False); min == all (ident True)
        return jnp.asarray(kind == "min", bool)
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.asarray(-jnp.inf if kind == "max" else jnp.inf, dt)
    info = jnp.iinfo(dt)
    return jnp.asarray(info.min if kind == "max" else info.max, dt)


def _monoid_scatter(buf_at, kind: str):
    """The scatter-combine method of ``x.at[idx]`` for a monoid kind."""
    return getattr(buf_at, _MONOID_OPS[kind][0])


def _monoid_fill(kind: str, flags, values):
    """Replace invalid entries with the monoid identity, leafwise."""
    return jax.tree.map(
        lambda a: jnp.where(_b(flags, a), a,
                            _monoid_identity(kind, a.dtype)), values)


def _sliding_reduce_plain(comb, flags, values, R: int, axis: int,
                          monoid: str):
    """Flagless dilated sliding fold for declared-monoid combiners
    (withSumCombiner / withMonoidCombiner): invalid entries are filled
    with the monoid identity once, then the log2(R) doubling runs on
    values alone — half the operand traffic of the flag-aware fold.
    Only valid when ``comb(x, identity) == x`` on every leaf."""
    zeroed = _monoid_fill(monoid, flags, values)

    # identity-fill shift: the vacated slots hold the combiner's identity
    def zshift(v, k):
        if k == 0:
            return v
        return jax.tree.map(
            lambda a: _shift_leaf(
                a, k, axis, fill=_monoid_identity(monoid, a.dtype)), v)

    pow2 = [zeroed]
    width = 1
    while width * 2 <= R:
        v = pow2[-1]
        pow2.append(comb(zshift(v, width), v))
        width *= 2
    res = None
    offset = 0
    for j in range(len(pow2) - 1, -1, -1):
        w = 1 << j
        if R & w:
            v = zshift(pow2[j], offset)
            res = v if res is None else comb(v, res)
            offset += w
    return res


def make_ffat_step(capacity: int, K: int, P: int, R: int, D: int,
                   lift: Callable, comb: Callable,
                   key_fn: Optional[Callable],
                   key_base_fn: Optional[Callable[[], Any]] = None,
                   sum_like: bool = False, grouping: str = "rank_scatter",
                   monoid: Optional[str] = None, pallas=None):
    """Build the (un-jitted) FFAT per-batch program.

    Pure-function form of the operator step so the multi-chip layer
    (``parallel/mesh.py``) can trace it *inside* ``shard_map`` with a per-shard
    key base: when ``key_base_fn`` is given, raw keys are rebased by its traced
    value, so a chip owning keys ``[base, base+K)`` sees them as ``[0, K)`` and
    out-of-range keys are masked out (the dense-key sharding answer to the
    reference's per-key device state, ``ffat_replica_gpu.hpp:438-514``).

    The output batch is COMPACTED on device: the worst case for ONE key is
    the whole batch (``capacity/(P*D)`` windows), but the *total* windows a
    batch can fire across all keys has the same bound (plus a per-key
    partial), so the egress batch is ``MAXO ~ capacity/(P*D) + 2K`` rows
    where a dense per-key grid would hold millions.  Firing is a per-key
    prefix of window ids, so compaction is pure index arithmetic — a K-long
    running sum + searchsorted — never a dense-grid scatter (a dense-grid
    device→host copy per step would dominate any end-to-end pipeline; the
    reference's ``numWinsPerBatch`` output buffer is likewise sized to
    fired windows, not the worst case, ``flatfat_gpu.hpp:60-139``).

    Declared-monoid fast path: ``monoid`` ("sum" | "max" | "min"; the
    legacy ``sum_like=True`` means ``monoid="sum"``) declares the
    combiner a leafwise commutative monoid with an absorbing identity
    (the "sum" contract is the one the mesh reduce commits to when it
    rides ``lax.psum``, parallel/mesh.py), so with ``rank_scatter``
    grouping the step skips the permutation entirely — each lane's
    within-key rank (grouping.dense_rank) gives its pane cell and lifts
    scatter-COMBINE (add/max/min) straight into the [K, NP1] grid.  No
    sorted layout, no segmented scan, no run-end detection.  The declared
    op is commutative, so only float rounding order differs from the
    sequential fold (exactly the tolerance psum already implies; max/min
    are idempotent — bit-identical either way).

    ``pallas`` (a resolved :class:`windflow_tpu.kernels.PallasMode`, or
    None for the pure-lax program): the grouping/rank pass and the
    declared-monoid sliding fold trace their Pallas kernel bodies into
    this SAME program where the kernel gates hold — no extra dispatch,
    record-for-record identical output (docs/PERF.md round 14)."""
    monoid = resolve_monoid(sum_like, monoid)
    NP1 = capacity // P + 2           # pane cells incl. continuation cell
    # total fired across all keys: sum_k panes_k/D + per-key partials
    MAXO = capacity // (P * D) + 2 * K + 8
    # dense_rank runs one counting pass over K+1 buckets whatever K is;
    # the gate only bounds its [capacity/CHUNK, K+1] chunk-histogram
    # (int32) to a sane size — 4096 keys at the TPU bench capacity is a
    # ~134 MB table.  Beyond it the permutation path still applies.
    scatter_combine = (monoid is not None and grouping == "rank_scatter"
                       and K <= 4096)

    def step(state, payload, ts, valid):
        B = capacity
        kb = key_base_fn() if key_base_fn is not None else None
        keys = jax.vmap(key_fn)(payload).astype(jnp.int32) \
            if key_fn is not None else jnp.zeros(B, jnp.int32)
        if kb is not None:
            keys = keys - jnp.int32(kb)
        ok = valid & (keys >= 0) & (keys < K)
        skey_for_sort = jnp.where(ok, keys, K)

        if scatter_combine:
            use_pk = False
            if pallas is not None:
                from windflow_tpu import kernels as pk
                use_pk = pk.grouping_supported(B, K + 1)
            if use_pk:
                # fused Pallas grouping: rank + histogram in one pass
                # (bit-identical to dense_rank — same (id, arrival)
                # counting), traced into this same program
                _, rank_u, hist_pk = pk.grouping_rank_hist(
                    skey_for_sort, K + 1, pallas.interpret)
                n_k = hist_pk[:K]
            else:
                rank_p, counts, _, _ = dense_rank(skey_for_sort, K + 1)
                rank_u = rank_p[:B]
                n_k = counts[:K]
            lifts = jax.vmap(lift)(payload)
            fill0_u = state["cur_fill"][jnp.minimum(skey_for_sort, K - 1)]
            col_u = jnp.where(
                ok, ((fill0_u + rank_u) // P).astype(jnp.int32), 0)

            def scat(leaf):
                ident = _monoid_identity(monoid, leaf.dtype)
                buf = jnp.full((K + 1, NP1) + leaf.shape[1:], ident,
                               leaf.dtype)
                return _monoid_scatter(
                    buf.at[skey_for_sort, col_u], monoid)(
                    jnp.where(_b(ok, leaf), leaf, ident))[:K]
            cells = jax.tree.map(scat, lifts)

            # carried partial pane merges by the declared op (empty cells
            # hold the monoid identity, so no has-mask is needed)
            def merge0(cur_leaf, cell_leaf):
                ident = _monoid_identity(monoid, cell_leaf.dtype)
                upd = jnp.where(_b(state["cur_valid"], cur_leaf),
                                cur_leaf, ident)
                return _monoid_scatter(cell_leaf.at[:, 0], monoid)(
                    cast_state_update(upd, cell_leaf.dtype,
                                      "FFAT pane merge"))
            cells = jax.tree.map(merge0, state["cur"], cells)
        else:
            # after a STABLE grouping by dense key, bucket b's lanes
            # occupy [start_b, start_b + hist_b), so the within-key rank
            # is index arithmetic off a histogram of the keys — no
            # [B]-length scan, no segment_sum (r5 TPU profile: the rank
            # scan was the dominant standalone stage, 0.086 ms of a
            # 0.100 ms step; a [K+1] cumsum replaces it).  The histogram
            # itself is the counting permutation's dense_rank byproduct
            # on the single-pass path — free.
            order, hist = _group_order_hist(skey_for_sort, K + 1,
                                            grouping, pallas)
            sk = skey_for_sort[order]
            slift = jax.tree.map(lambda a: a[order],
                                 jax.vmap(lift)(payload))
            pos = jnp.arange(B)
            bucket_start = jnp.cumsum(hist) - hist        # exclusive
            rank = pos - bucket_start[sk]
            starts = rank == 0

            n_k = hist[:K]      # buckets < K hold exactly the ok lanes
            fill0 = state["cur_fill"][jnp.minimum(sk, K - 1)]
            pane_rel = ((fill0 + rank) // P).astype(jnp.int32)

            # pane partials: segmented scan over (key, pane) runs
            pane_starts = starts | jnp.concatenate(
                [jnp.array([True]), pane_rel[1:] != pane_rel[:-1]])
            scanned = _seg_scan(comb, pane_starts, slift)
            ends = jnp.concatenate(
                [(sk[1:] != sk[:-1]) | (pane_rel[1:] != pane_rel[:-1]),
                 jnp.array([True])])
            # scatter segment-end partials into dense [K+1, NP1] cells
            row = jnp.where(ends, sk, K)
            col = jnp.where(ends, pane_rel, 0)

            def scat(leaf):
                buf = jnp.zeros((K + 1, NP1) + leaf.shape[1:], leaf.dtype)
                return buf.at[row, col].set(
                    jnp.where(_b(ends, leaf), leaf, 0))[:K]
            cells = jax.tree.map(scat, scanned)
            cell_has = jnp.zeros((K + 1, NP1), bool) \
                .at[row, col].set(ends)[:K]

            # merge continuation cell with the carried partial pane; comb
            # is a WHOLE-PYTREE combiner (cross-leaf combines are legal —
            # matrix products etc.), so it runs once on the tree, not per
            # leaf
            cell0 = jax.tree.map(lambda cl: cl[:, 0], cells)
            both0 = comb(state["cur"], cell0)

            def merge0(cur_leaf, cell_leaf, both_leaf):
                use_cur = state["cur_valid"]
                use_cell = cell_has[:, 0]
                v = jnp.where(_b(use_cur & use_cell, both_leaf), both_leaf,
                              jnp.where(_b(use_cur, both_leaf), cur_leaf,
                                        cell_leaf[:, 0]))
                # carried state may be wider than the batch-derived cells
                # (e.g. an f64 agg_spec under x64 vs f32 lifts); the cell
                # dtype is authoritative — a promoting scatter errors in
                # future JAX, and a kind-crossing cast is state corruption
                # (utils.dtypes)
                return cell_leaf.at[:, 0].set(
                    cast_state_update(v, cell_leaf.dtype,
                                      "FFAT pane merge"))
            cells = jax.tree.map(merge0, state["cur"], cells, both0)

        m_k = ((state["cur_fill"] + n_k) // P).astype(jnp.int32)
        new_fill = ((state["cur_fill"] + n_k) % P).astype(jnp.int32)

        # full pane sequence: carry (R-1 trailing) + this batch's panes
        full = jax.tree.map(
            lambda c, p: jnp.concatenate([c, p], axis=1),
            state["carry"], cells)
        col_ix = jnp.arange(NP1)[None, :]
        pane_valid = col_ix < m_k[:, None]
        full_valid = jnp.concatenate([state["carry_valid"], pane_valid],
                                     axis=1)

        # fire windows: key k fires ends e = win_next[k] + j*D while
        # e <= done[k] — a per-key PREFIX, so no dense [K, MW] firing grid
        # is ever needed: per-key counts + a searchsorted over their running
        # sum enumerate the fired (key, window) pairs directly in compacted
        # order.  The sliding fold (log2(R) dilated combines over the
        # [K, R-1+NP1] pane sequence) stays dense; window values are
        # gathered only at the MAXO compacted output slots.
        done = state["pane_base"] + m_k
        if monoid is not None:
            # declared identity-absorbing: the flag lane of the fold is
            # pure overhead here (the CB step never reads the flag output
            # — fired windows always contain data)
            use_fold = False
            if pallas is not None:
                from windflow_tpu import kernels as pk
                use_fold = pk.fold_supported(full, R, monoid,
                                             pallas.interpret)
            if use_fold:
                # Pallas pane combine: identity fill + blocked sliding
                # fold in one VMEM-resident kernel (MXU banded matmul
                # for f32 sums, the lax fold's own doubling schedule
                # on the VPU otherwise — module docstring)
                swin = pk.sliding_fold(full, full_valid, R, monoid,
                                       pallas.interpret)
            else:
                swin = _sliding_reduce_plain(comb, full_valid, full, R,
                                             axis=1, monoid=monoid)
        else:
            _, swin = _sliding_reduce(comb, full_valid, full, R, axis=1)

        n_fired = jnp.maximum(
            jnp.int64(0), (done - state["win_next"]) // D + 1)
        new_win_next = state["win_next"] + n_fired * D

        # new carry: panes [pane_base+m_k-(R-1), pane_base+m_k)
        cidx = m_k[:, None] + jnp.arange(R - 1)[None, :]       # [K, R-1]
        def carry_leaf(a):
            idx = cidx.reshape(K, R - 1, *([1] * (a.ndim - 2)))
            idx = jnp.broadcast_to(idx, (K, R - 1) + a.shape[2:])
            return jnp.take_along_axis(a, idx, axis=1)
        new_carry = jax.tree.map(carry_leaf, full)
        new_carry_valid = jnp.take_along_axis(full_valid, cidx, axis=1)

        def cur_leaf(cell_leaf):
            idx = m_k.reshape(K, 1, *([1] * (cell_leaf.ndim - 2)))
            idx = jnp.broadcast_to(idx, (K, 1) + cell_leaf.shape[2:])
            return jnp.take_along_axis(cell_leaf, idx, axis=1)[:, 0]
        new_cur = jax.tree.map(cur_leaf, cells)
        new_cur_valid = new_fill > 0

        new_state = {
            "carry": new_carry,
            "carry_valid": new_carry_valid,
            "cur": new_cur,
            "cur_valid": new_cur_valid,
            "cur_fill": new_fill,
            "pane_base": done,
            "win_next": new_win_next,
        }

        # output batch (see docstring): compacted slot i belongs to the key
        # whose fired-count running sum first exceeds i; everything else is
        # per-slot arithmetic + one gather from the sliding fold.
        offs = jnp.cumsum(n_fired)                             # [K]
        n_out = offs[K - 1]
        i_slot = jnp.arange(MAXO, dtype=jnp.int64)
        k_out = jnp.searchsorted(offs, i_slot, side="right") \
            .astype(jnp.int32)                                 # [MAXO]
        k_c = jnp.minimum(k_out, K - 1)
        j_out = i_slot - (offs[k_c] - n_fired[k_c])            # rank in key
        e_out = state["win_next"][k_c] + j_out * D
        # window value: sliding-fold cell at the window's end pane
        widx_out = jnp.clip(
            (e_out - state["pane_base"][k_c] + (R - 2)).astype(jnp.int32),
            0, R - 1 + NP1 - 1)                                # [MAXO]
        wvals_out = jax.tree.map(lambda a: a[k_c, widx_out], swin)
        out = {
            "key": k_c + (jnp.int32(kb) if kb is not None else 0),
            "wid": (e_out - R) // D,
            "value": wvals_out,
        }
        out_valid = i_slot < n_out
        batch_ts = jnp.max(jnp.where(valid, ts, 0))
        out_ts = jnp.where(out_valid, batch_ts, 0)
        return new_state, out, out_valid, out_ts

    return step


def make_ffat_flush(K: int, P: int, R: int, D: int, comb: Callable,
                    key_base_fn: Optional[Callable[[], Any]] = None):
    """Build the (un-jitted) CB EOS flush: fire every remaining partial
    window from the carried pane history (reference EOS flush of open
    windows).  Pure-function form so the mesh layer can trace it inside
    ``shard_map`` with a per-shard key base — a plain ``jit`` over the
    key-sharded state lets XLA choose the OUTPUT layout, and each
    process's sink would read whichever key rows XLA happened to place
    locally (found by the two-process graph test)."""
    MWF = R // D + 2

    def flush(state):
        kb = key_base_fn() if key_base_fn is not None else None
        # total panes including the partial pane
        has_cur = state["cur_valid"]
        total = state["pane_base"] + has_cur.astype(jnp.int64)
        # available pane history: carry (R-1) + cur  -> [K, R]
        hist = jax.tree.map(
            lambda c, cur: jnp.concatenate([c, cur[:, None]], axis=1),
            state["carry"], state["cur"])
        hist_valid = jnp.concatenate(
            [state["carry_valid"], has_cur[:, None]], axis=1)
        # hist column i holds pane (pane_base - (R-1) + i)
        j = jnp.arange(MWF, dtype=jnp.int64)
        e = state["win_next"][:, None] + j[None, :] * D
        start = e - R
        fire = start < total[:, None]
        # gather window panes from hist: local = pane - pane_base + R-1
        lidx = (start[:, :, None] + jnp.arange(R)[None, None, :]
                - state["pane_base"][:, None, None] + (R - 1))
        inb = (lidx >= 0) & (lidx < R)
        lidx_c = jnp.clip(lidx, 0, R - 1).astype(jnp.int32)
        pane_ok = jnp.take_along_axis(
            jnp.broadcast_to(hist_valid[:, None], (K, MWF, R)),
            lidx_c, axis=2) & inb
        # panes must also be < total (cur counts once)
        pane_abs = start[:, :, None] + jnp.arange(R)[None, None, :]
        pane_ok = pane_ok & (pane_abs < total[:, None, None]) \
            & (pane_abs >= 0)

        def gather_leaf(a):
            expanded = jnp.broadcast_to(a[:, None], (K, MWF) + a.shape[1:])
            idx = lidx_c.reshape(K, MWF, R, *([1] * (a.ndim - 2)))
            idx = jnp.broadcast_to(idx, (K, MWF, R) + a.shape[2:])
            return jnp.take_along_axis(expanded, idx, axis=2)
        wpanes = jax.tree.map(gather_leaf, hist)
        any_ok, wvals = _masked_reduce_last(comb, pane_ok, wpanes, axis=2)
        fired = fire & any_ok
        wid = (e - R) // D
        out = {
            "key": (jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None], (K, MWF))
                + (jnp.int32(kb) if kb is not None else 0)).reshape(-1),
            "wid": wid.reshape(-1),
            "value": jax.tree.map(
                lambda a: a.reshape((K * MWF,) + a.shape[2:]), wvals),
        }
        ts = jnp.zeros((K * MWF,), jnp.int64)
        return out, fired.reshape(-1), ts

    return flush


def make_ffat_tb_state(agg_spec, K: int, NP: int):
    """Dense pane-ring state for time-based FFAT: column ``i`` of ``cells``
    holds the aggregate of time pane ``base + i`` (pane = ts // P_usec) for
    each key.  All keys share the pane clock, so ``base``/``win_next`` are
    scalars — unlike the count-based state, no per-key fill tracking is
    needed (the TPU re-design of the reference's TB quantum panes,
    ``ffat_replica_gpu.hpp:92-216``)."""
    zeros = lambda shape: jax.tree.map(
        lambda s: jnp.zeros(shape + s.shape, s.dtype), agg_spec)
    return {
        "cells": zeros((K, NP)),
        "cell_valid": jnp.zeros((K, NP), bool),
        "base": jnp.zeros((), jnp.int64),      # pane index of column 0
        "win_next": jnp.zeros((), jnp.int64),  # next unfired window id
        # newest data pane ever placed: windows starting beyond it can never
        # emit, so firing never advances past it (bounds EOS flush loops)
        "max_seen": jnp.full((), -(1 << 60), jnp.int64),
        # per-key overflow taint: one past the newest DATA pane evicted by a
        # capacity roll before its windows fired; windows starting below it
        # lost data (the drop-window overflow policy suppresses them)
        "horizon": jnp.full((K,), -(1 << 60), jnp.int64),
        "n_late": jnp.zeros((), jnp.int64),    # dropped late tuples
        "n_evicted": jnp.zeros((), jnp.int64),  # pane cells lost to overflow
        "n_win_dropped": jnp.zeros((), jnp.int64),  # windows suppressed
    }


def make_ffat_tb_step(capacity: int, K: int, P_usec: int, R: int, D: int,
                      NP: int, lift: Callable, comb: Callable,
                      key_fn: Optional[Callable],
                      key_base_fn: Optional[Callable[[], Any]] = None,
                      drop_tainted: bool = False,
                      grouping: str = "rank_scatter",
                      sum_like: bool = False,
                      monoid: Optional[str] = None, pallas=None):
    """Time-based FFAT per-batch program.

    Window ``w`` covers panes ``[w*D, w*D + R)`` — times
    ``[w*slide, w*slide + win)`` — and fires once the (lateness-adjusted)
    watermark passes the window end; the host passes ``wm_adj`` per batch.
    The ring holds ``NP`` panes.

    The step fires in passes around placement so a watermark/time jump
    (an idle gap in the stream) cannot evict fireable windows:

    * pass A, *before* making room for the batch, fires windows complete
      under ``min(wm, oldest batch pane)`` — the frontier below which no
      tuple of this batch (nor, by the watermark contract, any future one)
      can fall, so those windows' data is fully in the ring already.  It
      runs TWICE: one pass only fires windows whose ends are inside the
      ring, and with a lagging watermark the ring may hold data whose
      windows end beyond it — the first pass's roll brings those ends in
      range, the second fires them (two passes cover all in-ring data
      because ``NP >= 2R``, enforced by the operator).
    * the capacity roll then makes room for the batch's newest pane; panes
      it evicts belong to windows overlapping the batch's own time range —
      data loss only under a genuinely undersized ring (pane_capacity <
      window span + batch time spread), surfaced via ``n_evicted``.
    * pass B, after placement, fires what the batch itself completed —
      windows ending between the batch's oldest pane and the watermark
      (routinely non-empty: on an ordered stream these are the windows the
      batch's own tuples closed).

    Returns ``(state, out, fired, out_ts, n_advanced)``; ``n_advanced``
    counts windows passed (fired or skipped-as-evicted) so drivers can loop
    EOS/catch-up flushes until the frontier genuinely stops moving (windows
    beyond an empty gap would otherwise stall behind a no-emission pass).

    ``drop_tainted`` (the drop-window overflow policy): windows whose span
    lost a DATA pane to a capacity-roll eviction are suppressed instead of
    firing a wrong partial aggregate; every suppression increments
    ``n_win_dropped``.  The reference never fires a wrong window — it
    grows/blocks instead — so wrong-but-counted is opt-in (``count``).

    ``monoid`` ("sum" | "max" | "min"; legacy ``sum_like=True`` means
    ``monoid="sum"`` — withSumCombiner / withMonoidCombiner): TB
    placement then needs NO grouping at all — the pane cell is timestamp
    arithmetic, so lifts scatter-COMBINE (add/max/min) into the ring and
    the whole sort/segmented-scan machinery disappears (for "sum", float
    rounding order may differ from the sequential fold, the psum
    tolerance; max/min are idempotent — identical either way).
    """
    monoid = resolve_monoid(sum_like, monoid)
    MW = NP // D + 2
    N_PASSES = 3                     # A1, A2 (pre-place), B (post-place)

    def roll_left(flags, values, k):
        # advance the ring by k panes (k is traced); vacated tail = invalid
        idx = jnp.arange(NP, dtype=jnp.int64) + k
        inb = idx < NP
        idxc = jnp.clip(idx, 0, NP - 1).astype(jnp.int32)
        f = jnp.take(flags, idxc, axis=1) & inb[None, :]
        v = jax.tree.map(lambda a: jnp.take(a, idxc, axis=1), values)
        return f, v

    def fire_pass(cells, cell_valid, base, win_next, frontier, max_seen,
                  horizon):
        """Fire windows ending <= frontier whose end pane is inside the
        ring; returns the rolled ring + firing outputs.  Firing is capped to
        in-ring ends: if the frontier outruns the ring, later windows wait
        for the next pass/step (the roll brings their ends in range) — every
        fired fold is exactly over its own panes.  It is also capped to
        windows starting at or before the newest data pane (``max_seen``):
        later windows can never emit, so advancing past them would let an
        infinite-watermark flush loop run forever."""
        j = jnp.arange(MW, dtype=jnp.int64)
        w = win_next + j
        end_local = (w * D + R - 1 - base)                     # [MW]
        fire = ((w * D + R) <= frontier) & (end_local < NP) \
            & (w * D <= max_seen)                              # [MW] prefix
        # end_local < 0 happens only when a capacity roll evicted the whole
        # window (overload); such windows must not fire with pane-0 data
        emitable = fire & (end_local >= 0)
        eidx = jnp.clip(end_local, 0, NP - 1).astype(jnp.int32)
        n_fired = jnp.sum(fire.astype(jnp.int64))

        def do_fold(_):
            # the O(K*NP*log R) sliding fold + gathers, only when this pass
            # actually fires something (on an ordered stream the pre-place
            # passes usually fire nothing — the previous step's post-place
            # pass already did their work)
            sflag, swin = _sliding_reduce(comb, cell_valid, cells, R, axis=1)

            def pick_leaf(a):
                idx = eidx.reshape(1, MW, *([1] * (a.ndim - 2)))
                idx = jnp.broadcast_to(idx, (K, MW) + a.shape[2:])
                return jnp.take_along_axis(a, idx, axis=1)
            wvals = jax.tree.map(pick_leaf, swin)
            any_data = jnp.take_along_axis(
                sflag, jnp.broadcast_to(eidx[None, :], (K, MW)), axis=1)
            # advance past fully-evicted windows (fire) but never emit them
            # (emitable): their eidx clips to pane 0, which they don't cover
            f = emitable[None, :] & any_data
            n_drop = jnp.zeros((), jnp.int64)
            if drop_tainted:
                # suppress windows whose span lost data to an eviction;
                # count them per tainted key — including windows whose
                # WHOLE span was evicted (fire & ~emitable), which can
                # never emit but did lose that key's data
                clean = (w * D)[None, :] >= horizon[:, None]
                gone = (fire & ~emitable)[None, :] & ~clean
                n_drop = jnp.sum((f & ~clean).astype(jnp.int64)) \
                    + jnp.sum(gone.astype(jnp.int64))
                f = f & clean
            return f, wvals, n_drop

        def no_fold(_):
            zvals = jax.tree.map(
                lambda a: jnp.zeros((K, MW) + a.shape[2:], a.dtype), cells)
            return jnp.zeros((K, MW), bool), zvals, jnp.zeros((), jnp.int64)

        fired, wvals, n_drop = jax.lax.cond(n_fired > 0, do_fold, no_fold,
                                            None)
        new_next = win_next + n_fired
        shift = jnp.clip(new_next * D - base, 0, NP)
        cell_valid, cells = roll_left(cell_valid, cells, shift)
        return (cells, cell_valid, base + shift, new_next,
                fired, wvals, w, n_fired, n_drop)

    def step(state, payload, ts, valid, wm_pane):
        B = capacity
        kb = key_base_fn() if key_base_fn is not None else None
        keys = jax.vmap(key_fn)(payload).astype(jnp.int32) \
            if key_fn is not None else jnp.zeros(B, jnp.int32)
        if kb is not None:
            keys = keys - jnp.int32(kb)
        ok = valid & (keys >= 0) & (keys < K)
        pane = ts.astype(jnp.int64) // P_usec
        if D > R:
            # hopping windows with gaps (slide > win): panes in the
            # inter-window gap belong to no window — never place or count
            # them (pane p is covered iff p mod D < R)
            ok = ok & ((pane % D) < R)

        # 1. pass A (twice): fire everything no tuple of this batch can
        # touch; the second pass reaches windows whose ends the first
        # pass's roll brought inside the ring
        min_pane = jnp.min(jnp.where(ok, pane, jnp.int64(1) << 60))
        frontier_a = jnp.minimum(wm_pane, min_pane)
        cells, cell_valid, base, win_next = (
            state["cells"], state["cell_valid"], state["base"],
            state["win_next"])
        a_outs = []
        n_win_dropped = state["n_win_dropped"]
        for _ in range(2):
            (cells, cell_valid, base, win_next,
             fired_i, wvals_i, w_i, n_i, nd_i) = fire_pass(
                cells, cell_valid, base, win_next, frontier_a,
                state["max_seen"], state["horizon"])
            a_outs.append((fired_i, wvals_i, w_i, n_i))
            n_win_dropped = n_win_dropped + nd_i

        # 2. capacity roll: make room for this batch's newest pane
        max_pane = jnp.max(jnp.where(ok, pane, base))
        max_seen = jnp.maximum(state["max_seen"],
                               jnp.max(jnp.where(ok, pane, -(1 << 60))))
        shift_cap = jnp.maximum(jnp.int64(0), max_pane - base - (NP - 1))
        col = jnp.arange(NP, dtype=jnp.int64)[None, :]
        evict_mask = cell_valid & (col < shift_cap)
        evicted = jnp.sum(evict_mask.astype(jnp.int64))
        # per-key taint horizon: one past the newest data pane lost here
        horizon = jnp.maximum(
            state["horizon"],
            jnp.max(jnp.where(evict_mask, base + col + 1, -(1 << 60)),
                    axis=1))
        cell_valid, cells = roll_left(cell_valid, cells, shift_cap)
        base = base + shift_cap

        # 3. place the batch: sort by (key, pane), fold runs, merge cells
        rel = pane - base
        late = ok & (rel < 0)
        ok = ok & (rel >= 0)
        rel_c = jnp.clip(rel, 0, NP - 1).astype(jnp.int32)
        if monoid is not None:
            # declared leafwise-monoid combiner: a tuple's pane cell is
            # pure timestamp arithmetic (no within-key rank exists in
            # TB), so placement needs NO grouping at all — lifts
            # scatter-COMBINE straight into the ring (absent cells hold
            # the monoid identity).  The reference pays its sort for
            # every TB batch regardless (thrust::sort_by_key,
            # ffat_replica_gpu.hpp:917).
            row_u = jnp.where(ok, keys, K)
            col_u = jnp.where(ok, rel_c, 0)

            def scat(leaf):
                ident = _monoid_identity(monoid, leaf.dtype)
                buf = jnp.full((K + 1, NP) + leaf.shape[1:], ident,
                               leaf.dtype)
                return _monoid_scatter(buf.at[row_u, col_u], monoid)(
                    jnp.where(_b(ok, leaf), leaf, ident))[:K]
            partial = jax.tree.map(scat, jax.vmap(lift)(payload))
            partial_has = (jnp.zeros((K + 1, NP), jnp.int32)
                           .at[row_u, col_u].add(ok.astype(jnp.int32))[:K]
                           > 0)
            mop = _MONOID_OPS[monoid][1]

            def merge_m(old_leaf, new_leaf):
                # declared op with dtype PROMOTION, exactly like the
                # grouped path's comb merge — a wider (e.g. f64) state
                # stays wide; no scatter is involved so no cast is needed
                old = jnp.where(_b(cell_valid, old_leaf), old_leaf,
                                _monoid_identity(monoid, old_leaf.dtype))
                return mop(new_leaf, old)
            cells = jax.tree.map(merge_m, cells, partial)
        else:
            sid = jnp.where(ok, keys.astype(jnp.int64) * NP + rel_c,
                            jnp.int64(K) * NP)
            if K * NP + 1 < (1 << 31):   # counting ids are int32
                order = _group_order(sid.astype(jnp.int32), K * NP + 1,
                                     grouping, pallas)
            else:
                order = jnp.argsort(sid, stable=True)
            ssid = sid[order]
            slift = jax.tree.map(lambda a: a[order],
                                 jax.vmap(lift)(payload))
            starts = jnp.concatenate([jnp.array([True]),
                                      ssid[1:] != ssid[:-1]])
            scanned = _seg_scan(comb, starts, slift)
            ends = jnp.concatenate([ssid[1:] != ssid[:-1],
                                    jnp.array([True])])
            row = jnp.where(ends, ssid // NP, K).astype(jnp.int32)
            col = jnp.where(ends, ssid % NP, 0).astype(jnp.int32)

            def scat(leaf):
                buf = jnp.zeros((K + 1, NP) + leaf.shape[1:], leaf.dtype)
                return buf.at[row, col].set(
                    jnp.where(_b(ends, leaf), leaf, 0))[:K]
            partial = jax.tree.map(scat, scanned)
            partial_has = jnp.zeros((K + 1, NP), bool) \
                .at[row, col].set(ends)[:K]

            # comb is a whole-pytree combiner (see CB merge above)
            both_cells = comb(cells, partial)

            def merge(old_leaf, new_leaf, both_leaf):
                return jnp.where(_b(cell_valid & partial_has, both_leaf),
                                 both_leaf,
                                 jnp.where(_b(partial_has, both_leaf),
                                           new_leaf, old_leaf))
            cells = jax.tree.map(merge, cells, partial, both_cells)
        cell_valid = cell_valid | partial_has

        # 4. pass B: fire what this batch completed under the watermark
        (cells, cell_valid, base, win_next,
         fired_b, wvals_b, w_b, n_b, nd_b) = fire_pass(
            cells, cell_valid, base, win_next, wm_pane, max_seen, horizon)
        n_win_dropped = n_win_dropped + nd_b

        new_state = {
            "cells": cells,
            "cell_valid": cell_valid,
            "base": base,
            "win_next": win_next,
            "max_seen": max_seen,
            "horizon": horizon,
            "n_late": state["n_late"] + jnp.sum(late.astype(jnp.int64)),
            "n_evicted": state["n_evicted"] + evicted,
            "n_win_dropped": n_win_dropped,
        }
        # outputs: pass A1, A2, then B rows, [K, N_PASSES*MW] flattened
        all_passes = a_outs + [(fired_b, wvals_b, w_b, n_b)]
        w2 = jnp.concatenate([p[2] for p in all_passes])
        fired = jnp.concatenate([p[0] for p in all_passes], axis=1)
        wvals = jax.tree.map(
            lambda *leaves: jnp.concatenate(leaves, axis=1),
            *[p[1] for p in all_passes])
        NM = N_PASSES * MW
        out_ts = (w2 * D + R) * P_usec - 1                     # end-1 (TB)
        out = {
            "key": (jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None], (K, NM))
                + (jnp.int32(kb) if kb is not None else 0)).reshape(-1),
            "wid": jnp.broadcast_to(w2[None, :], (K, NM)).reshape(-1),
            "value": jax.tree.map(
                lambda a: a.reshape((K * NM,) + a.shape[2:]), wvals),
        }
        n_adv = sum(p[3] for p in all_passes)
        return new_state, out, fired.reshape(-1), \
            jnp.broadcast_to(out_ts[None, :], (K, NM)).reshape(-1), n_adv

    return step


def make_ffat_state(agg_spec, K: int, R: int):
    """Dense per-key FFAT device state over a static key space ``[0, K)``
    (see :class:`FfatWindowsTPU` for the layout)."""
    zeros = lambda shape: jax.tree.map(
        lambda s: jnp.zeros(shape + s.shape, s.dtype), agg_spec)
    return {
        "carry": zeros((K, R - 1)),               # trailing R-1 panes
        "carry_valid": jnp.zeros((K, R - 1), bool),
        "cur": zeros((K,)),                       # partial pane aggregate
        "cur_valid": jnp.zeros((K,), bool),
        "cur_fill": jnp.zeros((K,), jnp.int32),   # tuples in partial pane
        "pane_base": jnp.zeros((K,), jnp.int64),  # completed panes
        "win_next": jnp.full((K,), R, jnp.int64),  # next end pane
    }


def agg_spec_for(lift: Callable, payload_tree) -> Any:
    """Shape/dtype skeleton of one aggregate, from a batch payload pytree."""
    one = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), payload_tree)
    spec = jax.eval_shape(lift, one)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), spec)


