"""Hand-written Pallas TPU kernels for the FFAT hot loop.

The first subsystem where windflow_tpu emits its own TPU machine code
instead of leaning on XLA fusion (ROADMAP item 3): the hottest regions
of the fused FFAT/reduce programs — segmented grouping, the pane-level
sliding fold, and the dense segmented reduce — as Pallas kernels that
drop into the SAME wf_jit programs the lax compositions occupied
(zero dispatch-count change; ``Config.pallas_kernels`` /
``WF_TPU_PALLAS`` gates, lax path restored verbatim under ``=0``).
"""

from windflow_tpu.kernels.pallas_ffat import (PallasMode, dense_monoid_table,
                                              fold_supported,
                                              grouping_rank_hist,
                                              grouping_supported,
                                              monoid_identity_py, order_hist,
                                              pallas_build_count,
                                              pallas_forced, resolve_pallas,
                                              resolve_pallas_for,
                                              routed_monoid_tables,
                                              sliding_fold, table_leaf_ok,
                                              table_supported)

__all__ = [
    "PallasMode", "resolve_pallas", "resolve_pallas_for",
    "pallas_forced", "pallas_build_count",
    "grouping_supported", "grouping_rank_hist", "order_hist",
    "fold_supported", "sliding_fold",
    "table_supported", "table_leaf_ok", "dense_monoid_table",
    "routed_monoid_tables", "monoid_identity_py",
]
