"""Pallas TPU kernels for the FFAT hot loop (ROADMAP item 3).

Three kernels, chosen from the PROFILE_r05 component shares, each a
drop-in replacement for a lax composition INSIDE the same wf_jit
program (zero dispatch-count change — the kernels are traced into the
programs the jit registry already pins):

* **Segmented grouping** (:func:`grouping_rank_hist` /
  :func:`order_hist`) — the three components that each cost ~100-120%
  of the whole fused step standalone on the v5-lite profile
  (``key_extract_argsort``, ``grouping_rank_scatter``, ``sort_gather``)
  fused into ONE two-phase tiled kernel: an on-chip running key
  histogram (sequential TPU grid = cross-tile carry in VMEM scratch),
  stable within-tile rank assignment via a strictly-lower-triangular
  ones matmul on the MXU (the 1811.09736 "reduction as matmul" mapping
  — rank/histogram/offset gathers are one-hot contractions), and the
  counting-sort destinations emitted in the same pass.  Bit-identical
  to ``grouping.order_and_hist`` (both order by (id, arrival)).
* **Pane combine / sliding fold** (:func:`sliding_fold`) — the FlatFAT
  pane fold ``out[i] = fold(comb, panes[i-R+1..i])`` as a blocked scan:
  for declared ``"sum"`` over f32 the inner combine is an MXU matmul
  against a banded 0/1 carrier matrix (the 1811.09736 scan mapping);
  every other declared monoid/dtype runs the SAME dilated-doubling
  schedule as the lax fold on the VPU — bit-identical by construction
  (identical combine tree).  Generic traced combiners stay on the lax
  path (the WF607 downgrade, docs/ANALYSIS.md).
* **Segmented reduce** (:func:`dense_monoid_table`) — the PR 11
  dense/compacted one-scatter combine re-tiled: a sequential grid
  accumulates per-tile masked reductions into an HBM-contiguous
  ``[slots]`` table resident across grid steps, replacing the
  serialized XLA scatter with vectorized masked folds.

``Config.pallas_kernels`` / ``WF_TPU_PALLAS`` resolve here
(:func:`resolve_pallas`): ``"auto"`` compiles the kernels on TPU
backends and runs them ``interpret=True`` on the CPU fallback so
tier-1 exercises the real kernel bodies; ``"1"`` forces (downgrading
with a WF607 preflight warning where no lowering exists); ``"0"`` is
the kill switch — no kernel builds, the lax path verbatim.

Float-sum caveat (the psum tolerance, docs/PERF.md round 14): the MXU
banded matmul accumulates f32 sums in contraction order where the lax
fold uses a doubling tree — exact whenever the summands are integers
below 2**24 (every record-for-record A/B family), reassociation-grade
otherwise, exactly the tolerance the declared-"sum" contract already
implies for psum.  max/min and integer sums are bit-identical
unconditionally.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

#: lane tile of the grouping / table kernels (second-to-last dim of the
#: one-hot blocks; 256 keeps the [TILE, buckets] mask under ~4 MB VMEM
#: at the bucket ceiling below).
LANE_TILE = 256
#: key-row tile of the sliding-fold kernel.
ROW_TILE = 128
#: output-column chunk of the banded-matmul fold (band block is
#: [chunk + R - 1, chunk]).
FOLD_CHUNK = 128
#: bucket-space ceiling for the one-hot kernels: beyond it the
#: [TILE, buckets] masks outgrow VMEM and the lax path (radix /
#: scatter) keeps the job.
MAX_BUCKETS = 4096
#: lane-count ceiling: destinations are exact in f32 only below 2**24;
#: 2**22 leaves margin for the cross-tile offsets.
MAX_LANES = 1 << 22
#: window-width ceiling for the fold kernel (band block height).
MAX_FOLD_R = 512
#: pane-axis ceiling for the fold kernel: the whole (padded) pane row
#: lives in one VMEM block of [ROW_TILE, panes] per leaf (input +
#: output + the shared valid mask), so the axis must be bounded the
#: same way MAX_BUCKETS bounds the one-hot kernels — 4096 keeps a
#: worst-case 8-byte leaf block at 4 MB.  The TPU bench shape
#: (capacity 262144, P=128 → ~2064 panes) fits; wider rings keep the
#: lax fold.
MAX_FOLD_PANES = 4096

#: kernels built since import — the off-path budget assert reads this
#: (the kill switch must build NOTHING).
_BUILD_COUNT = 0


def pallas_build_count() -> int:
    return _BUILD_COUNT


class PallasMode(NamedTuple):
    """Resolved Pallas gate: ``interpret`` runs the kernel bodies under
    the Pallas interpreter (CPU tier-1) instead of Mosaic."""

    interpret: bool


def _mode_str(config) -> str:
    raw = getattr(config, "pallas_kernels", "auto")
    if raw is True:
        return "1"
    if raw is False:
        return "0"
    return str(raw).strip().lower()


def pallas_forced(config) -> bool:
    """True when the user explicitly forced the kernels on
    (``WF_TPU_PALLAS=1``) — the only mode whose downgrades warn
    (WF607); ``auto`` picks silently, mirroring WF606."""
    return _mode_str(config) in ("1", "on", "force", "true")


def resolve_pallas(config) -> Optional[PallasMode]:
    """Resolve ``Config.pallas_kernels`` against the runtime backend.

    ``None`` = lax path (kill switch, or no lowering for this
    backend).  TPU backends compile the kernels; the CPU fallback runs
    them ``interpret=True`` so tier-1 executes the real kernel bodies.
    Other backends (GPU: no Mosaic, and the TPU-shaped kernels have no
    Triton lowering here) downgrade to lax — named by WF607 when
    forced."""
    mode = _mode_str(config)
    if mode in ("0", "off", "false", ""):
        return None
    backend = jax.default_backend()
    if backend == "tpu":
        return PallasMode(interpret=False)
    if backend == "cpu":
        return PallasMode(interpret=True)
    return None


def resolve_pallas_for(op) -> Optional[PallasMode]:
    """:func:`resolve_pallas` against an OPERATOR's effective config —
    the graph-attached ``op.config`` when built inside a PipeGraph,
    else the process default (standalone operators: bench kernel legs,
    direct ``_step`` drivers).  THE one spelling of that fallback rule
    for every step builder."""
    from windflow_tpu.basic import default_config
    return resolve_pallas(getattr(op, "config", default_config))


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def _ceil_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _pad_axis(a, new: int, axis: int, value):
    pad = new - a.shape[axis]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=value)


def _iota2(dtype, shape, dim):
    return jax.lax.broadcasted_iota(dtype, shape, dim)


def _shift_cols(x, k: int, fill):
    """Shift a [..., N] VALUE right along the last axis by ``k``,
    filling the vacated low columns with ``fill`` (the in-kernel form
    of ``ffat_kernels._shift_leaf``)."""
    if k == 0:
        return x
    widths = [(0, 0)] * (x.ndim - 1) + [(k, 0)]
    return jnp.pad(x, widths, constant_values=fill)[..., :x.shape[-1]]


def _monoid_op(kind: str):
    return {"sum": jnp.add, "max": jnp.maximum, "min": jnp.minimum}[kind]


def _identity_scalar(kind: str, dtype):
    """The monoid identity as a PYTHON scalar — the jnp form
    (``ffat_kernels._monoid_identity``) becomes a tracer under
    omnistaging, which pallas would reject as a captured constant and
    pad/fill sites would needlessly stage.  Same values per dtype."""
    dt = np.dtype(dtype)
    if kind == "sum":
        return False if dt == np.bool_ else dt.type(0).item()
    if dt == np.bool_:
        return kind == "min"
    if dt.kind == "f":
        return float("-inf") if kind == "max" else float("inf")
    info = np.iinfo(dt)
    return int(info.min if kind == "max" else info.max)


#: public spelling for callers building ``dense_monoid_table`` inits
monoid_identity_py = _identity_scalar


# ---------------------------------------------------------------------------
# kernel 1: segmented grouping — rank + histogram + counting-sort dests
# ---------------------------------------------------------------------------

def grouping_supported(n: int, nbuckets: int) -> bool:
    """Gate for the grouping kernel: the one-hot tiles bound the bucket
    space, f32 exactness bounds the lane count.  Outside it the lax
    counting/radix/argsort path keeps the job (bit-identical either
    way)."""
    return 2 <= nbuckets <= MAX_BUCKETS and 0 < n <= MAX_LANES


def grouping_rank_hist(ids, nbuckets: int, interpret: bool):
    """Single-pass segmented grouping: returns ``(dest, rank, hist)``
    for int ids in ``[0, nbuckets)`` (callers pre-clamp, exactly the
    ``grouping.py`` contract).

    * ``rank[i]`` — arrival-stable rank of lane *i* among equal ids
      (``dense_rank``'s rank, computed without its 31-pass shifted
      compare: the within-tile half is ONE [TILE, TILE] x [TILE, NB]
      strictly-lower-triangular matmul on the MXU, the cross-tile half
      the sequential grid's running histogram).
    * ``dest[i] = bucket_start[id_i] + rank[i]`` — the stable
      counting-sort destination; ``invert_perm(dest)`` is exactly
      ``jnp.argsort(ids, stable=True)`` for such ids.
    * ``hist[b]`` — occurrences of id ``b``.

    Two phases over the same tiles (one sequential TPU grid): phase 0
    accumulates the histogram; phase 1 prefix-sums it into bucket
    starts (log-shift doubling over the [NB] row) and emits
    rank/dest while re-accumulating the running per-bucket offsets."""
    global _BUILD_COUNT
    _BUILD_COUNT += 1
    B = int(ids.shape[0])
    NB = int(nbuckets)
    NBp = _ceil_to(NB, 128)
    Bp = _ceil_to(B, LANE_TILE)
    T = Bp // LANE_TILE
    ids2 = _pad_axis(ids.astype(jnp.int32), Bp, 0, NB)[None, :]

    def kernel(ids_ref, dest_ref, rank_ref, hist_ref, run, bstart):
        ph = pl.program_id(0)
        t = pl.program_id(1)
        tiles = pl.num_programs(1)
        tids = ids_ref[0, :]
        lane = _iota2(jnp.int32, (LANE_TILE, 1), 0)[:, 0]
        real = (t * LANE_TILE + lane) < B
        onehot = (tids[:, None] == _iota2(jnp.int32, (LANE_TILE, NBp), 1)) \
            & real[:, None]
        colsum = jnp.sum(onehot.astype(jnp.int32), axis=0,
                         dtype=jnp.int32)[None, :]

        @pl.when(ph == 0)
        def _phase0():
            @pl.when(t == 0)
            def _():
                run[...] = jnp.zeros_like(run)

            run[...] += colsum

            @pl.when(t == tiles - 1)
            def _():
                hist_ref[...] = run[...]

        @pl.when(ph == 1)
        def _phase1():
            @pl.when(t == 0)
            def _():
                tot = run[0, :]
                inc = tot
                s = 1
                while s < NBp:
                    inc = inc + _shift_cols(inc, s, 0)
                    s *= 2
                bstart[...] = (inc - tot)[None, :]
                run[...] = jnp.zeros_like(run)

            onef = onehot.astype(jnp.float32)
            tri = (_iota2(jnp.int32, (LANE_TILE, LANE_TILE), 1)
                   < _iota2(jnp.int32, (LANE_TILE, LANE_TILE), 0)) \
                .astype(jnp.float32)
            # earlier[i, b] = lanes j < i of this tile with id_j == b —
            # the within-tile stable rank, as one MXU contraction
            earlier = jnp.dot(tri, onef,
                              preferred_element_type=jnp.float32)
            within = jnp.sum(onef * earlier, axis=1)
            # one-hot row selects = gathers: rank offset and bucket
            # start read through the same mask (f32 exact: all values
            # are counts below 2**24 — see MAX_LANES)
            cross = jnp.sum(
                onef * run[0, :].astype(jnp.float32)[None, :], axis=1)
            start = jnp.sum(
                onef * bstart[0, :].astype(jnp.float32)[None, :], axis=1)
            rank_ref[0, :] = (within + cross).astype(jnp.int32)
            dest_ref[0, :] = (within + cross + start).astype(jnp.int32)
            run[...] += colsum

    from jax.experimental.pallas import tpu as pltpu
    dest, rank, hist = pl.pallas_call(
        kernel,
        grid=(2, T),
        in_specs=[pl.BlockSpec((1, LANE_TILE), lambda p, t: (0, t))],
        out_specs=(pl.BlockSpec((1, LANE_TILE), lambda p, t: (0, t)),
                   pl.BlockSpec((1, LANE_TILE), lambda p, t: (0, t)),
                   pl.BlockSpec((1, NBp), lambda p, t: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((1, Bp), jnp.int32),
                   jax.ShapeDtypeStruct((1, Bp), jnp.int32),
                   jax.ShapeDtypeStruct((1, NBp), jnp.int32)),
        scratch_shapes=[pltpu.VMEM((1, NBp), jnp.int32),
                        pltpu.VMEM((1, NBp), jnp.int32)],
        interpret=interpret,
    )(ids2)
    return dest[0, :B], rank[0, :B], hist[0, :NB]


def order_hist(ids, nbuckets: int, interpret: bool):
    """Pallas twin of ``grouping.order_and_hist``: the stable grouping
    permutation plus the id histogram.  The kernel emits counting-sort
    DESTINATIONS; one O(n) scatter of iota inverts them into gather
    indices (``grouping.invert_perm`` — the same single scatter the lax
    path already pays)."""
    from windflow_tpu.windows.grouping import invert_perm
    dest, _, hist = grouping_rank_hist(ids, nbuckets, interpret)
    return invert_perm(dest), hist


# ---------------------------------------------------------------------------
# kernel 2: pane combine / sliding fold
# ---------------------------------------------------------------------------

def _fold_leaf_dtype_ok(dtype, interpret: bool) -> bool:
    """Per-leaf dtype gate for the fold kernel — same stance as
    :func:`table_leaf_ok`: the interpreter folds any numeric dtype
    exactly; compiled Mosaic keeps to the natively tiled f32/i32 set
    (int64/f64 pane aggregates keep the lax fold on a real TPU; bool
    is excluded in both modes — its max/min degenerate to or/and and
    the lax fold owns that edge)."""
    dt = jnp.dtype(dtype)
    if dt == jnp.bool_:
        return False
    if interpret:
        return dt.kind in "fiu"
    return dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.int32))


def fold_supported(values, R: int, monoid: Optional[str],
                   interpret: bool) -> bool:
    """Gate for the sliding-fold kernel: declared monoid, 2-D
    ``[K, panes]`` leaves (scalar aggregates — trailing-dim aggregates
    keep the lax fold), kernel-foldable dtypes per backend mode, a
    band that fits the blocked matmul, and a pane axis whose full row
    fits the VMEM block (MAX_FOLD_PANES — the fold keeps whole rows
    resident, unlike the chunked one-hot kernels)."""
    if monoid not in ("sum", "max", "min") or not (1 <= R <= MAX_FOLD_R):
        return False
    leaves = jax.tree_util.tree_leaves(values)
    if not leaves or not all(l.ndim == 2 for l in leaves):
        return False
    if int(leaves[0].shape[1]) + (R - 1) > MAX_FOLD_PANES:
        return False
    return all(_fold_leaf_dtype_ok(l.dtype, interpret) for l in leaves)


def _fold_leaf(x, valid, R: int, monoid: str):
    """One leaf's in-kernel fold over a ``[rows, NPPp]`` block: the
    banded MXU matmul for f32 sums, the lax fold's OWN dilated-doubling
    schedule (bit-identical combine tree) for everything else."""
    ident = _identity_scalar(monoid, x.dtype)
    filled = jnp.where(valid, x, ident)
    if monoid == "sum" and x.dtype == jnp.float32:
        rows, npp = filled.shape
        padded = jnp.pad(filled, ((0, 0), (R - 1, 0)),
                         constant_values=0.0)
        chunks = []
        for c0 in range(0, npp, FOLD_CHUNK):
            ch = min(FOLD_CHUNK, npp - c0)
            sub = padded[:, c0:c0 + ch + R - 1]
            li = _iota2(jnp.int32, (ch + R - 1, ch), 0)
            mi = _iota2(jnp.int32, (ch + R - 1, ch), 1)
            band = ((li >= mi) & (li <= mi + (R - 1))) \
                .astype(jnp.float32)
            chunks.append(jnp.dot(sub, band,
                                  preferred_element_type=jnp.float32))
        return jnp.concatenate(chunks, axis=1)
    # VPU path: EXACTLY ffat_kernels._sliding_reduce_plain's schedule
    # (pow2 doubling + binary stitching) so float results are
    # bit-identical to the lax fold, not merely equivalent
    op = _monoid_op(monoid)
    pow2 = [filled]
    width = 1
    while width * 2 <= R:
        v = pow2[-1]
        pow2.append(op(_shift_cols(v, width, ident), v))
        width *= 2
    res = None
    offset = 0
    for j in range(len(pow2) - 1, -1, -1):
        w = 1 << j
        if R & w:
            v = _shift_cols(pow2[j], offset, ident)
            res = v if res is None else op(v, res)
            offset += w
    return res


def sliding_fold(values, valid, R: int, monoid: str, interpret: bool):
    """Pallas pane combine: ``out[k, i] = fold(monoid-op,
    values[k, i-R+1..i])`` with invalid panes absorbed as the monoid
    identity — the kernel twin of ``_monoid_fill`` +
    ``_sliding_reduce_plain`` fused into one VMEM-resident pass,
    blocked over key rows."""
    global _BUILD_COUNT
    _BUILD_COUNT += 1
    leaves, treedef = jax.tree_util.tree_flatten(values)
    K, NPP = (int(leaves[0].shape[0]), int(leaves[0].shape[1]))
    Kp = _ceil_to(K, ROW_TILE)
    NPPp = _ceil_to(NPP, 128)
    vpad = _pad_axis(_pad_axis(valid, Kp, 0, False), NPPp, 1, False)
    lpad = [
        _pad_axis(_pad_axis(l, Kp, 0,
                            _identity_scalar(monoid, l.dtype)),
                  NPPp, 1, _identity_scalar(monoid, l.dtype))
        for l in leaves]

    def kernel(valid_ref, *refs):
        ins = refs[:len(leaves)]
        outs = refs[len(leaves):]
        v = valid_ref[...]
        for i_ref, o_ref in zip(ins, outs):
            o_ref[...] = _fold_leaf(i_ref[...], v, R, monoid)

    spec = pl.BlockSpec((ROW_TILE, NPPp), lambda k: (k, 0))
    folded = pl.pallas_call(
        kernel,
        grid=(Kp // ROW_TILE,),
        in_specs=[spec] * (1 + len(leaves)),
        out_specs=tuple([spec] * len(leaves)),
        out_shape=tuple(jax.ShapeDtypeStruct((Kp, NPPp), l.dtype)
                        for l in leaves),
        interpret=interpret,
    )(vpad, *lpad)
    if not isinstance(folded, (list, tuple)):
        folded = (folded,)
    return jax.tree_util.tree_unflatten(
        treedef, [f[:K, :NPP] for f in folded])


# ---------------------------------------------------------------------------
# kernel 3: segmented reduce — dense monoid slot tables
# ---------------------------------------------------------------------------

def table_supported(n: int, nslots: int) -> bool:
    """Slot-space/lane-count gate for the dense-table kernel (the
    [TILE, slots] one-hot bound; beyond it the lax scatter keeps the
    job)."""
    return 1 <= nslots <= MAX_BUCKETS and 0 < n <= MAX_LANES


def table_leaf_ok(shape, dtype, interpret: bool) -> bool:
    """Per-leaf gate for the dense-table kernel: 1-D lanes or packed
    ``[B, W]`` carrier columns; under the interpreter every numeric
    dtype folds exactly, compiled Mosaic keeps to the natively tiled
    f32/i32/bool set (other dtypes stay on the lax scatter — per-leaf
    routing, values unchanged either way)."""
    if len(shape) not in (1, 2):
        return False
    if len(shape) == 2 and shape[1] > 8:
        return False
    dt = jnp.dtype(dtype)
    if interpret:
        return dt.kind in "fiub"
    return dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.int32),
                  jnp.dtype(jnp.bool_))


def routed_monoid_tables(row, payload, monoid: str,
                         nslots: int, interpret: bool,
                         lax_leaf, ts=None, ts_init: int = 0,
                         lax_ts=None, want_count: bool = False):
    """Per-leaf routing around :func:`dense_monoid_table` — THE shared
    front door for the dense/compacted reduce steps (ops/tpu.py,
    parallel/compaction.py), so the dtype gates, the ts-column probe,
    and every fallback merge live once.

    Returns ``None`` when no leaf of the ``payload`` pytree passes the
    gates (caller keeps its pure-lax body), else
    ``(table_tree, ts_table, count_table)`` where ``table_tree``
    mirrors ``payload`` with gated-out leaves computed through
    ``lax_leaf(leaf)``, ``ts_table`` is the per-slot max of ``ts``
    starting from ``ts_init`` — computed by ``lax_ts()`` instead when
    ``ts``'s int64 lanes fail the compiled dtype gate (``None`` when
    ``ts`` was not given) — and ``count_table`` the int32 per-slot
    lane count (``None`` unless ``want_count``)."""
    leaves, treedef = jax.tree_util.tree_flatten(payload)
    B = int(row.shape[0])
    if not table_supported(B, nslots):
        return None
    routed = [table_leaf_ok(l.shape, l.dtype, interpret) for l in leaves]
    if not any(routed):
        return None
    hot = [l for l, r in zip(leaves, routed) if r]
    vals = list(hot)
    ops = [monoid] * len(hot)
    inits = [_identity_scalar(monoid, l.dtype) for l in hot]
    if want_count:
        vals.append(jnp.ones(B, jnp.int32))
        ops.append("sum")
        inits.append(0)
    ts_rides = ts is not None and table_leaf_ok((B,), jnp.int64,
                                                interpret)
    if ts_rides:
        vals.append(ts)
        ops.append("max")
        inits.append(int(ts_init))
    tabs = dense_monoid_table(row, vals, ops, inits, nslots, interpret)
    it = iter(tabs[:len(hot)])
    table_tree = jax.tree_util.tree_unflatten(
        treedef, [next(it) if r else lax_leaf(l)
                  for l, r in zip(leaves, routed)])
    cnt = tabs[len(hot)] if want_count else None
    if ts_rides:
        ts_t = tabs[-1]
    else:
        ts_t = lax_ts() if (ts is not None and lax_ts is not None) \
            else None
    return table_tree, ts_t, cnt


def dense_monoid_table(row, leaves: Sequence, ops: Sequence[str],
                       inits: Sequence, nslots: int,
                       interpret: bool) -> List:
    """Segmented reduce into dense slot tables: for each leaf,
    ``table[s] = fold(op, leaf[lanes with row == s])`` over
    ``s in [0, nslots)``, starting from ``init`` (lanes whose ``row``
    falls outside ``[0, nslots)`` — the dump row of the lax scatter —
    contribute nothing).  Leaves are ``[B]`` lanes or ``[B, W]`` packed
    carrier columns; each carries its own op ("sum" | "max" | "min")
    and init, so the payload tables, the ts max column, and the
    liveness count ride ONE kernel.

    A sequential grid walks lane tiles; the tables live in the output
    block (constant index map — resident across grid steps), so the
    combine is a vectorized masked fold per tile instead of XLA's
    serialized scatter."""
    global _BUILD_COUNT
    _BUILD_COUNT += 1
    # inits must be PYTHON scalars: a jnp identity would be captured as
    # a traced constant inside the kernel closure, which pallas rejects
    inits = [i if isinstance(i, (int, float, bool))
             else np.asarray(i).item() for i in inits]
    B = int(row.shape[0])
    S = int(nslots)
    Sp = _ceil_to(S, 128)
    Bp = _ceil_to(B, LANE_TILE)
    row2 = _pad_axis(row.astype(jnp.int32), Bp, 0, S)[None, :]
    ins = []
    widths = []
    for l in leaves:
        if l.ndim == 1:
            ins.append(_pad_axis(l[None, :], Bp, 1, 0))
            widths.append(1)
        else:
            ins.append(_pad_axis(l.T, Bp, 1, 0))
            widths.append(int(l.shape[1]))

    def kernel(row_ref, *refs):
        t = pl.program_id(0)
        vrefs = refs[:len(ins)]
        orefs = refs[len(ins):]
        ids = row_ref[0, :]
        lane = _iota2(jnp.int32, (LANE_TILE, 1), 0)[:, 0]
        real = ((t * LANE_TILE + lane) < B) & (ids >= 0) & (ids < S)
        onehot = (ids[:, None] == _iota2(jnp.int32, (LANE_TILE, Sp), 1)) \
            & real[:, None]

        @pl.when(t == 0)
        def _():
            for o_ref, init in zip(orefs, inits):
                o_ref[...] = jnp.full(o_ref.shape, init, o_ref.dtype)

        for v_ref, o_ref, op, w in zip(vrefs, orefs, ops, widths):
            op_fn = _monoid_op(op)
            for col in range(w):
                v = v_ref[col, :]
                if op == "sum":
                    contrib = jnp.sum(
                        jnp.where(onehot, v[:, None],
                                  jnp.zeros((), v.dtype)),
                        axis=0, dtype=v.dtype)
                else:
                    ident = _identity_scalar(op, v.dtype)
                    contrib = (jnp.max if op == "max" else jnp.min)(
                        jnp.where(onehot, v[:, None], ident), axis=0)
                o_ref[col, :] = op_fn(o_ref[col, :], contrib)

    out_specs = tuple(pl.BlockSpec((w, Sp), lambda t: (0, 0))
                      for w in widths)
    outs = pl.pallas_call(
        kernel,
        grid=(Bp // LANE_TILE,),
        in_specs=[pl.BlockSpec((1, LANE_TILE), lambda t: (0, t))]
        + [pl.BlockSpec((w, LANE_TILE), lambda t: (0, t))
           for w in widths],
        out_specs=out_specs,
        out_shape=tuple(jax.ShapeDtypeStruct((w, Sp), l.dtype)
                        for w, l in zip(widths, leaves)),
        interpret=interpret,
    )(row2, *ins)
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    tables = []
    for o, l, w in zip(outs, leaves, widths):
        tables.append(o[0, :S] if l.ndim == 1 else o[:, :S].T)
    return tables
