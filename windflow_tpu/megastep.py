"""Device-resident sweep megastep: K batch sweeps in ONE compiled program.

PR 13 shrank the bytes and PR 14 the kernels; what bounds the staged
e2e now is the HOST PACER — every batch still pays one Python-driven
dispatch round trip (pack, ship, dispatch, drain), so throughput is
batches/s times whatever the host loop manages, not what the chip can
sustain.  This module lifts the fusion executor's move one level, from
per-sweep to per-K-sweeps (the DrJAX whole-round-as-one-program stance,
arXiv 2403.07128): a ``lax.scan`` over a staged super-batch of K packed
wire buffers whose body is the EXISTING per-sweep program — the shared
unpack decode (``batch.unpack_body``, wire decompression included)
feeding the tail operator's raw step function, extracted from the very
``wf_jit`` wrapper the per-batch path dispatches.  One program, one
host→device super-transfer, one device→host drain per K batches.

Correctness stance — the per-batch path IS the reference semantics:

* The scan body calls the tail's own traced step (``WfJit._fn``), so a
  megastep's K outputs are record-for-record what K per-batch dispatches
  produce.  ``Config.megastep_sweeps = 1`` (the kill switch) never
  builds a plane and restores today's cadence verbatim.
* Warm-up, capacity/treedef/wire-format changes, partial groups at a
  flush (quiesce, EOS, punctuation cadence), and a non-empty tail inbox
  all fall back to the per-batch ship — record-identical by
  construction, so eligibility can be conservative without being wrong.
* Step REBUILDS (TB ring regrow, durability restore) are detected by
  wrapper object IDENTITY: the scan cache pins the wrapper it traced
  and recompiles when the operator swapped it.
* Host-side per-batch bookkeeping (watermark advance, TB span regrow,
  flight-recorder spans, stats counters) replays at K-granularity from
  the packet metadata each batch carried — the trace lane stamps
  PER-BATCH timestamps (staged at enqueue, collected/dispatched at the
  megastep, sunk at the sink), so Latency p50/p99 stays honest.

Eligible edges: a single-destination host→device staging edge
(``DeviceStageEmitter``) on a source replica, feeding one replica of a
single-chip, non-compacted FfatWindowsTPU (CB or TB), ReduceTPU
(sorted or dense declared-monoid), or dense-keys stateful map/filter —
fused preludes ride along for free (they live inside the raw step).
Everything else (host operators, host-interning stateful tails,
mesh-sharded state, compacted key spaces) downgrades to per-batch;
preflight surfaces the downgrade as WF608 when the user FORCED K>1
(analysis/preflight.py).

Dispatch accounting: one megastep is ONE registry dispatch
(``megastep.<tail>``) serving K logical batches; the tail replica's
``device_programs_launched`` advances per LOGICAL batch so the sweep
ledger's ``dispatches_per_batch`` honestly reports 1/K
(docs/OBSERVABILITY.md "Megastep in the ledger").
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from windflow_tpu import staging
from windflow_tpu.analysis.hotpath import hot_path
from windflow_tpu.basic import current_time_usecs
from windflow_tpu.batch import WM_NONE, DeviceBatch, unpack_body
from windflow_tpu.monitoring import recorder as flightrec
from windflow_tpu.monitoring.jit_registry import wf_jit

#: default K on real accelerator backends ("auto"); the CPU fallback
#: stays per-batch so the tier-1 suite exercises the verbatim cadence
AUTO_K = 8


def resolve_megastep(config) -> int:
    """Resolved megastep width K from ``Config.megastep_sweeps`` /
    ``WF_TPU_MEGASTEP``: "auto" → AUTO_K on tpu/gpu backends and 1 on
    the CPU fallback; an explicit integer forces that K anywhere
    (including CPU — the bench's A/B lever); K <= 1 is the kill
    switch."""
    raw = getattr(config, "megastep_sweeps", "auto")
    if raw is None:
        raw = "auto"
    if isinstance(raw, str):
        s = raw.strip().lower()
        if s in ("", "auto"):
            return AUTO_K if jax.default_backend() in ("tpu", "gpu") else 1
        raw = int(s)
    return max(1, int(raw))


def megastep_forced(config) -> int:
    """The K the user EXPLICITLY forced (> 1), or 0 when the gate is
    "auto"/kill-switch — preflight only warns about ineligible graphs
    when the user asked for a K the graph cannot honor (WF608)."""
    raw = getattr(config, "megastep_sweeps", "auto")
    if raw is None:
        return 0
    if isinstance(raw, str):
        s = raw.strip().lower()
        if s in ("", "auto"):
            return 0
        raw = int(s)
    k = int(raw)
    return k if k > 1 else 0


def tail_kind(op):
    """``(kind, None)`` when ``op`` can tail a megastep scan, else
    ``(None, reason)`` — the reason strings feed the WF608 preflight
    hint.  Kind selects the scan-body adapter (carry layout + raw step
    signature)."""
    if not getattr(op, "is_tpu", False):
        return None, "host operator (no device step to fold into a scan)"
    if getattr(op, "mesh", None) is not None:
        return None, "mesh-sharded state (per-chip collectives per batch)"
    if getattr(op, "_compactor", None) is not None:
        return None, ("compacted key space (host admission runs per "
                      "batch)")
    if getattr(op, "_fusion_exec", None) is not None:
        return None, ("all-stateless fused segment (no stateful tail "
                      "step to carry)")
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    if isinstance(op, FfatWindowsTPU):
        if op.parallelism != 1:
            return None, "parallel window state (per-replica rings)"
        return ("ffat_tb" if op.is_tb else "ffat_cb"), None
    if isinstance(op, ReduceTPU):
        if op.monoid is not None and op.max_keys is not None:
            return "reduce_dense", None
        return "reduce_sorted", None
    if isinstance(op, _StatefulTPUBase):
        if not op.dense_keys:
            return None, ("host-interning stateful (per-batch D2H "
                          "intern sync; declare withDenseKeys)")
        return "stateful", None
    return None, f"unsupported tail operator {type(op).__name__}"


def _raw_fn(wrapper):
    """The undecorated step body behind a ``wf_jit`` wrapper: the
    registry's ``WfJit`` keeps it as ``_fn``; with the watch plane off
    ``wf_jit`` returns plain ``jax.jit`` which exposes
    ``__wrapped__``."""
    if wrapper is None:
        return None
    fn = getattr(wrapper, "_fn", None)
    if fn is not None:
        return fn
    return getattr(wrapper, "__wrapped__", None)


class _SpanMeta:
    """Host-metadata stand-in for a DeviceBatch: exactly the fields
    FfatWindowsTPU._regrow_for_span reads (all host stamps, zero device
    syncs)."""

    __slots__ = ("ts_max", "ts_min", "frontier")

    def __init__(self, ts_max, ts_min, frontier):
        self.ts_max = ts_max
        self.ts_min = ts_min
        self.frontier = frontier


class MegastepEdge:
    """One eligible staging edge: the per-edge packet queue, the cached
    scan program, and the drain that replays per-batch bookkeeping.

    The feeding ``DeviceStageEmitter`` offers every finalized packed
    batch here (``offer``); acceptance queues it and the K-th packet
    runs the megastep.  Refusal (tail cold, signature change mid-group)
    and ``drain_remainder`` (external flush: quiesce, EOS, punctuation)
    ship per-batch through the emitter's verbatim path — so durability
    epochs land on megastep boundaries and partial groups stay
    record-identical."""

    def __init__(self, k: int, op, rep, emitter, kind: str) -> None:
        self.k = k
        self.op = op
        self.rep = rep          # the tail operator's single replica
        self.emitter = emitter  # the feeding DeviceStageEmitter
        self.kind = kind
        self._q = []
        # scan-program cache: (tail wrapper identity, wire fmt) -> the
        # wf_jit'd scan.  The wrapper ref is STRONG on purpose: object
        # identity is the rebuild signal (regrow/restore swap it), and a
        # GC'd wrapper could otherwise recycle its id
        self._scan_wrapper = None
        self._scan_fmt = None
        self._scan = None
        # counters (plane summary / bench / observability docs)
        self.megasteps = 0
        self.batches = 0            # logical batches served by scans
        self.fallback_batches = 0   # per-batch ships while warm
        self.warmup_batches = 0     # per-batch ships while cold
        # per-packet event-time span accumulation (ts_max - ts_min of the
        # staged lanes): the measured basis of the K x batch-span
        # freshness floor the latency ledger surfaces per edge
        self._span_sum_usec = 0.0
        self._span_n = 0
        # preallocated per-megastep scratch (the @hot_path contract on
        # run(): no per-group allocations).  Refilling per megastep is
        # safe: the previous group's one blocking D2H drain returned
        # before the next run() starts, so the device has consumed the
        # prior H2D of these buffers.
        self._wm_buf = np.empty(k, np.int64)
        self._trace_buf = [None] * k

    # -- eligibility at offer time -------------------------------------------
    def _tail_warm(self, cap: int) -> bool:
        """True once the tail's per-batch path has built everything the
        scan body reuses (capacity pinned, step program traced, state
        initialized, first-batch contract checks done).  Cold tails keep
        the per-batch path — which is exactly the warm-up the per-batch
        path performs."""
        op, kind = self.op, self.kind
        if op._compactor is not None or op.mesh is not None:
            return False    # attached after plane build: stand down
        if kind in ("ffat_cb", "ffat_tb"):
            if op._capacity != cap or op._jit_step is None \
                    or 0 not in op._states:
                return False
            return not (kind == "ffat_tb" and op._payload_zero is None)
        if kind == "reduce_sorted":
            return cap in op._jit_steps
        if kind == "reduce_dense":
            return ("dense", cap) in op._jit_steps
        return cap in op._steps     # stateful dense-keys

    def _wrapper(self, cap: int):
        op, kind = self.op, self.kind
        if kind in ("ffat_cb", "ffat_tb"):
            return op._jit_step
        if kind == "reduce_sorted":
            return op._jit_steps.get(cap)
        if kind == "reduce_dense":
            return op._jit_steps.get(("dense", cap))
        return op._steps.get(cap)

    @staticmethod
    def _sig_match(a, b) -> bool:
        return (a.treedef == b.treedef and a.dtypes == b.dtypes
                and a.capacity == b.capacity and a.fmt == b.fmt
                and a.buf.shape[0] == b.buf.shape[0])

    # -- emitter contract ----------------------------------------------------
    @hot_path
    def offer(self, pkt) -> bool:
        """Queue one finalized packed batch.  False → the caller ships
        it per-batch (tail cold).  A signature change against the queued
        group drains the group per-batch first — a megastep only ever
        runs K same-shaped buffers."""
        if not self._tail_warm(pkt.capacity):
            self.warmup_batches += 1
            return False
        if self._q and not self._sig_match(self._q[0], pkt):
            self.drain_remainder()
        if self.kind == "ffat_tb":
            # TB host prep replays per batch IN ARRIVAL ORDER at enqueue
            # (exactly the per-batch _step preamble): span regrow —
            # which may rebuild the step; the run-time identity check
            # recompiles the scan — the fold flag, and the wm_pane the
            # scan lane carries.
            op = self.op
            front = pkt.frontier if pkt.frontier >= pkt.wm else pkt.wm
            if op._auto_np:
                op._regrow_for_span(
                    _SpanMeta(pkt.ts_max, pkt.ts_min, front))
            if front != WM_NONE:
                op._fold_stepped = True
            pkt.wm_pane = op._wm_pane(front)
        self._q.append(pkt)
        if len(self._q) >= self.k:
            self.run()
        return True

    @hot_path
    def drain_remainder(self) -> None:
        """Ship every queued packet per-batch (FIFO) through the
        feeding emitter's verbatim path — external flushes (quiesce,
        EOS, punctuation cadence) call this so a checkpoint or a
        watermark never overtakes queued data."""
        q, self._q = self._q, []
        for pkt in q:
            self.fallback_batches += 1
            self.emitter._ship_packed(pkt)

    # -- the megastep itself -------------------------------------------------
    def _scan_for(self, wrapper, pkt):
        if self._scan is not None and self._scan_wrapper is wrapper \
                and self._scan_fmt == pkt.fmt:
            return self._scan
        self._scan = self._build_scan(wrapper, pkt)
        self._scan_wrapper = wrapper
        self._scan_fmt = pkt.fmt
        # direct operator attribute: the sweep ledger's wrapper walk
        # (monitoring/sweep_ledger._op_wrappers) finds it there, so the
        # megastep's dispatch count lands in the tail's ledger row
        self.op._megastep_jit = self._scan
        return self._scan

    def _build_scan(self, wrapper, pkt):
        """ONE wf_jit program: scan the K packed buffers through the
        shared unpack decode + the tail's raw step.  The carry is the
        tail's cross-batch state (pane ring / slot table / drop
        counter); per-batch outputs stack on the scan's ys axis."""
        raw = _raw_fn(wrapper)
        kind = self.kind
        treedef = pkt.treedef
        unpack = unpack_body(pkt.dtypes, pkt.capacity, wire=pkt.fmt)

        def decode(buf):
            cols, ts, valid, _n = unpack(buf)
            return jax.tree.unflatten(treedef, list(cols)), ts, valid

        if kind == "ffat_cb":
            def body(carry, x):
                payload, ts, valid = decode(x["buf"])
                st, out, fired, out_ts = raw(carry, payload, ts, valid)
                return st, (out, out_ts, fired)
        elif kind == "ffat_tb":
            def body(carry, x):
                payload, ts, valid = decode(x["buf"])
                st, out, fired, out_ts, _n_adv = raw(
                    carry, payload, ts, valid, x["wm"])
                return st, (out, out_ts, fired)
        elif kind == "reduce_sorted":
            def body(carry, x):
                payload, ts, valid = decode(x["buf"])
                _keys, out, out_ts, out_valid = raw(None, payload, ts,
                                                    valid)
                return carry, (out, out_ts, out_valid)
        elif kind == "reduce_dense":
            def body(carry, x):
                payload, ts, valid = decode(x["buf"])
                table, ts_t, has, n_drop = raw(None, payload, ts, valid)
                return carry + n_drop, (table, ts_t, has)
        else:   # stateful dense-keys map/filter
            def body(carry, x):
                payload, ts, valid = decode(x["buf"])
                st, out, out_valid = raw(carry, payload, valid, None)
                return st, (out, ts, out_valid)

        def mega(carry, xs):
            return jax.lax.scan(body, carry, xs)

        # state kinds donate the carry exactly like the per-batch steps
        # (ring/table updated in place); the reduce kinds' carries are
        # None or a host-referenced drop scalar — nothing to donate
        donate = (0,) if kind in ("ffat_cb", "ffat_tb", "stateful") \
            else ()
        return wf_jit(mega, op_name=f"megastep.{self.op.name}",
                      donate_argnums=donate)

    def _carry_init(self):
        op, kind = self.op, self.kind
        if kind in ("ffat_cb", "ffat_tb"):
            return op._states[0]
        if kind == "stateful":
            return op._state
        if kind == "reduce_dense":
            d = op._mesh_dropped
            return jnp.int64(0) if d is None else d
        return None

    def _commit_carry(self, carry) -> None:
        op, kind = self.op, self.kind
        if kind in ("ffat_cb", "ffat_tb"):
            op._states[0] = carry
        elif kind == "stateful":
            op._state = carry
        elif kind == "reduce_dense":
            op._mesh_dropped = carry

    @hot_path
    def run(self) -> None:
        """Execute one full-K megastep: stack the queued buffers into a
        pooled super-buffer, dispatch the scan, commit the carry, then
        drain the stacked outputs ONCE and emit K per-batch
        DeviceBatches downstream with their original per-batch
        watermark/trace/frontier stamps."""
        if len(self._q) < self.k:
            return
        rep = self.rep
        if rep.inbox or rep.done:
            # warm-up stragglers (or punctuation) still queued in the
            # tail's inbox: running the scan now would overtake them —
            # fall back per-batch, order preserved
            self.drain_remainder()
            return
        wrapper = self._wrapper(self._q[0].capacity)
        raw = _raw_fn(wrapper)
        if raw is None:
            self.drain_remainder()
            return
        group, self._q = self._q, []
        mega = self._scan_for(wrapper, group[0])

        # super-batch staging: ONE pooled K*L host buffer, ONE H2D
        nwords = group[0].buf.shape[0]
        pool = group[0].pool
        sup = pool.acquire(self.k * nwords)
        for i, p in enumerate(group):
            sup[i * nwords:(i + 1) * nwords] = p.buf
            p.pool.release(p.buf, None)     # host copy done, no gate
        xs = {"buf": jax.device_put(sup.reshape(self.k, nwords))}
        if self.kind == "ffat_tb":
            for i, p in enumerate(group):
                self._wm_buf[i] = p.wm_pane
            xs["wm"] = jax.device_put(self._wm_buf)

        # trace lane, per batch at GROUP times: collected+dispatched when
        # the scan actually launches (so emitted->dispatched measures each
        # batch's real K-wait) and device_done when the one blocking D2H
        # drain returns.  Both stamps are shared by the whole K-group, so
        # they carry shared_k=K — the latency ledger keeps the wall value
        # (each batch truly waited) but divides device-busy credit by K
        # instead of smearing the group's compute onto every batch.
        ring = self.rep.ring
        traced = self._trace_buf      # preallocated: no per-group list
        n_traced = 0
        if ring is not None:
            for p in group:
                if p.trace is not None:
                    traced[n_traced] = p.trace
                    n_traced += 1
        if n_traced:
            t_disp = current_time_usecs()
            for idx in range(n_traced):
                tr = traced[idx]
                ring.record(tr[0], flightrec.COLLECTED, t_disp,
                            shared=self.k)
                ring.record(tr[0], flightrec.DISPATCHED, t_disp,
                            shared=self.k)
        carry, ys = mega(self._carry_init(), xs)
        # the ONE blocking D2H per megastep: materialize the stacked
        # outputs; per-batch slices below are zero-copy numpy views
        host = jax.tree.map(np.asarray, ys)
        if n_traced:
            t_done = current_time_usecs()
            for idx in range(n_traced):
                ring.record(traced[idx][0], flightrec.DEVICE_DONE,
                            t_done, shared=self.k)
        pool.release(sup, None)     # outputs ready => device read it
        self._commit_carry(carry)
        self.megasteps += 1
        self.batches += self.k
        for p in group:
            if p.ts_max is not None and p.ts_min is not None \
                    and p.ts_max >= p.ts_min > 0:
                self._span_sum_usec += p.ts_max - p.ts_min
                self._span_n += 1

        self._emit(group, host)
        self._post_hooks()

    @hot_path
    def _emit(self, group, host) -> None:
        """Per-batch honesty at drain: each of the K logical batches
        advances the tail replica's watermark, counters, and trace
        spans exactly as its own dispatch would, then rides the tail's
        emitter downstream (the sink stamps SUNK + e2e per batch)."""
        rep, op, kind = self.rep, self.op, self.kind
        lat = rep.latency
        windowed = kind in ("ffat_cb", "ffat_tb")
        fused = op._fused_prelude is not None
        filt = bool(getattr(op, "_is_filter", False))
        for i, p in enumerate(group):
            staging.device_bytes.note(p.nbytes, p.logical_nbytes)
            rep._advance_wm(p.wm)
            rep.stats.inputs_received += p.n
            tr = p.trace
            # collected/dispatched/device_done stamped at group times in
            # run() (shared_k=K); here only the freshness gauge fires —
            # ts_i/valid_i are already host numpy from the one drain, so
            # fire-time minus window-close costs zero extra syncs
            pay = jax.tree.map(lambda a: a[i], host[0])
            ts_i = host[1][i]
            valid_i = host[2][i]
            if lat is not None and windowed and tr is not None:
                lat.note_window_fire(op.name, ts_i, valid_i)
            front = p.frontier if p.frontier >= p.wm else p.wm
            if kind in ("ffat_cb", "ffat_tb"):
                out = DeviceBatch(pay, ts_i, valid_i, watermark=p.wm,
                                  size=None)
            elif kind in ("reduce_sorted", "reduce_dense"):
                out = DeviceBatch(pay, ts_i, valid_i, watermark=p.wm,
                                  size=None, frontier=front)
            else:
                size = None if (filt or fused) else p.n
                out = DeviceBatch(pay, ts_i, valid_i, watermark=p.wm,
                                  size=size, frontier=front)
            out.trace = tr
            # one LOGICAL batch served: the ledger divides the single
            # megastep dispatch by these to report 1/K honestly
            rep.stats.device_programs_launched += 1
            rep.stats.outputs_sent += out.known_size or 0
            rep.emitter.emit_device_batch(out)
            rep._maybe_hook_wm()

    def _post_hooks(self) -> None:
        """The per-batch cadence checkpoints, replayed once per
        megastep (the cadences are heuristics; crossing them once per K
        batches keeps their guarantees)."""
        op, kind = self.op, self.kind
        if kind == "ffat_tb":
            before = op._overflow_steps
            op._overflow_steps = before + self.k
            if (before + self.k) // 32 > before // 32:
                if op._auto_np:
                    op._maybe_regrow()
                if op.overflow_policy == "error":
                    op._check_overflow()
        elif kind == "reduce_dense":
            op._drop_steps += self.k
            if not op._drop_warned and op._drop_steps % 64 < self.k:
                prev = op._pending_drop
                op._pending_drop = op._mesh_dropped
                if prev is not None:
                    op._maybe_warn_drops(int(prev))

    def freshness_floor_usec(self):
        """The explicit freshness floor a K-group imposes: a batch's
        result cannot leave the device sooner than the K x mean batch
        event-time span it waited to group with (docs/OBSERVABILITY.md
        "Latency plane & SLO"); None before any scanned batch carried
        event-time extrema."""
        if not self._span_n:
            return None
        return round(self.k * self._span_sum_usec / self._span_n, 3)

    def summary(self) -> dict:
        return {
            "operator": self.op.name,
            "kind": self.kind,
            "k": self.k,
            "megasteps": self.megasteps,
            "batches": self.batches,
            "fallback_batches": self.fallback_batches,
            "warmup_batches": self.warmup_batches,
            "freshness_floor_usec": self.freshness_floor_usec(),
        }


class MegastepPlane:
    """Graph-level view: the resolved K and the eligible edges.  Built
    by PipeGraph._build AFTER wire attach and fusion (both change what
    the staging emitters and tails look like); ``active`` gates the
    driver's K-granular source ticking and the durability epoch
    rounding."""

    def __init__(self, k: int) -> None:
        self.k = k
        self.edges = []

    @property
    def active(self) -> bool:
        return self.k > 1 and bool(self.edges)

    def summary(self) -> dict:
        return {"k": self.k,
                "edges": [e.summary() for e in self.edges]}


def attach_plane(config, source_replicas) -> MegastepPlane:
    """Walk the built graph's source replicas and hook a MegastepEdge
    onto every eligible staging emitter.  Conservative by design:
    anything the edge cannot prove safe stays on the per-batch path
    (auto mode silently; forced K>1 graphs get the WF608 preflight
    warning)."""
    plane = MegastepPlane(resolve_megastep(config))
    if plane.k <= 1:
        return plane
    from windflow_tpu.parallel.emitters import DeviceStageEmitter
    for rep in source_replicas:
        em = rep.emitter
        # exact type: keyed/aligned-mesh staging emitters partition or
        # shard per batch — their inner emitters are NOT single-edge
        if type(em) is not DeviceStageEmitter \
                or getattr(em, "_megastep", None) is not None:
            continue
        if em._stage_target is not None or len(em.dests) != 1:
            continue
        tail, _ch = em.dests[0]
        top = tail.op
        # exactly ONE feeding channel: a merged tail folds watermarks
        # across channels in collector arrival order, which a bypassing
        # drain cannot reproduce
        if tail.num_channels != 1 or top.parallelism != 1:
            continue
        kind, _why = tail_kind(top)
        if kind is None:
            continue
        if tail.emitter is None \
                or not hasattr(tail.emitter, "emit_device_batch"):
            continue
        edge = MegastepEdge(plane.k, top, tail, em, kind)
        em._megastep = edge
        plane.edges.append(edge)
    return plane


def round_epoch_to_megastep(config, plane: MegastepPlane) -> Optional[int]:
    """Align the durability epoch cadence to megastep boundaries.

    ``Config.durability_epoch_sweeps`` counts DRIVER sweeps, and under
    an active plane one driver sweep paces K logical batch sweeps
    (PipeGraph._tick_chunk) — left alone, a configured cadence would
    checkpoint K× less data-frequently than the same graph at K=1.  So
    the configured value is read as LOGICAL sweeps, rounded UP to a
    whole number of megasteps, and stored back as driver sweeps
    (``ceil(eps / K)``): every epoch then covers the same stream extent
    it covered per-batch (within one megastep of rounding), and every
    commit's quiesce lands between megasteps — the driver's
    ``on_sweep`` site sits between driver sweeps, which are whole
    megasteps.  Returns the new stored cadence when it changed, else
    None.  Idempotent: re-applying to an already-converted value at
    the same K only shrinks toward 1 and stabilizes there."""
    if not plane.active:
        return None
    eps = getattr(config, "durability_epoch_sweeps", 0) or 0
    if eps <= 0:
        return None
    driver = max(1, (eps + plane.k - 1) // plane.k)
    if driver == eps:
        return None
    config.durability_epoch_sweeps = driver
    return driver
