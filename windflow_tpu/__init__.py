"""windflow_tpu — a TPU-native data stream processing framework.

A ground-up re-design of the capabilities of WindFlow (reference mounted at
``/root/reference``; see SURVEY.md): dataflow graphs of streaming operators —
Source, Map, Filter, FlatMap, Reduce, Sink, keyed/parallel/paned/map-reduce
sliding and tumbling windows, FlatFAT incremental aggregation — with
event-time watermarks, punctuations, and DEFAULT / DETERMINISTIC /
PROBABILISTIC execution modes.  Device operators (MapTPU, FilterTPU,
ReduceTPU, FfatWindowsTPU) execute as XLA programs on TPU; keyed work shards
across chips over ICI via ``jax.sharding`` (``windflow_tpu.parallel``).

Umbrella module, equivalent of the reference's ``windflow.hpp`` /
``windflow_gpu.hpp`` include pair.
"""

import jax as _jax

# Stream timestamps are microseconds since the epoch: they need int64 lanes on
# device (the reference uses uint64 throughout).  Payload dtypes are always
# explicit, so this does not change compute precision anywhere hot.
_jax.config.update("jax_enable_x64", True)

from windflow_tpu.basic import (Config, EMPTY_KEY, ExecutionMode, RoutingMode,
                                TimePolicy, WindFlowError, WinType,
                                current_time_usecs, default_config)
from windflow_tpu.batch import (DeviceBatch, HostBatch, Punctuation,
                                device_to_host, host_to_device)
from windflow_tpu.context import LocalStorage, RuntimeContext
from windflow_tpu.graph.builders import (Ffat_Windows_Builder,
                                         DeviceSource_Builder,
                                         Ffat_WindowsTPU_Builder,
                                         Filter_Builder, FilterTPU_Builder,
                                         FlatMap_Builder,
                                         Keyed_Windows_Builder, Map_Builder,
                                         MapReduce_Windows_Builder,
                                         MapTPU_Builder,
                                         Paned_Windows_Builder,
                                         Parallel_Windows_Builder,
                                         Reduce_Builder, ReduceTPU_Builder,
                                         Sink_Builder, Source_Builder)
from windflow_tpu.graph.multipipe import MultiPipe
from windflow_tpu.graph.pipegraph import PipeGraph
from windflow_tpu.ops.base import Operator, Replica
from windflow_tpu.ops.filter_op import Filter
from windflow_tpu.ops.flatmap_op import FlatMap, Shipper
from windflow_tpu.ops.map_op import Map
from windflow_tpu.ops.reduce_op import Reduce
from windflow_tpu.ops.sink import Sink, SinkColumns
from windflow_tpu.ops.source import Source
from windflow_tpu.ops.tpu import FilterTPU, MapTPU, ReduceTPU
from windflow_tpu.ops.tpu_stateful import StatefulFilterTPU, StatefulMapTPU
from windflow_tpu.windows.engine import WindowSpec
from windflow_tpu.windows.ffat_op import FfatWindows
from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
from windflow_tpu.windows.flatfat import FlatFAT
from windflow_tpu.windows.ops import (KeyedWindows, MapReduceWindows,
                                      PanedWindows, ParallelWindows,
                                      WindowResult)
from windflow_tpu.persistent import (DBHandle, LogKV, PFilter, PFlatMap,
                                     PKeyedWindows, PMap, PReduce, PSink,
                                     P_Filter_Builder, P_FlatMap_Builder,
                                     P_Keyed_Windows_Builder, P_Map_Builder,
                                     P_Reduce_Builder, P_Sink_Builder)
from windflow_tpu import staging
from windflow_tpu.staging import StagingPool
from windflow_tpu.analysis import (ConcurrencyViolation, Diagnostic,
                                   hot_path)
from windflow_tpu.analysis.diagnostics import (PreflightError,
                                               PreflightWarning)
from windflow_tpu.durability import EpochFileSink

__version__ = "0.3.0"  # keep in sync with pyproject.toml

__all__ = [
    "Config", "EMPTY_KEY", "ExecutionMode", "RoutingMode", "TimePolicy",
    "WinType", "WindFlowError", "current_time_usecs", "default_config",
    "DeviceBatch", "HostBatch", "Punctuation", "device_to_host",
    "host_to_device", "LocalStorage", "RuntimeContext", "MultiPipe",
    "PipeGraph", "Operator", "Replica", "Source", "Map", "Filter", "FlatMap",
    "Shipper", "Reduce", "Sink", "SinkColumns", "MapTPU", "FilterTPU", "ReduceTPU",
    "StatefulMapTPU", "StatefulFilterTPU",
    "Source_Builder", "DeviceSource_Builder", "Map_Builder",
    "Filter_Builder", "FlatMap_Builder",
    "Reduce_Builder", "Sink_Builder", "MapTPU_Builder", "FilterTPU_Builder",
    "ReduceTPU_Builder",
    "WindowSpec", "WindowResult", "KeyedWindows", "ParallelWindows",
    "PanedWindows", "MapReduceWindows", "FfatWindows", "FfatWindowsTPU",
    "FlatFAT", "Keyed_Windows_Builder", "Parallel_Windows_Builder",
    "Paned_Windows_Builder", "MapReduce_Windows_Builder",
    "Ffat_Windows_Builder", "Ffat_WindowsTPU_Builder",
    "DBHandle", "LogKV", "PMap", "PFilter", "PFlatMap", "PReduce", "PSink",
    "PKeyedWindows", "P_Map_Builder", "P_Filter_Builder",
    "P_FlatMap_Builder", "P_Reduce_Builder", "P_Sink_Builder",
    "P_Keyed_Windows_Builder",
    "staging", "StagingPool",
    "ConcurrencyViolation", "Diagnostic", "PreflightError",
    "PreflightWarning", "hot_path",
    "EpochFileSink",
]
