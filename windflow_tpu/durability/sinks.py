"""Exactly-once file sinks: stage per epoch, atomic rename on commit.

:class:`EpochFileSink` is a plain Sink callable (pass it to
``Sink_Builder``) that makes a file-backed sink restart-safe:

* every record appends to a **staging** file under
  ``<dir>/.staging/`` — a crash mid-epoch leaves only staging garbage;
* at epoch commit (the durability plane calls :meth:`commit_epoch` at
  the checkpoint barrier, after the graph quiesced) the staging file is
  fsynced and ``os.replace``'d to ``<dir>/epoch_<e>.jsonl`` — atomic on
  POSIX, and idempotent: a replayed commit of the same epoch simply
  overwrites the file with the replay's (boundary-adjusted) content, so
  the concatenation of committed epochs is always the exact record
  sequence, no loss, no duplicates;
* at restore (:meth:`on_restore`) staging leftovers are discarded —
  committed epochs are the only truth.

Records are serialized one JSON object per line by default
(``serialize``/``deserialize`` override for other formats).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional


class EpochFileSink:
    """See module docstring.  Single logical writer per directory: give
    each sink replica its own directory (the replica index rides the
    runtime context) when running the sink replicated."""

    def __init__(self, dir: str,
                 serialize: Optional[Callable[[Any], str]] = None) -> None:
        self.dir = dir
        self._staging_dir = os.path.join(dir, ".staging")
        os.makedirs(self._staging_dir, exist_ok=True)
        self._ser = serialize or (lambda item: json.dumps(
            item, sort_keys=True, default=str))
        self._epoch = 0          # epoch currently staging
        self._f = None
        self.records_staged = 0
        self.epochs_committed = 0
        # a COLD restart after a crash (no restore — e.g. nothing was
        # checkpointed yet) constructs a fresh sink over the same dir:
        # the dead run's staged-but-uncommitted records must not leak
        # into this run's first epoch (staging appends).  on_restore()
        # covers the PipeGraph.restore() path; this covers cold starts.
        try:
            os.unlink(self._staging_path())
        except FileNotFoundError:
            pass

    # -- Sink callable contract ---------------------------------------------
    def __call__(self, item, ctx=None) -> None:
        if item is None:         # EOS: commit whatever is staged
            self.commit_epoch(self._epoch)
            return
        if self._f is None:
            self._f = open(self._staging_path(), "ab")
        self._f.write(self._ser(item).encode() + b"\n")
        self.records_staged += 1

    def _staging_path(self) -> str:
        return os.path.join(self._staging_dir, "open.jsonl")

    def _epoch_path(self, epoch: int) -> str:
        return os.path.join(self.dir, f"epoch_{epoch:06d}.jsonl")

    # -- durability-plane hooks ----------------------------------------------
    def commit_epoch(self, epoch: int) -> None:
        """Atomically publish the staged records as epoch ``epoch``."""
        if self._f is None:
            self._epoch = epoch + 1
            return               # empty epoch: publish nothing
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        os.replace(self._staging_path(), self._epoch_path(epoch))
        self._epoch = epoch + 1
        self.epochs_committed += 1

    def on_restore(self, epoch: int) -> None:
        """Discard staging leftovers from the crashed run; replay
        re-stages everything past checkpoint ``epoch``."""
        if self._f is not None:
            self._f.close()
            self._f = None
        try:
            os.unlink(self._staging_path())
        except FileNotFoundError:
            pass
        self._epoch = epoch + 1

    # -- read-back (chaos diff / consumers) ----------------------------------
    @staticmethod
    def read_committed(dir: str,
                       deserialize: Optional[Callable[[str], Any]] = None
                       ) -> List[Any]:
        """All committed records in epoch order — staging files are
        never read (they are the not-yet-happened half of the story)."""
        de = deserialize or json.loads
        out: List[Any] = []
        try:
            names = sorted(n for n in os.listdir(dir)
                           if n.startswith("epoch_")
                           and n.endswith(".jsonl"))
        except FileNotFoundError:
            return out
        for name in names:
            with open(os.path.join(dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        out.append(de(line))
        return out
