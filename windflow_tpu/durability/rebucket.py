"""Re-bucketing of checkpointed keyed state for shape-changing restores.

``PipeGraph.restore()`` onto a *different* shard shape — a keyed
operator's parallelism changed, or the graph moved to a different mesh
(N±1 chips, single-chip ↔ mesh) — is the production ops story: chip
failure, rolling upgrade, capacity change under live traffic.  The
epoch protocol makes it cheap: every checkpoint snapshot is taken at a
quiesced aligned barrier with the state pulled to host numpy, so a
rescale is pure host-side array surgery between ``load_checkpoint`` and
``restore_state`` — re-bucket each keyed row/entry to the shard the NEW
placement assigns it, then let the operator re-place the result on the
new mesh.

Placement mirrors the routing plane exactly (the state must land where
the keys will):

* host ``KeyByEmitter`` edges (host Reduce): ``stable_hash(key) % n``;
* keyed staging / device keyby edges (FFAT, stateful):
  ``splitmix64(k32) % n``;
* compacted key spaces (parallel/compaction.py): ``slot % n`` — the
  remap table itself rides the operator blob, so slots survive the
  restore and hot keys stay balanced on the new shard count;
* executor placement overrides (windflow_tpu/serving): moves applied by
  a live reshard are recorded in the checkpoint and re-applied before
  the hash, exactly as the advisor's ``move_keys`` contract routes.

What cannot re-bucket raises :class:`RescaleError` (surfaced as WF605):
state of an unknown kind, a key space that does not divide the new mesh
key axis, or TB pane rings whose per-shard clocks disagree at the
barrier (each shard's ring base/window frontier is shard-local state; a
merge across disagreeing clocks would re-fire or skip windows — restore
once on the checkpointed shape to reconcile, then rescale).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from windflow_tpu.basic import WindFlowError, int32_key, stable_hash

#: the TB scalar-clock lanes (mesh: one per key shard; single chip /
#: per-replica states: shape ()) — mirror of parallel/mesh._TB_SCALARS,
#: duplicated so this module never imports jax at module scope
TB_SCALARS = ("base", "win_next", "max_seen", "n_late", "n_evicted",
              "n_win_dropped")
#: TB clock lanes that must AGREE across merged shards (the ring
#: alignment invariants); the remaining scalars merge (max / sum)
TB_ALIGNED = ("base", "win_next")


class RescaleError(WindFlowError):
    """A shape-changing restore that cannot re-bucket (WF605)."""

    def __init__(self, op_name: str, why: str) -> None:
        super().__init__(
            f"WF605 restore: operator '{op_name}' cannot re-bucket its "
            f"checkpointed state onto the new shard shape — {why}")


def mesh_shape(mesh) -> Optional[dict]:
    """JSON-able shape record the manifest pins for a mesh graph."""
    if mesh is None:
        return None
    from windflow_tpu.parallel.mesh import DATA_AXIS, KEY_AXIS
    return {"devices": int(np.prod(list(mesh.devices.shape))),
            "data": int(mesh.shape[DATA_AXIS]),
            "key": int(mesh.shape[KEY_AXIS])}


def _owner_fn(kind: str, n: int, override: Optional[dict]):
    """Shard owner of a key/row under one placement — bit-identical to
    the emitter the edge routes through (parallel/emitters.py).  The
    override map must be keyed in the SAME domain the owner is asked
    about (user keys for hash placements, ring rows for ``slot_mod`` —
    see ``_slot_override``)."""
    from windflow_tpu.parallel.emitters import splitmix64_int
    ov = override or {}

    def owner(key) -> int:
        d = ov.get(key)
        if isinstance(d, int) and 0 <= d < n:
            return d
        if kind == "slot_mod":
            return int(key) % n
        if kind == "stable_hash":
            return stable_hash(key) % n
        return splitmix64_int(int32_key(key)) % n

    return owner


def _slot_override(blob: dict, override: Optional[dict]
                   ) -> Optional[dict]:
    """Translate an executor key→shard override (USER keys — the domain
    the emitters route by) into the ROW/slot domain a compacted ring's
    state is indexed by, through the compactor's checkpointed key→slot
    map.  Without this, an overridden hot key's tuples would route to
    one shard while its pane rows re-bucket to ``slot % n`` on
    another."""
    if not override:
        return None
    key_slot = (blob.get("compactor") or {}).get("key_slot") or {}
    ks = {int32_key(k): int(v) for k, v in key_slot.items()}
    out = {}
    for k, dst in override.items():
        slot = ks.get(int32_key(k))
        if slot is not None:
            out[slot] = dst
    return out or None


# ---------------------------------------------------------------------------
# per-kind re-bucketing
# ---------------------------------------------------------------------------

def _rebucket_reduce_host(op, blob, new_p: int,
                          override: Optional[dict]) -> dict:
    """Host Reduce per-replica per-key dicts: merge, re-split by the
    host keyby placement (``stable_hash(key) % n`` with overrides
    first) — each key's rolling state lands on the replica its tuples
    will now reach."""
    merged = {}
    for d in blob.get("replicas") or []:
        merged.update(d)
    owner = _owner_fn("stable_hash", new_p, override)
    reps = [dict() for _ in range(new_p)]
    for k, v in merged.items():
        reps[owner(k)][k] = v
    return {"kind": "reduce_host", "replicas": reps}


def _tb_scalar(v) -> np.ndarray:
    """Normalize a TB clock scalar to a 1-D lane array (single-chip
    checkpoints carry shape ())."""
    a = np.asarray(v)
    return a.reshape(1) if a.ndim == 0 else a


def _check_aligned(op, states: dict, names=TB_ALIGNED) -> dict:
    """All contributing TB states/lanes must agree on the ring
    alignment scalars; returns the agreed value per name."""
    agreed = {}
    for name in names:
        vals = set()
        for st in states.values():
            for x in _tb_scalar(st[name]).tolist():
                vals.add(int(x))
        if len(vals) > 1:
            raise RescaleError(
                op.name,
                f"TB pane-ring clocks disagree across shards at the "
                f"checkpoint barrier ({name} in {sorted(vals)}); "
                "restore once on the checkpointed shape to reconcile "
                "the rings, then rescale")
        agreed[name] = vals.pop() if vals else 0
    return agreed


def _tree_map(fn, tree):
    import jax
    return jax.tree.map(fn, tree)


def _rebucket_ffat(op, blob, old_p: int, new_p: int,
                   old_kk: int, new_kk: int,
                   override: Optional[dict]) -> dict:
    """FFAT pane rings.  CB state is purely per-key (one shared table,
    per-key clock lanes) — shape-independent; only the mesh key-axis
    divisibility needs a check.  TB state carries ring clocks: one
    scalar lane per mesh key shard, or one full state per replica when
    keyed at parallelism > 1 — both re-bucket only when the clocks
    agree at the barrier (see :class:`RescaleError`)."""
    K = int(op.max_keys)
    if new_kk > 1 and K % new_kk:
        raise RescaleError(
            op.name, f"max_keys {K} not divisible by the new mesh key "
                     f"axis {new_kk}")
    states: Dict[int, dict] = blob["states"]
    is_tb = bool(getattr(op, "is_tb", False))
    kind = "slot_mod" if blob.get("compactor") is not None else "splitmix"
    old_per_rep = is_tb and op.key_extractor is not None and old_p > 1
    new_per_rep = is_tb and op.key_extractor is not None and new_p > 1

    if not old_per_rep and not new_per_rep:
        if not is_tb or old_kk == new_kk or not states:
            return blob     # per-key state only: nothing shard-local
        # TB scalar lanes re-shaped old_kk -> new_kk (1 == single chip)
        st = dict(states[0])
        agreed = _check_aligned(op, {0: st})
        lanes = max(1, new_kk)

        def lane(name, fill):
            a = np.full((lanes,), fill,
                        _tb_scalar(st[name]).dtype)
            return a if new_kk > 1 else a.reshape(())

        for name in TB_ALIGNED:
            st[name] = lane(name, agreed[name])
        st["max_seen"] = lane("max_seen",
                              int(_tb_scalar(st["max_seen"]).max()))
        for name in ("n_late", "n_evicted", "n_win_dropped"):
            total = int(_tb_scalar(st[name]).sum())
            a = np.zeros((lanes,), _tb_scalar(st[name]).dtype)
            a[0] = total
            st[name] = a if new_kk > 1 else a.reshape(())
        out = dict(blob)
        out["states"] = {0: st}
        return out

    # keyed TB across replica counts: gather each key row from its old
    # owner state into its new owner state; ring clocks must agree
    live = {s: st for s, st in states.items() if st}
    if not live:
        return blob
    agreed = _check_aligned(op, live)
    max_seen = max(int(_tb_scalar(st["max_seen"]).max())
                   for st in live.values())
    counters = {name: sum(int(_tb_scalar(st[name]).sum())
                          for st in live.values())
                for name in ("n_late", "n_evicted", "n_win_dropped")}
    if kind == "slot_mod":
        # compacted rings index rows by SLOT; executor overrides are
        # keyed by USER key — translate through the checkpointed remap
        override = _slot_override(blob, override)
    owner_old = _owner_fn(kind, max(1, old_p), override if old_per_rep
                          else None)
    owner_new = _owner_fn(kind, max(1, new_p), override)
    o_old = np.array([owner_old(r) for r in range(K)])
    o_new = np.array([owner_new(r) for r in range(K)])
    template = next(iter(live.values()))
    n_new_states = new_p if new_per_rep else 1

    def build(j: int) -> dict:
        out = {}
        rows_j = o_new == j if new_per_rep else np.ones(K, bool)
        for name, val in template.items():
            if name in TB_SCALARS:
                if name in TB_ALIGNED:
                    out[name] = np.asarray(agreed[name],
                                           _tb_scalar(val).dtype)
                elif name == "max_seen":
                    out[name] = np.asarray(max_seen,
                                           _tb_scalar(val).dtype)
                else:
                    out[name] = np.asarray(counters[name] if j == 0
                                           else 0,
                                           _tb_scalar(val).dtype)
                out[name] = out[name].reshape(())
                continue
            # per-key leaves (cells/cell_valid/horizon): axis 0 is K —
            # map over the pytree so nested aggregate structures work
            out[name] = _tree_map(
                lambda leaf, _n=name: _gather_rows(live, o_old, rows_j,
                                                   _n, leaf, template),
                val)
        return out

    new_states = {j: build(j) for j in range(n_new_states)}
    out = dict(blob)
    out["states"] = new_states
    return out


def _gather_rows(live, o_old, rows_j, name, leaf, template):
    """One per-key leaf gathered row-wise from the old owner states.
    ``leaf`` is the template's leaf; matching leaves in every old state
    share its position in the pytree, found by flattened index."""
    import jax
    t_leaves, treedef = jax.tree_util.tree_flatten(template[name])
    idx = next(i for i, l in enumerate(t_leaves) if l is leaf)
    acc = np.zeros_like(np.asarray(leaf))
    for s, st in live.items():
        m = rows_j & (o_old == s)
        if m.any():
            src = jax.tree_util.tree_flatten(st[name])[0][idx]
            acc[m] = np.asarray(src)[m]
    return acc


def _rebucket_stateful(op, blob, new_kk: int) -> dict:
    """Dense/interned stateful tables are ONE shared table across
    replicas (per-key arrival order comes from keyed routing, not state
    ownership) — shape-independent; only mesh divisibility can block."""
    S = int(getattr(op, "num_key_slots", 0) or 0)
    if new_kk > 1 and S and S % new_kk:
        raise RescaleError(
            op.name, f"num_key_slots {S} not divisible by the new mesh "
                     f"key axis {new_kk}")
    return blob


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def rebucket_blob(op, blob: dict, old_p: int, new_p: int,
                  old_mesh: Optional[dict], new_mesh: Optional[dict],
                  override: Optional[dict] = None) -> dict:
    """Re-bucket one operator's checkpoint blob from the shape it was
    written under (``old_p`` replicas on ``old_mesh``) onto the shape
    the restoring graph builds (``new_p`` / ``new_mesh``).  Blobs whose
    state is shape-independent pass through unchanged; unknown kinds
    under a genuine shape change raise :class:`RescaleError`."""
    old_kk = (old_mesh or {}).get("key", 1) or 1
    new_kk = (new_mesh or {}).get("key", 1) or 1
    unchanged = old_p == new_p and old_kk == new_kk \
        and (old_mesh is None) == (new_mesh is None)
    if unchanged:
        return blob
    kind = blob.get("kind") if isinstance(blob, dict) else None
    if kind == "reduce_host":
        return _rebucket_reduce_host(op, blob, new_p, override)
    if kind == "ffat_tpu":
        return _rebucket_ffat(op, blob, old_p, new_p, old_kk, new_kk,
                              override)
    if kind == "stateful_tpu":
        return _rebucket_stateful(op, blob, new_kk)
    if kind == "reduce_tpu":
        return blob     # drop counters + remap: shard-shape independent
    raise RescaleError(
        op.name,
        f"state of kind {kind!r} has no re-bucketing rule (the operator "
        "declares neither a dense key space nor a compaction remap)")
