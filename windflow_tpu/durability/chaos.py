"""Failure-injection harness: seeded kills, restore, record-for-record
A/B diff (the test family ROADMAP item 5 names; driven by
``tools/wf_chaos.py`` and ``tests/test_durability.py``).

The experiment, per (graph family, kill point, fusion on/off) cell:

1. **Baseline** — run the factory's graph uninterrupted (durability ON,
   same epoch cadence) and read the sunk output.
2. **Chaos** — run an identical graph (own broker/output/checkpoint
   store), kill it at the seeded point, ``PipeGraph.restore()`` a fresh
   instance from the last complete epoch, drive it to completion, read
   the sunk output.
3. **Verdict** — the two outputs must match record for record: no loss,
   no duplicates, no reordering within a partition.

Kill points:

* ``mid_epoch`` — raise :class:`ChaosKill` on the N-th driver sweep
  (between checkpoints: operator state is mid-stream, sinks hold
  uncommitted buffered output).
* ``mid_window`` — raise after the N-th batch processed by a named
  operator (a window/stateful replica dies with panes half-filled).
* ``mid_sink_flush`` — raise inside checkpoint K, between the sink
  epoch commit and the manifest write: the torn two-phase window where
  output is published but the epoch never committed — exactly the case
  the sink fence dedupes.

Kills are simulated in-process (the exception rides the driver loop's
crash path, postmortem and all); the broker, checkpoint store, and sink
files survive as the "external world" a real restart would see.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from windflow_tpu.basic import WindFlowError

KILL_POINTS = ("mid_epoch", "mid_window", "mid_sink_flush")


class ChaosKill(RuntimeError):
    """The injected failure.  RuntimeError so the driver's crash path
    (salvage telemetry, postmortem, finalize) treats it like any crash."""


@dataclasses.dataclass
class KillSpec:
    """One seeded kill.  ``after`` counts events at the kill point
    (sweeps, batches, or checkpoints); ``op_name`` names the victim
    operator for ``mid_window``."""

    point: str
    after: int = 3
    op_name: Optional[str] = None

    def __post_init__(self) -> None:
        if self.point not in KILL_POINTS:
            raise WindFlowError(
                f"unknown kill point '{self.point}' (one of {KILL_POINTS})")
        if self.point == "mid_window" and not self.op_name:
            raise WindFlowError("mid_window kills need op_name")


def arm(graph, spec: KillSpec) -> None:
    """Install the kill on a STARTED graph (replicas and the durability
    plane exist).  Test-only instrumentation: nothing here touches the
    production hot path — the plane hooks run at checkpoint cadence and
    the mid_window wrapper exists only on armed graphs."""
    plane = graph._durability
    if plane is None:
        raise WindFlowError("chaos needs Config.durability enabled")
    count = {"n": 0}
    if spec.point == "mid_epoch":
        def hook(site):
            if site == "sweep":
                count["n"] += 1
                if count["n"] == spec.after:
                    raise ChaosKill(f"mid_epoch kill at sweep {count['n']}")
        plane.chaos_hook = hook
    elif spec.point == "mid_sink_flush":
        def hook(site):
            if site == "post_sink_commit":
                count["n"] += 1
                if count["n"] == spec.after:
                    raise ChaosKill(
                        f"mid_sink_flush kill: checkpoint {count['n']} "
                        "died after the sink commit, before the manifest")
        plane.chaos_hook = hook
    else:  # mid_window
        victims = [op for op in graph._operators
                   if op.name == spec.op_name]
        if not victims:
            raise WindFlowError(
                f"mid_window kill: no operator named '{spec.op_name}'")
        for op in victims:
            for rep in op.replicas:
                _wrap_replica(rep, count, spec.after)


def _wrap_replica(rep, count: dict, after: int) -> None:
    orig_dev = rep.process_device_batch
    orig_single = rep.process_single

    def _maybe_kill():
        count["n"] += 1
        if count["n"] == after:
            raise ChaosKill(
                f"mid_window kill: replica {rep.op.name}[{rep.index}] "
                f"died processing batch {count['n']}")

    def dev(batch):
        _maybe_kill()
        return orig_dev(batch)

    def single(item, ts, wm):
        _maybe_kill()
        return orig_single(item, ts, wm)

    rep.process_device_batch = dev
    rep.process_single = single


def abandon(graph) -> None:
    """Post-kill teardown of the dead graph's external handles: Kafka
    consumers leave their group (a real crash gets this from the broker
    session timeout; in-process ghosts would keep partitions assigned
    and starve the restored run), producers close.  The checkpoint
    store was already flushed+closed by the crash path's finalize."""
    for sr in graph._source_replicas:
        c = getattr(sr, "_consumer", None)
        if c is not None:
            try:
                c.close()
            except Exception:  # lint: broad-except-ok (abandon runs in
                # test teardown after a simulated crash; a half-dead
                # client must not mask the experiment's verdict)
                pass
    for op in graph._operators:
        if op.is_terminal:
            for rep in op.replicas:
                p = getattr(rep, "_producer", None)
                if p is not None:
                    try:
                        p.close()
                    except Exception:  # lint: broad-except-ok (same
                        # teardown stance as the consumer close above)
                        pass


def run_killed_and_restored(factory: Callable[[], object],
                            spec: KillSpec,
                            restore_factory: Optional[Callable] = None):
    """Start the factory's graph, arm the kill, drive to the crash,
    restore a fresh instance from the checkpoint store, and drive it to
    completion.  Returns the completed (restored) graph.  Raises if the
    kill never fired — a chaos cell that does not kill proves nothing.

    ``restore_factory`` (kill-a-shard / restore-on-N±1 cells) builds
    the RESTORED graph on a different shard shape — keyed parallelism
    or mesh — exercising the rescale-on-restore re-bucketing
    (durability/rebucket.py) under the same record-for-record
    contract."""
    g = factory()
    g.start()
    arm(g, spec)
    killed = False
    try:
        g.wait_end()
    except ChaosKill:
        killed = True
        abandon(g)
    if not killed:
        raise WindFlowError(
            f"chaos kill {spec} never fired — the run completed; "
            "lower `after` or feed more data")
    g2 = (restore_factory or factory)()
    g2.restore(g2.config.durability)
    g2.wait_end()
    return g2


def run_baseline(factory: Callable[[], object]):
    """The uninterrupted control run (same durability config)."""
    g = factory()
    g.run()
    return g


# ---------------------------------------------------------------------------
# output readers / diff
# ---------------------------------------------------------------------------

def read_topic(broker, topic: str) -> List[list]:
    """Committed values per partition, in offset order — the unit of
    Kafka's ordering guarantee, so the A/B diff compares per-partition
    sequences, never a cross-partition interleaving."""
    with broker._lock:
        parts = broker._topics.get(topic, [])
        return [[m.value for m in p.log] for p in parts]


def diff_records(baseline, chaos) -> Optional[str]:
    """None when the two outputs match record for record; otherwise the
    first divergence, rendered for a test failure message."""
    if baseline == chaos:
        return None
    if isinstance(baseline, list) and isinstance(chaos, list) \
            and len(baseline) == len(chaos):
        for i, (a, b) in enumerate(zip(baseline, chaos)):
            if a != b:
                if isinstance(a, list) and isinstance(b, list):
                    return _diff_seq(f"partition {i}", a, b)
                return f"record {i}: baseline={a!r} chaos={b!r}"
    if isinstance(baseline, list) and isinstance(chaos, list):
        return _diff_seq("output", baseline, chaos)
    return f"outputs differ: baseline={baseline!r} chaos={chaos!r}"


def _diff_seq(what: str, a: list, b: list) -> str:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return (f"{what}: first divergence at index {i}: "
                    f"baseline={a[i]!r} chaos={b[i]!r} "
                    f"(lengths {len(a)} vs {len(b)})")
    if len(a) != len(b):
        kind = "loss" if len(b) < len(a) else "duplication"
        extra = (a if len(a) > len(b) else b)[n:n + 3]
        return (f"{what}: {kind} — baseline has {len(a)} records, chaos "
                f"{len(b)}; first extra/missing: {extra!r}")
    return f"{what}: sequences differ"


# ---------------------------------------------------------------------------
# standard graph families (tools/wf_chaos.py + tests/test_durability.py)
# ---------------------------------------------------------------------------

FAMILIES = ("window_cb", "window_tb", "reduce", "stateful",
            "stateless_chain", "window_compact")

#: seeded determinism-VIOLATING families — cells that break the
#: docs/DURABILITY.md replay contract ON PURPOSE, so the static and
#: dynamic layers can be cross-validated: wfverify flags the graph
#: before any batch runs (WF61x, analysis/tracecheck.py), and the chaos
#: A/B diff fails dynamically on the same graph (the kernel bakes a
#: wall-clock read at trace time, so the restored run's re-trace
#: diverges from the committed prefix).  Expected-fail-dynamic,
#: caught-static: NOT part of the exactly-once soak matrix above.
DETERMINISM_FAMILIES = ("wallclock",)

#: per-family mid_window kill counts that land after the first
#: checkpoint and before completion at the default cell size (device
#: replicas count batches; the host reduce counts records)
MID_WINDOW_AFTER = {"window_cb": 12, "window_tb": 12, "stateful": 12,
                    "stateless_chain": 12, "reduce": 3000,
                    "wallclock": 12, "window_compact": 12}

#: the operator a mid_window kill targets, per family
VICTIM = {"window_cb": "w", "window_tb": "w", "stateful": "st",
          "stateless_chain": "f", "reduce": "red", "wallclock": "m",
          "window_compact": "w"}


def make_cell(family: str, ckpt_dir: str, *, fusion: bool = True,
              out_dir: Optional[str] = None, n: int = 4096,
              keys: int = 8, app: str = "chaos",
              epoch_sweeps: int = 3, parallelism: int = 1,
              mesh=None) -> dict:
    """One isolated chaos cell: its own in-memory broker pre-filled with
    a deterministic event-time stream, a graph factory (re-invocable:
    the chaos path builds the graph twice; it also accepts
    ``parallelism=``/``mesh=`` overrides so a rescale cell can restore
    the same cell on a different shard shape), and an output reader.
    Returns ``{"factory", "read", "broker"}``.

    Determinism contract (docs/DURABILITY.md): EVENT-time records,
    interval punctuation pushed out of reach, sweep-counted epoch
    cadence — so the baseline run, the killed run, and the replay all
    stage identical batches in identical order, which is what makes the
    record-for-record diff (and the sink fence's seq-dedupe) exact."""
    import dataclasses as _dc

    import windflow_tpu as wf
    from windflow_tpu.kafka.client import InMemoryBroker
    from windflow_tpu.kafka.kafka_sink import KafkaSink, KafkaSinkMessage
    from windflow_tpu.kafka.kafka_source import KafkaSource
    if family not in FAMILIES + DETERMINISM_FAMILIES:
        raise WindFlowError(
            f"unknown chaos family '{family}' "
            f"(one of {FAMILIES + DETERMINISM_FAMILIES})")
    broker = InMemoryBroker()
    broker.create_topic("in", 1)
    p = broker.producer()
    for i in range(n):
        p.produce("in", {"key": i % keys, "value": float(i % 97)},
                  timestamp_usec=1_000 + i * 7)
    p.produce("in", "EOS", timestamp_usec=1_000 + n * 7)

    def deser(msg, shipper):
        if msg is None:
            return True
        if msg.value == "EOS":
            return False
        # float32 value lane (exact here: the stream holds small
        # integers, and every family's arithmetic stays < 2^24) so the
        # staged records pack — the chaos A/B therefore exercises the
        # WIRE-COMPRESSED staging path end to end (windflow_tpu/wire.py;
        # a float64 lane would silently fall back to per-lane transfers
        # and prove nothing about the decode)
        import numpy as _np
        r = dict(msg.value)
        r["value"] = _np.float32(r["value"])
        shipper.pushWithTimestamp(r, msg.timestamp_usec)
        return True

    file_sink = None
    if family == "stateless_chain":
        if out_dir is None:
            raise WindFlowError("stateless_chain needs out_dir")
        from windflow_tpu.durability.sinks import EpochFileSink
        file_sink = EpochFileSink(out_dir)

    def factory(parallelism: int = parallelism, mesh=mesh):
        cfg = _dc.replace(wf.default_config)
        cfg.durability = ckpt_dir
        cfg.durability_epoch_sweeps = epoch_sweeps
        cfg.whole_chain_fusion = fusion
        cfg.mesh = mesh
        # determinism: interval punctuation reads the wall clock, which
        # would move batch boundaries between runs
        cfg.punctuation_interval_usec = 10 ** 12
        cfg.health_postmortem_on_crash = False
        src = KafkaSource(deser, broker, ["in"], group_id="chaos",
                          name="ksrc", output_batch_size=256)
        # declared record spec: lets the wire plane compress this edge
        # (WF606 contract) — and the A/B diff then pins the decode
        import numpy as _np
        src.record_spec = {"key": _np.int64(0), "value": _np.float32(0.0)}
        g = wf.PipeGraph(app, config=cfg)
        pipe = g.add_source(src)
        ser = (lambda r: KafkaSinkMessage(
            "out", tuple(sorted((k, round(float(v), 6))
                                for k, v in r.items()))))
        if family in ("window_cb", "window_tb"):
            pipe.add(wf.MapTPU_Builder(
                lambda t: {"key": t["key"], "value": t["value"] * 2.0})
                .withName("m").build())
            wb = wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                            lambda a, b: a + b)
            wb = (wb.withCBWindows(16, 8) if family == "window_cb"
                  else wb.withTBWindows(70, 35))
            pipe.add(wb.withKeyBy(lambda t: t["key"])
                     .withParallelism(parallelism)
                     .withMaxKeys(keys).withName("w").build())
            pipe.add_sink(KafkaSink(ser, broker, name="ksnk"))
        elif family == "window_compact":
            # compacted key space (parallel/compaction.py): the FFAT's
            # pane rings index by REMAP slots, so this cell proves the
            # remap table restores exactly — a replay under a different
            # key→slot assignment would read the restored ring rows as
            # the wrong keys and the record diff catches it.  Keys are
            # deliberately arbitrary (sparse int32, not [0, keys)); the
            # window is HOST-FED (keyed staging edge) so every key
            # admits at the boundary — the compacted FFAT contract.
            pipe.add(wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                                lambda a, b: a + b)
                     .withCBWindows(16, 8)
                     .withKeyBy(lambda t: t["key"] * 131 + 7)
                     .withCompactedKeys().withName("w").build())
            pipe.add_sink(KafkaSink(ser, broker, name="ksnk"))
        elif family == "stateful":
            pipe.add(wf.MapTPU_Builder(
                lambda t: {"key": t["key"], "value": t["value"] + 1.0})
                .withName("m").build())

            def st_fn(t, s):
                ns = {"n": s["n"] + 1, "s": s["s"] + t["value"]}
                return ({"key": t["key"], "value": t["value"],
                         "n": ns["n"], "s": ns["s"]}, ns)

            pipe.add(wf.MapTPU_Builder(st_fn)
                     .withInitialState({"n": 0, "s": 0.0})
                     .withKeyBy(lambda t: t["key"])
                     .withParallelism(parallelism)
                     .withNumKeySlots(keys).withDenseKeys()
                     .withName("st").build())
            pipe.add_sink(KafkaSink(ser, broker, name="ksnk"))
        elif family == "reduce":
            def red_fn(item, state):
                state["key"] = item["key"]
                state["n"] = state.get("n", 0) + 1
                state["s"] = round(state.get("s", 0.0) + item["value"], 6)

            pipe.add(wf.Reduce_Builder(red_fn, dict)
                     .withKeyBy(lambda t: t["key"])
                     .withParallelism(parallelism)
                     .withName("red").build())
            pipe.add_sink(KafkaSink(ser, broker, name="ksnk"))
        elif family == "wallclock":
            # DELIBERATE determinism violation (DETERMINISM_FAMILIES):
            # the kernel bakes a wall-clock read into the traced program
            # as a constant — wfverify flags it statically (WF612) and
            # the A/B diff fails dynamically because the restored run
            # re-traces with a different clock.  No suppression: being
            # flagged is this family's purpose.
            import time as _time
            pipe.add(wf.MapTPU_Builder(
                lambda t: {"key": t["key"],
                           "value": t["value"] + (_time.time() % 3600.0)})
                .withName("m").build())
            pipe.add_sink(KafkaSink(ser, broker, name="ksnk"))
        else:  # stateless_chain -> exactly-once epoch file sink
            pipe.add(wf.MapTPU_Builder(
                lambda t: {"key": t["key"], "value": t["value"] * 3.0})
                .withName("m").build())
            pipe.add(wf.FilterTPU_Builder(lambda t: (t["key"] & 1) == 0)
                     .withName("f").build())
            pipe.add_sink(wf.Sink_Builder(file_sink).withName("fsink")
                          .build())
        return g

    if family == "stateless_chain":
        from windflow_tpu.durability.sinks import EpochFileSink as _EFS

        def read():
            return _EFS.read_committed(out_dir)
    else:
        def read():
            return read_topic(broker, "out")

    return {"factory": factory, "read": read, "broker": broker}


def default_kill(family: str, point: str) -> KillSpec:
    """The seeded kill each (family, point) cell uses by default."""
    if point == "mid_window":
        return KillSpec(point, after=MID_WINDOW_AFTER[family],
                        op_name=VICTIM[family])
    if point == "mid_sink_flush":
        return KillSpec(point, after=2)
    return KillSpec(point, after=6)


# ---------------------------------------------------------------------------
# kill-a-shard / restore-on-N±1 (rescale) cells
# ---------------------------------------------------------------------------

#: families whose keyed operator rescales across REPLICA shard counts
#: (kill at parallelism P, restore at P±1); stateless_chain has no
#: keyed operator and window_compact's remap already rides the blob
RESCALE_FAMILIES = ("reduce", "stateful", "window_cb", "window_tb")

#: families that rescale across MESH shapes (kill on kk key shards,
#: restore on a different mesh) — the multi-chip N±1 story
MESH_RESCALE_FAMILIES = ("window_cb", "window_tb")


def record_key(rec):
    """The routing key of one sunk record (the cells' serializer ships
    sorted (field, value) pair tuples)."""
    try:
        return dict(rec).get("key")
    except (TypeError, ValueError):
        return None


def keyed_sequences(parts: List[list]) -> dict:
    """Per-key record sequences in offset order.  Under keyed routing
    the per-KEY subsequence is the unit of the ordering guarantee — a
    shard-count change legitimately re-interleaves keys against each
    other (different shard drain order), exactly as Kafka guarantees
    order per partition, not across partitions."""
    out: dict = {}
    for p in parts:
        for rec in p:
            out.setdefault(record_key(rec), []).append(rec)
    return out


def diff_keyed_records(baseline, chaos) -> Optional[str]:
    """None when every key's record sequence matches exactly; otherwise
    the first per-key divergence.  The rescale form of
    :func:`diff_records`: loss, duplication, or per-key reorder all
    surface — only the cross-key interleaving (which the shard count
    legitimately changes) is factored out."""
    a, b = keyed_sequences(baseline), keyed_sequences(chaos)
    for k in sorted(set(a) | set(b), key=repr):
        if k not in a:
            return f"key {k!r}: {len(b[k])} record(s) only in chaos run"
        if k not in b:
            return f"key {k!r}: {len(a[k])} record(s) only in baseline"
        if a[k] != b[k]:
            return _diff_seq(f"key {k!r}", a[k], b[k])
    return None


def run_rescale_ab(family: str, point: str, workdir: str, *,
                   shards_kill: int, shards_restore: int,
                   mesh_kill=None, mesh_restore=None,
                   n: int = 4096, fusion: bool = True) -> dict:
    """One kill-a-shard / restore-on-N±1 cell: baseline runs
    uninterrupted on the KILL shape; the chaos twin is killed on the
    kill shape and restored on the RESTORE shape (different keyed
    parallelism and/or mesh).  The diff is per-key record-for-record —
    docs/DURABILITY.md "rescale-on-restore"."""
    import os as _os
    tag = (f"rescale_{family}_{point}_{shards_kill}to{shards_restore}"
           f"_{'on' if fusion else 'off'}")
    base = make_cell(family, _os.path.join(workdir, tag, "ckpt_a"),
                     fusion=fusion, n=n, parallelism=shards_kill,
                     mesh=mesh_kill,
                     out_dir=_os.path.join(workdir, tag, "out_a"))
    chal = make_cell(family, _os.path.join(workdir, tag, "ckpt_b"),
                     fusion=fusion, n=n, parallelism=shards_kill,
                     mesh=mesh_kill,
                     out_dir=_os.path.join(workdir, tag, "out_b"))
    spec = default_kill(family, point)
    if point == "mid_window" and shards_kill > 1 and family != "reduce":
        # device families count BATCHES, shared across replicas: P
        # keyed partitions stage ~P× as many (smaller) batches by the
        # same stream position, so scale the kill to land after the
        # first checkpoint, as the single-shard default does.  The host
        # reduce counts RECORDS — position-invariant, no scaling.
        spec = KillSpec(point, after=spec.after * shards_kill,
                        op_name=spec.op_name)
    gb = run_baseline(base["factory"])
    gc = run_killed_and_restored(
        chal["factory"], spec,
        restore_factory=lambda: chal["factory"](
            parallelism=shards_restore, mesh=mesh_restore))
    base_out, chaos_out = base["read"](), chal["read"]()
    dur = gc.stats()["Durability"]
    return {
        "family": family, "point": point, "rescale": True,
        "shards": f"{shards_kill}->{shards_restore}",
        "mesh": None if mesh_kill is None else
                f"{_mesh_tag(mesh_kill)}->{_mesh_tag(mesh_restore)}",
        "fusion": fusion,
        "diff": diff_keyed_records(base_out, chaos_out),
        "records": sum(len(p) for p in base_out)
        if base_out and isinstance(base_out[0], list) else len(base_out),
        "restored_epoch": dur.get("restored_epoch"),
        "restore_ms": dur.get("restore_ms"),
        "epochs_committed_baseline":
            gb.stats()["Durability"].get("epochs_committed"),
        "dedupe_hits": dur.get("dedupe_hits"),
    }


def _mesh_tag(mesh) -> str:
    if mesh is None:
        return "none"
    from windflow_tpu.durability.rebucket import mesh_shape
    s = mesh_shape(mesh)
    return f"{s['data']}x{s['key']}"


def run_ab(factory_baseline: Callable[[], object],
           factory_chaos: Callable[[], object],
           spec: KillSpec,
           read_baseline: Callable[[], object],
           read_chaos: Callable[[], object]) -> dict:
    """One chaos cell end to end.  The two factories must build
    IDENTICAL graphs over identical input but isolated externals (own
    broker/topic/checkpoint dir/output dir — and distinct consumer
    groups if they do share a broker).  Returns the verdict dict
    ``tools/wf_chaos.py`` renders; ``diff`` is None on exactly-once."""
    gb = run_baseline(factory_baseline)
    gc = run_killed_and_restored(factory_chaos, spec)
    base_out, chaos_out = read_baseline(), read_chaos()
    dur = gc.stats()["Durability"]
    return {
        "kill": dataclasses.asdict(spec),
        "diff": diff_records(base_out, chaos_out),
        "records": sum(len(p) for p in base_out)
        if base_out and isinstance(base_out[0], list) else len(base_out),
        "restored_epoch": dur.get("restored_epoch"),
        "epochs_committed_baseline":
            gb.stats()["Durability"].get("epochs_committed"),
        "dedupe_hits": dur.get("dedupe_hits"),
    }
