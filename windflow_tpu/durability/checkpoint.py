"""Watermark-aligned checkpoint/restore: the DurabilityPlane.

Protocol (the whole correctness argument lives in these five steps, in
this order — docs/DURABILITY.md walks the failure cases):

1. **Barrier (quiesce).**  The driver stops ticking sources, flushes
   every live emitter's open batch, and drains replicas until the graph
   is idle.  Because the host driver is one cooperative loop, this is a
   *perfectly aligned* snapshot point: no record is simultaneously
   "in flight" and "in state" — the distributed-barrier machinery of
   Chandy-Lamport degenerates to a drain.  The epoch id needs no
   in-band marker riding the batch lanes; the barrier IS the alignment
   (the trace lane precedent from PR 2 carries the epoch implicitly:
   every batch staged before the barrier belongs to the epoch).
2. **Sink epoch commit.**  Exactly-once sinks publish the epoch's
   buffered output atomically: the Kafka sink commits through the
   broker-side fence (dedupe on the replica's lifetime sequence number
   — ``kafka/client.py fenced_commit``), file sinks rename their staged
   epoch file into place.  Commit comes BEFORE the manifest: a crash
   between 2 and 4 re-commits the epoch on replay and the fence /
   idempotent rename dedupes it.
3. **State snapshot.**  Every operator's ``snapshot_state()`` blob plus
   per-replica watermark/offset bookkeeping is written into the LogKV
   under epoch-versioned keys.  Device arrays are pulled to host numpy
   (the only device sync durability ever pays, at checkpoint cadence).
4. **Manifest commit.**  One ``ep/<e>/manifest`` record (topology
   signature + counters) is appended LAST, then the log is fsynced.
   The LogKV's open-time torn-tail truncation makes this the atomic
   commit point: an epoch exists iff its manifest survived.
5. **GC.**  Epochs older than ``Config.durability_keep`` are
   tombstoned; LogKV auto-compaction reclaims the space.

``restore_graph`` (surfaced as ``PipeGraph.restore()``) inverts it:
find the last complete epoch, validate the manifest's topology
signature against the composed graph (WF602 named diff on mismatch),
stash the blobs, ``start()`` the graph, apply operator/replica state
after ``_build`` and before the first source tick, and seek Kafka
consumers back to the checkpointed offsets.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Optional

from windflow_tpu.basic import WindFlowError, current_time_usecs

CHECKPOINT_SCHEMA = "wf-checkpoint/1"

#: safety valve on the quiesce drain: a graph that cannot drain within
#: this many flush+drain rounds is wedged (each round moves data at
#: least one hop; real graphs quiesce in a handful)
_MAX_QUIESCE_ROUNDS = 100_000


def topology_signature(ops) -> list:
    """Stable per-operator signature the manifest pins and restore
    validates (WF602): enough to prove the restored graph rebuilds the
    same state layout, not so much that a cosmetic change breaks it."""
    sig = []
    for op in ops:
        sig.append({
            "name": op.name,
            "type": type(op).__name__,
            "parallelism": op.parallelism,
            "routing": op.routing.value,
            "is_tpu": bool(op.is_tpu),
            "record_spec": _spec_str(getattr(op, "record_spec", None)),
        })
    return sig


def _spec_str(spec) -> Optional[str]:
    if spec is None:
        return None
    try:
        from windflow_tpu.analysis.preflight import _as_struct
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(_as_struct(spec))
        return str(treedef) + "|" + ";".join(
            f"{tuple(l.shape)}/{l.dtype}" for l in leaves)
    except Exception:  # lint: broad-except-ok (an unspecced/exotic
        # record declaration must not block checkpointing — the
        # signature simply omits it and topology still validates)
        return None


def quiesce(graph) -> None:
    """Drain a started graph to the aligned barrier: flush open emitter
    batches, then drain replicas until nothing moves.  Runs on the
    driver thread between sweeps, so no pool drain can race it.  Shared
    by the checkpoint protocol (step 1) and the reshard executor
    (windflow_tpu/serving) — a live reshard IS "quiesce, re-place the
    key→shard map, resume", the same barrier with no manifest."""
    for _ in range(_MAX_QUIESCE_ROUNDS):
        for rep in graph._all_replicas:
            if rep.emitter is not None and not rep.done:
                rep.emitter.flush(rep.current_wm)
        progressed = False
        for rep in graph._all_replicas:
            if rep.drain(0):
                progressed = True
        if not progressed:
            if any(rep.inbox for rep in graph._all_replicas):
                raise WindFlowError(
                    "durability barrier could not quiesce the graph: "
                    "a replica holds pending input but no replica "
                    "makes progress")
            return
    raise WindFlowError(
        "durability barrier exceeded the quiesce round bound — "
        "the graph keeps generating work without source ticks")


def keyed_emitters_into(graph, op):
    """Every override-capable keyed emitter feeding ``op``'s replicas
    (host KeyByEmitter and the keyed staging emitter; device keyby
    splits route in-program and are not override targets — documented
    executor limit).  Shared by the reshard executor (installing
    overrides) and the checkpoint plane (recording them)."""
    from windflow_tpu.parallel.emitters import (DeviceToHostEmitter,
                                                KeyByEmitter,
                                                KeyedDeviceStageEmitter,
                                                SplittingEmitter)
    dest_ids = {id(r) for r in op.replicas}
    out = []

    def visit(em):
        if em is None:
            return
        if isinstance(em, DeviceToHostEmitter):
            visit(em.inner)
            return
        if isinstance(em, SplittingEmitter):
            for b in em.branches:
                visit(b)
            return
        if isinstance(em, (KeyByEmitter, KeyedDeviceStageEmitter)) \
                and any(id(r) in dest_ids for r, _ in em.dests):
            out.append(em)

    for rep in graph._all_replicas:
        visit(rep.emitter)
    return out


def collect_overrides(graph) -> dict:
    """Per-operator merged key→shard override maps currently installed
    on the keyed emitters (reshard-executor moves) — the placement half
    of the manifest, so a restore (including a rescale) routes AND
    re-buckets through the same map the checkpointed run routed by."""
    out = {}
    for op in graph._operators:
        merged = {}
        for em in keyed_emitters_into(graph, op):
            ov = getattr(em, "_override", None)
            if ov:
                merged.update(ov)
        if merged:
            out[op.ordinal] = merged
    return out


def install_overrides(graph, overrides: dict) -> None:
    """Re-install recorded key→shard overrides onto a freshly built
    graph's keyed emitters, dropping moves that target shards beyond
    the new shard count (the rescale may have shrunk it)."""
    for op in graph._operators:
        ov = overrides.get(op.ordinal)
        if not ov:
            continue
        n = op.parallelism
        kept = {k: d for k, d in ov.items()
                if isinstance(d, int) and 0 <= d < n}
        if not kept:
            continue
        for em in keyed_emitters_into(graph, op):
            em.set_override(dict(kept))


class DurabilityPlane:
    """Per-graph checkpoint coordinator (built by ``PipeGraph._build``
    when ``Config.durability`` names a directory; ``None`` otherwise —
    the sweep loop's whole off-cost is that one check)."""

    def __init__(self, graph) -> None:
        from windflow_tpu.persistent.kv import LogKV
        cfg = graph.config
        self.graph = graph
        self.dir = cfg.durability
        os.makedirs(self.dir, exist_ok=True)
        self.kv = LogKV(os.path.join(self.dir, "checkpoint.kv"))
        self._closed = False
        #: next epoch id to commit (continues past the restored epoch)
        self.epoch = 0
        self._sweeps = 0
        # counters surfaced via stats()["Durability"] / wf_durability_*
        self.epochs_committed = 0
        self.last_checkpoint_ms = None
        self.checkpoint_ms_total = 0.0
        self.last_checkpoint_bytes = 0
        self.checkpoint_bytes_total = 0
        self.restored_epoch = None
        self.restore_ms = None
        self.sink_commits = 0
        #: failure-injection hook (durability/chaos.py): called with a
        #: site name at checkpoint milestones; raising aborts the graph
        #: there.  None in production — checkpoint-cadence checks only.
        self.chaos_hook = None
        self._bind_sinks()

    def _bind_sinks(self) -> None:
        """Switch Kafka sink replicas to buffered exactly-once mode: the
        fence id scopes dedupe to (app, operator, replica) — two graphs
        sharing a broker must run under distinct app names or their
        fences would dedupe each other's output.  Epoch-file-style sink
        functions (one shared object carrying commit_epoch) are rejected
        at parallelism > 1: every replica would share the same staging
        file handle, and pooled replicas racing its open/append would
        tear or lose records the commit then publishes."""
        from windflow_tpu.kafka.kafka_sink import KafkaSinkReplica
        for op in self.graph._operators:
            if not op.is_terminal:
                continue
            if op.parallelism > 1 and getattr(
                    getattr(op, "fn", None), "commit_epoch", None):
                raise WindFlowError(
                    f"sink '{op.name}': an epoch-committing sink "
                    "function (EpochFileSink) is one shared object and "
                    "supports parallelism == 1 — build one Sink per "
                    "partition, each with its own sink directory")
            for rep in op.replicas:
                if isinstance(rep, KafkaSinkReplica):
                    rep._durable = True
                    rep._fence_id = (f"{self.graph.name}/"
                                     f"{op.name}/{rep.index}")

    # -- keys ----------------------------------------------------------------
    @staticmethod
    def _k_manifest(epoch: int) -> bytes:
        return b"ep/%d/manifest" % epoch

    @staticmethod
    def _k_op(epoch: int, ordinal: int) -> bytes:
        return b"ep/%d/op/%d" % (epoch, ordinal)

    @staticmethod
    def _k_reps(epoch: int) -> bytes:
        return b"ep/%d/reps" % epoch

    @staticmethod
    def _k_placements(epoch: int) -> bytes:
        return b"ep/%d/placements" % epoch

    # -- sweep hook ----------------------------------------------------------
    def on_sweep(self) -> None:
        """Called once per driver sweep (PipeGraph.step).  Counts toward
        the epoch cadence; everything expensive lives in checkpoint()."""
        self._chaos("sweep")
        self._sweeps += 1
        every = max(1, self.graph.config.durability_epoch_sweeps)
        if self._sweeps % every == 0 and not self.graph.is_done():
            self.checkpoint()

    def _chaos(self, site: str) -> None:
        if self.chaos_hook is not None:
            self.chaos_hook(site)

    # -- checkpoint ----------------------------------------------------------
    def checkpoint(self) -> int:
        """Run the full epoch protocol (module docstring steps 1-5).
        Returns the committed epoch id."""
        t0 = time.perf_counter()
        epoch = self.epoch
        self._chaos("pre_barrier")
        self._quiesce()
        self._chaos("post_quiesce")
        self._commit_sinks(epoch)
        self._chaos("post_sink_commit")
        nbytes = self._write_snapshots(epoch)
        from windflow_tpu.durability.rebucket import mesh_shape
        manifest = {
            "schema": CHECKPOINT_SCHEMA,
            "app": self.graph.name,
            "epoch": epoch,
            "written_at_usec": current_time_usecs(),
            "topology": topology_signature(self.graph._operators),
            # rescale-on-restore (durability/rebucket.py): the shard
            # shape this epoch's keyed state was bucketed under — a
            # restore onto a different shape re-buckets through it
            "mesh": mesh_shape(self.graph.config.mesh),
            # keyed placement summary (which operators carry live
            # key→shard overrides; the override maps themselves ride
            # the pickled placements record — native key types)
            "placements": {str(ordinal): len(ov) for ordinal, ov
                           in collect_overrides(self.graph).items()},
        }
        self.kv.put(self._k_manifest(epoch), json.dumps(manifest).encode())
        self.kv.flush()          # the commit point: manifest + fsync
        self._chaos("post_manifest")
        self.epoch = epoch + 1
        self.epochs_committed += 1
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        self.last_checkpoint_ms = ms
        self.checkpoint_ms_total += ms
        self.last_checkpoint_bytes = nbytes
        self.checkpoint_bytes_total += nbytes
        self._gc(epoch)
        return epoch

    def _quiesce(self) -> None:
        quiesce(self.graph)

    def _sink_commit_hooks(self):
        """(replica, hook) pairs for every terminal replica exposing an
        epoch commit: durability-aware Kafka sink replicas, and plain
        Sink functions wrapping an EpochFileSink-style object."""
        out = []
        for op in self.graph._operators:
            if not op.is_terminal:
                continue
            for rep in op.replicas:
                hook = getattr(rep, "commit_epoch", None)
                if hook is None:
                    hook = getattr(getattr(op, "fn", None),
                                   "commit_epoch", None)
                if hook is not None:
                    out.append((rep, hook))
        return out

    def _commit_sinks(self, epoch: int) -> None:
        for _, hook in self._sink_commit_hooks():
            hook(epoch)
            self.sink_commits += 1

    def _write_snapshots(self, epoch: int) -> int:
        g = self.graph
        nbytes = 0
        for op in g._operators:
            blob = op.snapshot_state()
            if blob is None:
                continue
            try:
                raw = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
            except Exception as e:  # lint: broad-except-ok (re-raised
                # with the operator named: pickling arbitrary user state
                # fails in many exception types — TypeError,
                # PicklingError, RecursionError — and a raw one out of
                # step() points at this module, not at whose state (a
                # lambda, a generator, an open handle) broke it)
                raise WindFlowError(
                    f"checkpoint epoch {epoch}: state of operator "
                    f"'{op.name}' ({type(op).__name__}) is not "
                    f"picklable ({type(e).__name__}: {e}) — keep "
                    "checkpointed per-key state to plain "
                    "data (numbers, strings, dicts, numpy)") from e
            self.kv.put(self._k_op(epoch, op.ordinal), raw)
            nbytes += len(raw)
        raw = pickle.dumps(self._replica_records(),
                           protocol=pickle.HIGHEST_PROTOCOL)
        self.kv.put(self._k_reps(epoch), raw)
        nbytes += len(raw)
        # live key→shard placement overrides (reshard executor moves):
        # restore re-installs them so a rescale re-buckets keyed state
        # through the SAME placement the keys will route by
        overrides = collect_overrides(self.graph)
        if overrides:
            raw = pickle.dumps(overrides,
                               protocol=pickle.HIGHEST_PROTOCOL)
            self.kv.put(self._k_placements(epoch), raw)
            nbytes += len(raw)
        return nbytes

    def _replica_records(self) -> list:
        """Per-replica host bookkeeping: watermark frontiers, source
        timestamp/origin-id sequencing, Kafka consumer offsets, sink
        fence sequence numbers."""
        from windflow_tpu.ops.source import BaseSourceReplica
        out = []
        for op in self.graph._operators:
            for rep in op.replicas:
                d = {"ordinal": op.ordinal, "index": rep.index,
                     "wm": rep.current_wm, "hooked_wm": rep._hooked_wm}
                if isinstance(rep, BaseSourceReplica):
                    d["last_ts"] = rep._last_ts
                    d["tid_seq"] = rep._tid_seq
                    d["since_punct"] = rep._since_punct
                d.update(self._kafka_record(rep))
                out.append(d)
        return out

    @staticmethod
    def _kafka_record(rep) -> dict:
        from windflow_tpu.kafka.kafka_sink import KafkaSinkReplica
        from windflow_tpu.kafka.kafka_source import KafkaSourceReplica
        if isinstance(rep, KafkaSourceReplica):
            pos = None
            if rep._consumer is not None:
                pos = rep._consumer.positions()
            return {"kafka_positions": pos,
                    "part_max": dict(rep._part_max)}
        if isinstance(rep, KafkaSinkReplica):
            return {"sink_seq": rep._seq, "sink_epoch": rep._epoch}
        return {}

    def _gc(self, committed: int) -> None:
        keep = max(1, self.graph.config.durability_keep)
        drop_before = committed - keep + 1
        if drop_before <= 0:
            return
        for key in self.kv.keys():
            try:
                if not key.startswith(b"ep/"):
                    continue
                ep = int(key.split(b"/", 2)[1])
            except (ValueError, IndexError):
                continue
            if ep < drop_before:
                self.kv.delete(key)

    # -- restore (the plane side; entry point is restore_graph below) --------
    def apply_restore(self, pending: dict) -> None:
        """Apply stashed checkpoint state to a just-built graph — called
        by ``PipeGraph.start()`` after ``_build()`` (replicas and fusion
        preludes exist) and before the first source tick.  On a rescale
        (the manifest's shard shape differs from the graph's) every
        keyed blob is re-bucketed first (durability/rebucket.py) and the
        recorded key→shard overrides are re-installed, so state lands
        exactly where the new placement will route its keys."""
        t0 = time.perf_counter()
        g = self.graph
        epoch = pending["epoch"]
        from windflow_tpu.durability.rebucket import (mesh_shape,
                                                      rebucket_blob)
        old_mesh = pending["manifest"].get("mesh")
        new_mesh = mesh_shape(g.config.mesh)
        topo = pending["manifest"].get("topology") or []
        placements = pending.get("placements") or {}
        rescaled = pending.get("rescaled", False)
        if placements:
            install_overrides(g, placements)
        for ordinal, blob in pending["ops"].items():
            op = g._operators[ordinal]
            old_p = topo[ordinal]["parallelism"] \
                if ordinal < len(topo) else op.parallelism
            blob = rebucket_blob(op, blob, old_p, op.parallelism,
                                 old_mesh, new_mesh,
                                 override=placements.get(ordinal))
            op.restore_state(blob)
        by_key = {(r["ordinal"], r["index"]): r for r in pending["reps"]}
        merged = self._merged_records(pending["reps"])
        from windflow_tpu.ops.source import BaseSourceReplica
        for op in g._operators:
            for rep in op.replicas:
                r = by_key.get((op.ordinal, rep.index))
                if r is None:
                    # rescale grew this operator: the new replica has no
                    # per-replica record — seed from the op's merged
                    # record (min watermark = conservative frontier; the
                    # replay advances it with the first real batches)
                    r = merged.get(op.ordinal)
                    if r is None:
                        continue
                rep.current_wm = r["wm"]
                rep._hooked_wm = r["hooked_wm"]
                if isinstance(rep, BaseSourceReplica):
                    rep._last_ts = r["last_ts"]
                    rep._tid_seq = r["tid_seq"]
                    rep._since_punct = r["since_punct"]
                self._apply_kafka(rep, r)
        for _, hook in self._sink_restore_hooks():
            hook(epoch)
        if rescaled:
            self._check_fences_reconciled(epoch)
        self.epoch = epoch + 1
        self.restored_epoch = epoch
        self.restore_ms = round((time.perf_counter() - t0) * 1e3
                                + pending.get("load_ms", 0.0), 3)

    @staticmethod
    def _merged_records(reps: list) -> dict:
        """Per-ordinal fold of the replica records, for replicas a
        rescale added: minimum watermark (never fires a window the old
        shards had not), maximum source sequencing."""
        out = {}
        for r in reps:
            m = out.get(r["ordinal"])
            if m is None:
                m = out[r["ordinal"]] = dict(r)
                # group-level Kafka state reseeds through the op-level
                # stash (_apply_kafka) from EVERY old record already;
                # the merged record must not re-apply one replica's
                del m["index"]
                continue
            m["wm"] = min(m["wm"], r["wm"])
            m["hooked_wm"] = min(m["hooked_wm"], r["hooked_wm"])
            for k in ("last_ts", "tid_seq", "since_punct"):
                if k in r and k in m:
                    m[k] = max(m[k], r[k])
        return out

    def _check_fences_reconciled(self, restored_epoch: int) -> None:
        """Rescale fence guard (the shard-count-changing exactly-once
        hole): the broker fence dedupes on the replica-LIFETIME message
        sequence, which stays exact across a replay only while the
        replayed record ORDER matches the committed one — true on the
        checkpointed shard shape, not across a rescale (a different
        shard count re-interleaves the replay).  If a sink fence sits
        AHEAD of the restored manifest (the mid-sink-flush torn window:
        epoch committed broker-side, manifest lost), a rescaled replay
        would dedupe by position against records it regenerates in a
        different order — refuse, and name the fix."""
        from windflow_tpu.kafka.kafka_sink import KafkaSinkReplica
        for op in self.graph._operators:
            if not op.is_terminal:
                continue
            for rep in op.replicas:
                if not isinstance(rep, KafkaSinkReplica) \
                        or not rep._durable:
                    continue
                fence_fn = getattr(
                    getattr(rep._producer, "_broker", None), "fence",
                    None)
                if fence_fn is None:
                    continue
                f = fence_fn(rep._fence_id)
                if f is not None and f[0] > restored_epoch:
                    raise WindFlowError(
                        f"WF605 restore: sink '{op.name}' replica "
                        f"{rep.index} committed epoch {f[0]} through its "
                        f"fence but the last complete manifest is epoch "
                        f"{restored_epoch} (a crash in the torn "
                        "two-phase window) — a shard-shape-changing "
                        "replay re-interleaves records and the fence's "
                        "sequence dedupe would drop the wrong ones. "
                        "Restore once on the checkpointed shape to "
                        "reconcile the torn epoch, checkpoint, then "
                        "rescale")

    @staticmethod
    def _apply_kafka(rep, r: dict) -> None:
        from windflow_tpu.kafka.kafka_sink import KafkaSinkReplica
        from windflow_tpu.kafka.kafka_source import KafkaSourceReplica
        if isinstance(rep, KafkaSourceReplica):
            # per-partition event-time frontiers are GROUP-level like the
            # positions below: the post-restart rebalance may hand a
            # partition to a different replica index, so every replica
            # seeds from the merged map and its first poll prunes to its
            # own assignment (the revoked-partition cleanup in tick())
            if r.get("part_max"):
                cur = getattr(rep.op, "_restore_part_max", None) or {}
                cur.update(r["part_max"])
                rep.op._restore_part_max = cur
            # consumer positions are applied at rep.start() (the consumer
            # does not exist yet): stash them on the operator, merged
            # over replicas — positions are group-level state
            if r.get("kafka_positions"):
                cur = getattr(rep.op, "_restore_positions", None) or {}
                cur.update(r["kafka_positions"])
                rep.op._restore_positions = cur
        elif isinstance(rep, KafkaSinkReplica):
            rep._seq = r.get("sink_seq", 0)
            rep._epoch = r.get("sink_epoch", 0)

    def _sink_restore_hooks(self):
        out = []
        for op in self.graph._operators:
            if not op.is_terminal:
                continue
            for rep in op.replicas:
                hook = getattr(rep, "on_restore", None)
                if hook is None:
                    hook = getattr(getattr(op, "fn", None),
                                   "on_restore", None)
                if hook is not None:
                    out.append((rep, hook))
        return out

    # -- read surface --------------------------------------------------------
    def section(self) -> dict:
        """stats()["Durability"] / OpenMetrics / postmortem payload."""
        dedupe = 0
        for op in self.graph._operators:
            for rep in op.replicas:
                dedupe += getattr(rep, "_dedupe_hits", 0)
        return {
            "enabled": True,
            "dir": self.dir,
            "epoch": self.epoch,
            "epochs_committed": self.epochs_committed,
            "last_checkpoint_ms": self.last_checkpoint_ms,
            "checkpoint_ms_total": round(self.checkpoint_ms_total, 3),
            "last_checkpoint_bytes": self.last_checkpoint_bytes,
            "checkpoint_bytes_total": self.checkpoint_bytes_total,
            "restored_epoch": self.restored_epoch,
            "restore_ms": self.restore_ms,
            "sink_commits": self.sink_commits,
            "dedupe_hits": dedupe,
        }

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.kv.flush()
            self.kv.close()


# ---------------------------------------------------------------------------
# restore entry point (PipeGraph.restore delegates here)
# ---------------------------------------------------------------------------

def last_complete_epoch(kv) -> Optional[int]:
    """Largest epoch with a manifest in the store — the commit marker
    whose presence the torn-tail truncation guarantees is trustworthy."""
    best = None
    for key in kv.keys():
        if key.startswith(b"ep/") and key.endswith(b"/manifest"):
            try:
                ep = int(key.split(b"/", 2)[1])
            except (ValueError, IndexError):
                continue
            if best is None or ep > best:
                best = ep
    return best


def load_checkpoint(ckpt_dir: str) -> dict:
    """Read the last complete epoch's manifest + blobs from a checkpoint
    directory (opens and closes its own KV handle — the plane reopens
    the store when the restored graph builds)."""
    from windflow_tpu.persistent.kv import LogKV
    path = os.path.join(ckpt_dir, "checkpoint.kv")
    if not os.path.exists(path):
        raise WindFlowError(
            f"no checkpoint store at {path!r} — nothing to restore")
    t0 = time.perf_counter()
    kv = LogKV(path)
    try:
        epoch = last_complete_epoch(kv)
        if epoch is None:
            raise WindFlowError(
                f"checkpoint store {path!r} holds no complete epoch "
                "(no manifest survived) — nothing to restore")
        manifest = json.loads(kv.get(b"ep/%d/manifest" % epoch))
        if manifest.get("schema") != CHECKPOINT_SCHEMA:
            raise WindFlowError(
                f"unknown checkpoint schema {manifest.get('schema')!r} "
                f"(want {CHECKPOINT_SCHEMA!r})")
        ops = {}
        prefix = b"ep/%d/op/" % epoch
        for key in kv.keys():
            if key.startswith(prefix):
                ops[int(key[len(prefix):])] = pickle.loads(kv.get(key))
        reps = pickle.loads(kv.get(b"ep/%d/reps" % epoch))
        raw = kv.get(b"ep/%d/placements" % epoch)
        placements = pickle.loads(raw) if raw is not None else {}
    finally:
        kv.close()
    return {"epoch": epoch, "manifest": manifest, "ops": ops,
            "reps": reps, "placements": placements,
            "load_ms": round((time.perf_counter() - t0) * 1e3, 3)}


def restore_graph(graph, ckpt_dir: Optional[str] = None):
    """Rebuild a composed-but-unstarted PipeGraph at the last complete
    checkpoint epoch: validate the manifest's topology signature (WF602
    named diff on mismatch), stash the state blobs, start the graph, and
    let the plane apply them before the first source tick.  Kafka
    sources resume from the checkpointed per-partition offsets; sinks
    resume fenced, so replayed output dedupes.  Returns the graph,
    started — drive it with ``wait_end()`` / ``step()``."""
    if graph._started:
        raise WindFlowError("restore() must run on an unstarted graph")
    d = ckpt_dir or graph.config.durability
    if not d:
        raise WindFlowError(
            "restore() needs a checkpoint directory (argument or "
            "Config.durability)")
    if graph.config.durability != d:
        # the rebuilt plane must reopen THIS store — but PipeGraph holds
        # a passed Config by reference, so mutate a private copy: writing
        # through would silently enable durability (on OUR store, with
        # fence collisions) for every other graph sharing the Config
        import dataclasses
        graph.config = dataclasses.replace(graph.config, durability=d)
    pending = load_checkpoint(d)
    from windflow_tpu.analysis.preflight import manifest_rescale_plan
    diags, rescaled = manifest_rescale_plan(graph, pending["manifest"])
    if diags:
        lines = "\n  ".join(str(dg) for dg in diags)
        raise WindFlowError(
            f"restore: graph does not match checkpoint epoch "
            f"{pending['epoch']} of app "
            f"{pending['manifest'].get('app')!r}:\n  {lines}")
    pending["rescaled"] = rescaled
    graph._pending_restore = pending
    graph.start()
    return graph
