"""Durable state: watermark-aligned checkpoint/restore + exactly-once
sinks (docs/DURABILITY.md).

The reference persists keyed operator state through RocksDB-backed
builders (``/root/reference/wf/persistent/builders_rocksdb.hpp``); this
package closes the other half of that story for the TPU reproduction:
not just *persisting* state but **restoring a whole running graph** —
FFAT pane rings, stateful slot tables, reduce states, Kafka source
offsets, per-replica watermark frontiers — at the last complete epoch,
with sinks that neither lose nor duplicate a record across the restart.

* :mod:`windflow_tpu.durability.checkpoint` — the
  :class:`DurabilityPlane` (epoch barriers, LogKV-backed snapshot store,
  manifest commit protocol) and ``restore_graph`` behind
  ``PipeGraph.restore()``.
* :mod:`windflow_tpu.durability.sinks` — :class:`EpochFileSink`, the
  stage-then-atomic-rename exactly-once file sink.
* :mod:`windflow_tpu.durability.rebucket` — shape-changing restore:
  re-bucket keyed state blobs between shard shapes (keyed parallelism
  N±1, mesh N±1 chips, single-chip ↔ mesh) through the placement the
  keys route by (docs/DURABILITY.md "rescale-on-restore").
* :mod:`windflow_tpu.durability.chaos` — the failure-injection harness
  (seeded kills, restore — including kill-a-shard / restore-on-N±1
  rescale cells — record-for-record A/B diff) driven by
  ``tools/wf_chaos.py`` and ``tests/test_durability.py``.
"""

from windflow_tpu.durability.checkpoint import (CHECKPOINT_SCHEMA,
                                                DurabilityPlane,
                                                quiesce, restore_graph)
from windflow_tpu.durability.rebucket import RescaleError, rebucket_blob
from windflow_tpu.durability.sinks import EpochFileSink

__all__ = ["CHECKPOINT_SCHEMA", "DurabilityPlane", "restore_graph",
           "quiesce", "RescaleError", "rebucket_blob", "EpochFileSink"]
