"""Fluent builders for the persistent operator suite (reference
``/root/reference/wf/persistent/builders_rocksdb.hpp:59-1502``).

All support ``withDBPath``, ``withSharedDb``, ``withKeepDb``,
``withSerializer``/``withDeserializer`` (defaults: pickle) and
``withInitialState``; `P_Keyed_Windows_Builder` adds the window clauses plus
``withMaxInMemoryElements`` (the reference's volatile-fragment capacity,
``p_window_replica.hpp:93``).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Optional

from windflow_tpu.graph.builders import (_BuilderBase, _WindowBuilderBase,
                                         _detect_incremental)
from windflow_tpu.persistent.ops import (PFilter, PFlatMap, PMap, PReduce,
                                         PSink)
from windflow_tpu.persistent.p_windows import PKeyedWindows


def _default_db_path(name: str) -> str:
    # Reference default: DBs under a fixed scratch root unless the user
    # chooses a path (builders_rocksdb.hpp dbpath arguments).
    root = os.environ.get("WF_TPU_DB_DIR",
                          os.path.join(tempfile.gettempdir(), "windflow_db"))
    return os.path.join(root, name)


class _PersistentBuilderMixin:
    def __init__(self) -> None:
        self._db_path: Optional[str] = None
        self._initial_state: Any = None
        self._serialize = None
        self._deserialize = None
        self._shared_db = False
        self._keep_db = False

    def withDBPath(self, path: str):
        self._db_path = path
        return self

    # reference-spelled aliases (builders_rocksdb.hpp withDbPath /
    # withDeleteDb) so transliterated programs work unchanged
    def withDbPath(self, path: str):
        return self.withDBPath(path)

    def withDeleteDb(self, delete: bool = True):
        return self.withKeepDb(not delete)

    def withInitialState(self, state: Any):
        """Initial per-key state: a value (deep-copied per key) or a zero-arg
        factory."""
        self._initial_state = state
        return self

    def withSerializer(self, fn: Callable[[Any], bytes]):
        self._serialize = fn
        return self

    def withDeserializer(self, fn: Callable[[bytes], Any]):
        self._deserialize = fn
        return self

    def withSharedDb(self, shared: bool = True):
        self._shared_db = shared
        return self

    def withKeepDb(self, keep: bool = True):
        """Keep the DB on disk after the run (reference: !deleteDb)."""
        self._keep_db = keep
        return self

    def _db_kwargs(self, name: str) -> dict:
        return dict(db_path=self._db_path or _default_db_path(name),
                    serialize=self._serialize,
                    deserialize=self._deserialize,
                    shared_db=self._shared_db,
                    keep_db=self._keep_db)


class _PersistentOpBuilder(_PersistentBuilderMixin, _BuilderBase):
    _op_class = None

    def __init__(self, fn: Callable) -> None:
        _BuilderBase.__init__(self)
        _PersistentBuilderMixin.__init__(self)
        self._fn = fn

    def withRebalancing(self):
        from windflow_tpu.basic import WindFlowError
        raise WindFlowError(
            "persistent operators route by key (their state is keyed); "
            "REBALANCING does not apply")

    def build(self):
        return self._op_class(
            self._fn, name=self._name, parallelism=self._parallelism,
            key_extractor=self._key_extractor,
            initial_state=self._initial_state,
            output_batch_size=self._output_batch_size,
            **self._db_kwargs(self._name))


class P_Map_Builder(_PersistentOpBuilder):
    _default_name = "p_map"
    _op_class = PMap


class P_Filter_Builder(_PersistentOpBuilder):
    _default_name = "p_filter"
    _op_class = PFilter


class P_FlatMap_Builder(_PersistentOpBuilder):
    _default_name = "p_flatmap"
    _op_class = PFlatMap


class P_Reduce_Builder(_PersistentOpBuilder):
    _default_name = "p_reduce"
    _op_class = PReduce


class P_Sink_Builder(_PersistentOpBuilder):
    _default_name = "p_sink"
    _op_class = PSink

    def withOutputBatchSize(self, *_):
        from windflow_tpu.basic import WindFlowError
        raise WindFlowError("a Sink has no output to batch")

    def build(self):
        return PSink(
            self._fn, name=self._name, parallelism=self._parallelism,
            key_extractor=self._key_extractor,
            initial_state=self._initial_state,
            **self._db_kwargs(self._name))


class P_Keyed_Windows_Builder(_PersistentBuilderMixin, _WindowBuilderBase):
    _default_name = "p_keyed_windows"

    def __init__(self, fn: Callable) -> None:
        _WindowBuilderBase.__init__(self)
        _PersistentBuilderMixin.__init__(self)
        self._fn = fn
        self._n_max_elements = 1024

    def withMaxInMemoryElements(self, n: int):
        self._n_max_elements = int(n)
        return self

    def build(self) -> PKeyedWindows:
        return PKeyedWindows(
            self._fn, self._spec(), name=self._name,
            parallelism=self._parallelism, key_extractor=self._key_extractor,
            incremental=_detect_incremental(self._fn),
            n_max_elements=self._n_max_elements,
            output_batch_size=self._output_batch_size,
            **self._db_kwargs(self._name))
