"""Persistent basic operators: keyed state lives in the embedded KV store.

Re-design of the reference's RocksDB operator family (``/root/reference/wf/
persistent/p_filter.hpp:292``, ``p_map.hpp:272``, ``p_flatmap.hpp:256``,
``p_reduce.hpp:197``, ``p_sink.hpp:244``): every input triggers a
read-modify-write of its key's state (``p_map.hpp:178-211`` — get, apply the
user function with the state as an extra argument, put back).  User function
shapes mirror the in-memory operators with one extra ``state`` parameter:

* ``P_Map``:     ``fn(item, state[, ctx]) -> out | None`` (None = in-place)
* ``P_Filter``:  ``fn(item, state[, ctx]) -> bool``
* ``P_FlatMap``: ``fn(item, state, shipper[, ctx])``
* ``P_Reduce``:  ``fn(item, state[, ctx]) -> new_state | None`` (None =
  mutated in place); the updated state is emitted per input, as the
  in-memory Reduce does
* ``P_Sink``:    ``fn(item | None, state[, ctx])`` — ``None`` once at EOS
  with a fresh meaningless state (reference ``p_sink.hpp`` svc_end)

State durability follows the reference: the DB path outlives the run when
``keep_db=True`` (otherwise the store is deleted at operator termination,
``db_handle.hpp:108-112``); ``shared_db`` points every replica of the
operator at one store — safe because KEYBY routing partitions keys
disjointly across replicas.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional

from windflow_tpu.basic import EMPTY_KEY, RoutingMode, WindFlowError
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica
from windflow_tpu.ops.flatmap_op import Shipper
from windflow_tpu.persistent.db_handle import DBHandle


class _PersistentReplica(Replica):
    """Shared plumbing: DB handle per replica + key extraction."""

    _fn_arity = 2  # (item, state)

    def __init__(self, op: "_PersistentOperator", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, self._fn_arity)
        self.db = DBHandle(op.db_path,
                           serialize=op.serialize,
                           deserialize=op.deserialize,
                           initial_state=op.initial_state,
                           shared=op.shared_db,
                           whoami=index,
                           delete_db=not op.keep_db)

    def _key_of(self, item: Any) -> Any:
        return (self.op.key_extractor(item)
                if self.op.key_extractor is not None else EMPTY_KEY)

    def on_eos(self) -> None:
        self.db.close()


class _PersistentOperator(Operator):
    # persistent ops already own their LogKV durability, but epoch
    # alignment with the graph checkpoint is not implemented (WF603)
    checkpoint_opaque = True
    def __init__(self, fn: Callable, name: str, parallelism: int,
                 key_extractor: Optional[Callable],
                 db_path: str,
                 initial_state: Any = None,
                 serialize: Callable[[Any], bytes] = None,
                 deserialize: Callable[[bytes], Any] = None,
                 shared_db: bool = False,
                 keep_db: bool = False,
                 output_batch_size: int = 0,
                 terminal: bool = False) -> None:
        routing = RoutingMode.KEYBY if key_extractor is not None \
            else RoutingMode.FORWARD
        if key_extractor is None and parallelism > 1:
            raise WindFlowError(
                f"persistent operator '{name}' without a key extractor "
                "requires parallelism == 1 (keyed state cannot be "
                "replicated without KEYBY routing)")
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=0 if terminal
                         else output_batch_size,
                         key_extractor=key_extractor)
        self.fn = fn
        self.db_path = db_path
        self.initial_state = initial_state
        self.serialize = serialize
        self.deserialize = deserialize
        self.shared_db = shared_db
        # a shared DB handle serializes its replicas on the driver thread
        # (the host worker pool must not interleave writers in one LogKV)
        self.host_pool_safe = not shared_db
        self.keep_db = keep_db


class PMapReplica(_PersistentReplica):
    def process_single(self, item, ts, wm):
        key = self._key_of(item)
        state = self.db.get(key)
        out = self._fn(item, state, self.context)
        self.db.put(key, state)
        if out is None:  # in-place variant
            out = item
        self.stats.outputs_sent += 1
        self.emitter.emit(out, ts, wm, tid=self.cur_tid)


class PMap(_PersistentOperator):
    replica_class = PMapReplica


class PFilterReplica(_PersistentReplica):
    def process_single(self, item, ts, wm):
        key = self._key_of(item)
        state = self.db.get(key)
        keep = self._fn(item, state, self.context)
        self.db.put(key, state)
        if keep:
            self.stats.outputs_sent += 1
            self.emitter.emit(item, ts, wm, tid=self.cur_tid)


class PFilter(_PersistentOperator):
    replica_class = PFilterReplica


class PFlatMapReplica(_PersistentReplica):
    _fn_arity = 3  # (item, state, shipper)

    def __init__(self, op, index):
        super().__init__(op, index)
        self._shipper = Shipper(self)

    def process_single(self, item, ts, wm):
        key = self._key_of(item)
        state = self.db.get(key)
        self._shipper._ts = ts
        self._shipper._wm = wm
        self._fn(item, state, self._shipper, self.context)
        self.db.put(key, state)


class PFlatMap(_PersistentOperator):
    replica_class = PFlatMapReplica


class PReduceReplica(_PersistentReplica):
    def process_single(self, item, ts, wm):
        key = self._key_of(item)
        state = self.db.get(key)
        out = self._fn(item, state, self.context)
        if out is None:  # in-place mutation variant
            out = state
        self.db.put(key, out)
        self.stats.outputs_sent += 1
        self.emitter.emit(copy.copy(out), ts, wm,
                          tid=self.cur_tid)


class PReduce(_PersistentOperator):
    replica_class = PReduceReplica


class PSinkReplica(_PersistentReplica):
    def process_single(self, item, ts, wm):
        key = self._key_of(item)
        state = self.db.get(key)
        self._fn(item, state, self.context)
        self.db.put(key, state)

    def on_eos(self):
        # EOS call with empty item + fresh meaningless state (reference
        # p_sink.hpp svc_end).
        self._fn(None, self.db.new_state(), self.context)
        super().on_eos()


class PSink(_PersistentOperator):
    replica_class = PSinkReplica
    is_terminal = True

    def __init__(self, *args, **kwargs):
        kwargs["terminal"] = True
        super().__init__(*args, **kwargs)
