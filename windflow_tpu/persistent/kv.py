"""Embedded key/value store backing the persistent operator suite.

The RocksDB analogue of the reference (``/root/reference/wf/persistent/
db_handle.hpp:53-140``): byte keys to byte values, durable across process
restarts when the DB path is kept.  The fast path is the native
log-structured store (``native/wf_kv.cpp``, loaded via ctypes); the pure
Python fallback speaks the same on-disk format, so a DB written by one
backend opens under the other.
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Dict, List, Optional, Tuple

from windflow_tpu import native

_HDR = struct.Struct("<Iq")  # u32 klen, i64 vlen (-1 = tombstone)
_MAX_KEY = 1 << 20           # writer cap == scanner sanity bound


class _PyKV:
    """Pure-Python log-structured store (same format as native/wf_kv.cpp)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "a+b")
        self._index: Dict[bytes, Tuple[int, int]] = {}
        self._live = 0
        self._end = self._scan()
        self._f.truncate(self._end)  # drop any torn tail

    def _scan(self) -> int:
        f = self._f
        f.seek(0, os.SEEK_END)
        size = f.tell()
        off = 0
        while off + _HDR.size <= size:
            f.seek(off)
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            klen, vlen = _HDR.unpack(hdr)
            if vlen < -1 or klen > _MAX_KEY:
                break
            rec = _HDR.size + klen + max(vlen, 0)
            if off + rec > size:
                break
            key = f.read(klen)
            old = self._index.pop(key, None)
            if old is not None:
                self._live -= _HDR.size + klen + max(old[1], 0)
            if vlen >= 0:
                self._index[key] = (off + _HDR.size + klen, vlen)
                self._live += rec
            off += rec
        return off

    def _append(self, key: bytes, val: Optional[bytes]) -> None:
        if len(key) > _MAX_KEY:
            raise ValueError(
                f"key of {len(key)} bytes exceeds the {_MAX_KEY}-byte cap "
                "(the open-time log scan would treat it as corruption)")
        vlen = -1 if val is None else len(val)
        self._f.seek(self._end)
        self._f.write(_HDR.pack(len(key), vlen) + key + (val or b""))
        self._end += _HDR.size + len(key) + max(vlen, 0)

    def put(self, key: bytes, val: bytes) -> None:
        off = self._end + _HDR.size + len(key)
        self._append(key, val)
        old = self._index.get(key)
        if old is not None:
            self._live -= _HDR.size + len(key) + max(old[1], 0)
        self._index[key] = (off, len(val))
        self._live += _HDR.size + len(key) + len(val)

    def get(self, key: bytes) -> Optional[bytes]:
        e = self._index.get(key)
        if e is None:
            return None
        self._f.seek(e[0])
        return self._f.read(e[1])

    def delete(self, key: bytes) -> bool:
        e = self._index.get(key)
        if e is None:
            return False
        # tombstone first: if the append fails (ENOSPC), the index must keep
        # matching the log or the record would resurrect on reopen
        self._append(key, None)
        del self._index[key]
        self._live -= _HDR.size + len(key) + max(e[1], 0)
        return True

    def keys(self) -> List[bytes]:
        return list(self._index.keys())

    def count(self) -> int:
        return len(self._index)

    def log_bytes(self) -> int:
        return self._end

    def live_bytes(self) -> int:
        return self._live

    def compact(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as out:
            nindex = {}
            off = 0
            for key, (voff, vlen) in self._index.items():
                self._f.seek(voff)
                val = self._f.read(vlen)
                out.write(_HDR.pack(len(key), vlen) + key + val)
                nindex[key] = (off + _HDR.size + len(key), vlen)
                off += _HDR.size + len(key) + vlen
            out.flush()
            os.fsync(out.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "r+b")
        self._index = nindex
        self._end = off
        self._live = off

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self, delete_db: bool = False) -> None:
        self._f.close()
        if delete_db and os.path.exists(self.path):
            os.unlink(self.path)


class _NativeKV:
    """ctypes wrapper over native/wf_kv.cpp."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._L = native.lib()
        self._h = self._L.wf_kv_open(path.encode(), 1)
        if not self._h:
            raise OSError(f"wf_kv_open failed for {path!r}")

    def put(self, key: bytes, val: bytes) -> None:
        if len(key) > _MAX_KEY:
            raise ValueError(
                f"key of {len(key)} bytes exceeds the {_MAX_KEY}-byte cap")
        if self._L.wf_kv_put(self._h, key, len(key), val, len(val)) != 0:
            raise OSError(f"wf_kv_put failed for {self.path!r}")

    def get(self, key: bytes) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(4096)
        n = self._L.wf_kv_get(self._h, key, len(key), buf, len(buf))
        if n < 0:
            return None
        if n > len(buf):
            buf = ctypes.create_string_buffer(n)
            n = self._L.wf_kv_get(self._h, key, len(key), buf, len(buf))
        return buf.raw[:n]

    def delete(self, key: bytes) -> bool:
        ret = self._L.wf_kv_del(self._h, key, len(key))
        if ret < 0:
            raise OSError(f"wf_kv_del failed for {self.path!r} "
                          "(tombstone write error)")
        return bool(ret)

    def keys(self) -> List[bytes]:
        it = self._L.wf_kv_iter_new(self._h)
        out = []
        buf = ctypes.create_string_buffer(4096)
        try:
            while True:
                n = self._L.wf_kv_iter_next(it, buf, len(buf))
                if n < 0:
                    break
                if n > len(buf):
                    buf = ctypes.create_string_buffer(n)
                    continue
                out.append(buf.raw[:n])
        finally:
            self._L.wf_kv_iter_destroy(it)
        return out

    def count(self) -> int:
        return self._L.wf_kv_count(self._h)

    def log_bytes(self) -> int:
        return self._L.wf_kv_log_bytes(self._h)

    def live_bytes(self) -> int:
        return self._L.wf_kv_live_bytes(self._h)

    def compact(self) -> None:
        if self._L.wf_kv_compact(self._h) != 0:
            raise OSError(f"wf_kv_compact failed for {self.path!r}")

    def flush(self) -> None:
        self._L.wf_kv_flush(self._h)

    def close(self, delete_db: bool = False) -> None:
        if self._h:
            self._L.wf_kv_close(self._h, int(delete_db))
            self._h = None


class LogKV:
    """One open store.  Auto-compacts when the log grows past
    ``compact_ratio`` times the live data (LSM-style space reclamation;
    the reference delegates this to RocksDB's level compaction,
    ``db_options.hpp:52-68``)."""

    def __init__(self, path: str, compact_ratio: float = 4.0,
                 min_compact_bytes: int = 1 << 20) -> None:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        backend = _NativeKV if native.is_available() else _PyKV
        self._kv = backend(path)
        self.path = path
        self.compact_ratio = compact_ratio
        self.min_compact_bytes = min_compact_bytes

    def put(self, key: bytes, val: bytes) -> None:
        self._kv.put(key, val)
        if (self._kv.log_bytes() > self.min_compact_bytes
                and self._kv.log_bytes()
                > self.compact_ratio * max(self._kv.live_bytes(), 1)):
            self._kv.compact()

    def get(self, key: bytes) -> Optional[bytes]:
        return self._kv.get(key)

    def delete(self, key: bytes) -> bool:
        return self._kv.delete(key)

    def keys(self) -> List[bytes]:
        return self._kv.keys()

    def __len__(self) -> int:
        return self._kv.count()

    def log_bytes(self) -> int:
        return self._kv.log_bytes()

    def live_bytes(self) -> int:
        return self._kv.live_bytes()

    def compact(self) -> None:
        self._kv.compact()

    def flush(self) -> None:
        self._kv.flush()

    def close(self, delete_db: bool = False) -> None:
        self._kv.close(delete_db)


# ---------------------------------------------------------------------------
# Shared-store registry: replicas of an operator built with a shared DB (the
# reference's _sharedDb flag, p_map.hpp:92-99) resolve the same path to one
# refcounted LogKV handle.
# ---------------------------------------------------------------------------

_open_stores: Dict[str, Tuple[LogKV, int]] = {}


def open_shared(path: str) -> LogKV:
    ap = os.path.abspath(path)
    if ap in _open_stores:
        kv, rc = _open_stores[ap]
        _open_stores[ap] = (kv, rc + 1)
        return kv
    kv = LogKV(ap)
    _open_stores[ap] = (kv, 1)
    return kv


def close_shared(path: str, delete_db: bool = False) -> None:
    ap = os.path.abspath(path)
    if ap not in _open_stores:
        return
    kv, rc = _open_stores[ap]
    if rc > 1:
        _open_stores[ap] = (kv, rc - 1)
        return
    del _open_stores[ap]
    kv.close(delete_db)
