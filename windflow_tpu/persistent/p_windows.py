"""Persistent keyed windows: archives spill to the embedded KV store.

Re-design of the reference ``P_Keyed_Windows`` (``/root/reference/wf/
persistent/p_keyed_windows.hpp:67``) and its ``P_Window_Replica``
(``p_window_replica.hpp:70-``): each key buffers up to ``n_max_elements``
tuples in memory; a full buffer is flushed to the store as a *fragment*
carrying (min, max, id) domain metadata, and window firing reloads only the
fragments whose [min, max] range overlaps the window — so window archives
can exceed RAM (the reference's sequence-scaling mechanism (d), SURVEY.md
§5.7).  Incremental logic keeps per-window accumulators in memory (the
reference's ``results_in_memory`` default) and needs no archive at all.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from windflow_tpu.persistent.db_handle import DBHandle
from windflow_tpu.windows.engine import Archive, WindowSpec
from windflow_tpu.windows.ops import KeyedWindows, _WindowReplicaBase


class SpillingArchive(Archive):
    """KV-backed archive of ``(domain, aid, item, ts)`` entries for one key."""

    __slots__ = ("_db", "_key", "_n_max", "_mem", "_frags", "_next_frag",
                 "_min", "_max")

    def __init__(self, db: DBHandle, key: Any, n_max: int) -> None:
        self._db = db
        self._key = key
        self._n_max = max(1, n_max)
        self._mem: List = []
        # fragment metadata: (min_domain, max_domain, frag_id, count) —
        # reference meta_frag_t (p_window_replica.hpp:92)
        self._frags: List[Tuple[int, int, int, int]] = []
        self._next_frag = 0
        self._min = None
        self._max = None

    def _frag_key(self, frag_id: int) -> Any:
        return ("__frag__", self._key, frag_id)

    def insert(self, entry) -> None:
        if len(self._mem) >= self._n_max:
            fid = self._next_frag
            self._next_frag += 1
            self._frags.append((self._min, self._max, fid, len(self._mem)))
            self._db.put(self._frag_key(fid), self._mem)
            self._mem = []
            self._min = self._max = None
        d = entry[0]
        self._min = d if self._min is None else min(self._min, d)
        self._max = d if self._max is None else max(self._max, d)
        self._mem.append(entry)

    def range(self, start: int, end: int) -> List:
        out = []
        for (lo, hi, fid, _n) in self._frags:
            # fragment useful iff its [lo, hi] overlaps [start, end)
            # (reference check_range_mm, p_window_replica.hpp:124-131)
            if hi >= start and lo < end:
                out.extend(e for e in self._db.lookup(self._frag_key(fid))
                           if start <= e[0] < end)
        out.extend(e for e in self._mem if start <= e[0] < end)
        out.sort(key=lambda e: e[:2])
        return out

    def purge_below(self, d: int) -> None:
        keep = []
        for frag in self._frags:
            if frag[1] < d:  # max domain below the horizon: fully dead
                self._db.delete(self._frag_key(frag[2]))
            else:
                keep.append(frag)
        self._frags = keep
        self._mem = [e for e in self._mem if e[0] >= d]
        self._recompute_mm()

    def clear(self) -> None:
        for frag in self._frags:
            self._db.delete(self._frag_key(frag[2]))
        self._frags = []
        self._mem = []
        self._min = self._max = None

    def _recompute_mm(self) -> None:
        # keep the buffer's min/max tight after purges, or the next spilled
        # fragment's metadata would cover phantom domains (making range()
        # load it needlessly and purge_below() never reclaim it)
        if self._mem:
            ds = [e[0] for e in self._mem]
            self._min, self._max = min(ds), max(ds)
        else:
            self._min = self._max = None

    def __len__(self) -> int:
        return len(self._mem) + sum(f[3] for f in self._frags)

    @property
    def spilled_fragments(self) -> int:
        return len(self._frags)


class PKeyedWindowsReplica(_WindowReplicaBase):
    def __init__(self, op: "PKeyedWindows", index: int) -> None:
        super().__init__(op, index)
        self.db = DBHandle(op.db_path,
                           serialize=op.serialize,
                           deserialize=op.deserialize,
                           shared=op.shared_db,
                           whoami=index,
                           delete_db=not op.keep_db)

    def on_eos(self):
        super().on_eos()   # fires remaining windows (may reload fragments)
        self.db.close()


class PKeyedWindows(KeyedWindows):
    replica_class = PKeyedWindowsReplica

    def __init__(self, fn, spec: WindowSpec, *, db_path: str,
                 name: str = "p_keyed_windows", parallelism: int = 1,
                 key_extractor: Optional[Callable] = None,
                 incremental: bool = False,
                 n_max_elements: int = 1024,
                 serialize: Callable[[Any], bytes] = None,
                 deserialize: Callable[[bytes], Any] = None,
                 shared_db: bool = False,
                 keep_db: bool = False,
                 output_batch_size: int = 0) -> None:
        super().__init__(fn, spec, name=name, parallelism=parallelism,
                         key_extractor=key_extractor, incremental=incremental,
                         output_batch_size=output_batch_size)
        self.db_path = db_path
        self.n_max_elements = n_max_elements
        self.serialize = serialize
        self.deserialize = deserialize
        self.shared_db = shared_db
        self.host_pool_safe = not shared_db  # see persistent/ops.py
        self.keep_db = keep_db

    def _engine_kwargs(self, replica):
        kw = super()._engine_kwargs(replica)
        kw["archive_factory"] = lambda key: SpillingArchive(
            replica.db, key, self.n_max_elements)
        return kw
