"""Typed handle over the embedded KV store.

Re-design of the reference ``DBHandle<T>`` (``/root/reference/wf/persistent/
db_handle.hpp:53-140``): serialize/deserialize functions turn operator state
into bytes, ``get`` returns a fresh copy of ``initial_state`` for unseen
keys, and the handle either owns a private store or shares one with the
other replicas of its operator (the reference's ``_sharedDb`` flag appends
``"_shared"`` to the path, ``p_map.hpp:92-99``; private handles suffix the
replica index).
"""

from __future__ import annotations

import copy
import pickle
from typing import Any, Callable, List, Optional

from windflow_tpu.persistent import kv as kvmod


def default_serialize(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


default_deserialize = pickle.loads


class DBHandle:
    def __init__(self, db_path: str,
                 serialize: Callable[[Any], bytes] = None,
                 deserialize: Callable[[bytes], Any] = None,
                 initial_state: Any = None,
                 shared: bool = False,
                 whoami: int = 0,
                 delete_db: bool = True) -> None:
        self.serialize = serialize or default_serialize
        self.deserialize = deserialize or default_deserialize
        self.initial_state = initial_state
        self.shared = shared
        self.delete_db = delete_db
        self.path = (db_path + "_shared") if shared \
            else f"{db_path}_r{whoami}"
        self._kv: Optional[kvmod.LogKV] = kvmod.open_shared(self.path) \
            if shared else kvmod.LogKV(self.path)
        self._closed = False

    # -- key encoding --------------------------------------------------------
    @staticmethod
    def key_bytes(key: Any) -> bytes:
        # Stable for the hashable key types streams use (ints, strings,
        # tuples); the reference serializes keys with the same user-supplied
        # mechanism as values.
        if isinstance(key, bytes):
            return b"b" + key
        if isinstance(key, str):
            return b"s" + key.encode()
        if isinstance(key, int):
            return b"i%d" % key
        return b"p" + pickle.dumps(key, protocol=4)

    @staticmethod
    def key_from_bytes(kb: bytes) -> Any:
        tag, rest = kb[:1], kb[1:]
        if tag == b"b":
            return rest
        if tag == b"s":
            return rest.decode()
        if tag == b"i":
            return int(rest)
        return pickle.loads(rest)

    # -- state access (the per-input read-modify-write loop,
    #    reference p_map.hpp:178-211) ----------------------------------------
    def new_state(self) -> Any:
        init = self.initial_state
        return init() if callable(init) else copy.deepcopy(init)

    def get(self, key: Any) -> Any:
        raw = self._kv.get(self.key_bytes(key))
        if raw is None:
            return self.new_state()
        return self.deserialize(raw)

    def lookup(self, key: Any) -> Optional[Any]:
        """Like get, but None (no initial state) for unseen keys."""
        raw = self._kv.get(self.key_bytes(key))
        return None if raw is None else self.deserialize(raw)

    def put(self, key: Any, state: Any) -> None:
        self._kv.put(self.key_bytes(key), self.serialize(state))

    def delete(self, key: Any) -> bool:
        return self._kv.delete(self.key_bytes(key))

    def keys(self) -> List[Any]:
        return [self.key_from_bytes(kb) for kb in self._kv.keys()]

    def __len__(self) -> int:
        return len(self._kv)

    # -- lifecycle -----------------------------------------------------------
    def flush(self) -> None:
        self._kv.flush()

    def close(self) -> None:
        """Close (and delete unless the DB is to be kept — reference deletes
        on destruction when ``deleteDb``, ``db_handle.hpp:108-112``)."""
        if self._closed:
            return
        self._closed = True
        if self.shared:
            kvmod.close_shared(self.path, self.delete_db)
        else:
            self._kv.close(self.delete_db)
