"""ctypes bindings for the native host runtime (``native/wf_host.cpp``).

The native layer mirrors the reference's C++ runtime surface (SURVEY.md
§2.2 keyby hashing, §5.8 watermark plumbing): bulk ingest parsing, key
partitioning, and the watermark fold, plus the log-structured KV
(``wf_kv.cpp``).  The library is built on demand with make from the
package-data sources and loaded via ctypes; every entry point has a numpy
fallback so the framework works (slower) without a C++ toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

# native sources ship as package data next to this module, so wheels and
# editable checkouts build identically
_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_NATIVE_DIR, "libwfhost.so")

_lib = None
_load_attempted = False


_SOURCES = ("wf_host.cpp", "wf_kv.cpp")


_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                          "windflow_tpu", "native")


def _build() -> bool:
    global _SO_PATH
    if not all(os.path.exists(os.path.join(_NATIVE_DIR, s))
               for s in _SOURCES):
        return False
    try:
        if os.access(_NATIVE_DIR, os.W_OK):
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
            return os.path.exists(_SO_PATH)
        # Read-only install (site-packages): build in a private temp dir
        # and atomically publish the .so into the user cache — concurrent
        # processes each build their own copy and the rename is atomic, so
        # a reader never dlopens a half-written library.
        import shutil
        import tempfile
        os.makedirs(_CACHE_DIR, exist_ok=True)
        with tempfile.TemporaryDirectory(dir=_CACHE_DIR) as tmp:
            for src in _SOURCES + ("Makefile",):
                shutil.copy(os.path.join(_NATIVE_DIR, src), tmp)
            subprocess.run(["make", "-C", tmp], check=True,
                           capture_output=True, timeout=120)
            built = os.path.join(tmp, "libwfhost.so")
            if not os.path.exists(built):
                return False
            final = os.path.join(_CACHE_DIR, "libwfhost.so")
            os.replace(built, final)
            _SO_PATH = final
            return True
    except (OSError, subprocess.SubprocessError):
        # no toolchain / read-only everything / make failure: callers fall
        # back to the numpy implementations
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it first if needed; None when the
    toolchain or sources are unavailable (callers fall back to numpy)."""
    global _lib, _load_attempted, _SO_PATH
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("WF_TPU_NO_NATIVE"):
        return None
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    if not os.access(_NATIVE_DIR, os.W_OK):
        # read-only install: the artifact lives in the user cache
        cached = os.path.join(_CACHE_DIR, "libwfhost.so")
        if os.path.exists(cached):
            _SO_PATH = cached
    stale = (not os.path.exists(_SO_PATH)
             or any(os.path.exists(s)
                    and os.path.getmtime(s) > os.path.getmtime(_SO_PATH)
                    for s in srcs))
    if stale and not _build():
        return None
    try:
        L = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    i8, i4, u8 = ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64
    p = ctypes.c_void_p
    L.wf_hash64.restype = u8
    L.wf_hash64.argtypes = [i8]
    L.wf_keyby_partition.restype = None
    L.wf_keyby_partition.argtypes = [p, i8, i4, p, p]
    L.wf_frame_record_bytes.restype = i8
    L.wf_frame_record_bytes.argtypes = [i4]
    L.wf_parse_frames.restype = i8
    L.wf_parse_frames.argtypes = [p, i8, i4, p, p, p, i8]
    L.wf_parse_csv.restype = i8
    L.wf_parse_csv.argtypes = [p, i8, i4, p, p, p, i8, p]
    L.wf_min_watermark.restype = i8
    L.wf_min_watermark.argtypes = [p, i4, i8]
    c = ctypes.c_char_p
    L.wf_kv_open.restype = p
    L.wf_kv_open.argtypes = [c, i4]
    L.wf_kv_put.restype = i4
    L.wf_kv_put.argtypes = [p, c, i4, c, i8]
    L.wf_kv_get.restype = i8
    L.wf_kv_get.argtypes = [p, c, i4, p, i8]
    L.wf_kv_del.restype = i4
    L.wf_kv_del.argtypes = [p, c, i4]
    L.wf_kv_count.restype = i8
    L.wf_kv_count.argtypes = [p]
    L.wf_kv_log_bytes.restype = i8
    L.wf_kv_log_bytes.argtypes = [p]
    L.wf_kv_live_bytes.restype = i8
    L.wf_kv_live_bytes.argtypes = [p]
    L.wf_kv_compact.restype = i4
    L.wf_kv_compact.argtypes = [p]
    L.wf_kv_flush.restype = i4
    L.wf_kv_flush.argtypes = [p]
    L.wf_kv_close.restype = None
    L.wf_kv_close.argtypes = [p, i4]
    L.wf_kv_iter_new.restype = p
    L.wf_kv_iter_new.argtypes = [p]
    L.wf_kv_iter_next.restype = i4
    L.wf_kv_iter_next.argtypes = [p, p, i4]
    L.wf_kv_iter_destroy.restype = None
    L.wf_kv_iter_destroy.argtypes = [p]
    _lib = L
    return _lib


def is_available() -> bool:
    return lib() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


# ---------------------------------------------------------------------------
# High-level wrappers (numpy in / numpy out, with pure-numpy fallbacks)
# ---------------------------------------------------------------------------

_SM_C1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_C2 = np.uint64(0x94D049BB133111EB)
_SM_ADD = np.uint64(0x9E3779B97F4A7C15)


def hash64(keys: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 (matches the native wf_hash64 bit-for-bit)."""
    x = keys.astype(np.uint64) + _SM_ADD
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * _SM_C1
        x = (x ^ (x >> np.uint64(27))) * _SM_C2
    return x ^ (x >> np.uint64(31))


def keyby_partition(keys: np.ndarray, ndest: int):
    """(dests int32[n], counts int64[ndest]): hash-routing of each tuple."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    n = len(keys)
    L = lib()
    if L is not None:
        dests = np.empty(n, np.int32)
        counts = np.empty(ndest, np.int64)
        L.wf_keyby_partition(_ptr(keys), n, ndest, _ptr(dests), _ptr(counts))
        return dests, counts
    dests = (hash64(keys) % np.uint64(ndest)).astype(np.int32)
    counts = np.bincount(dests, minlength=ndest).astype(np.int64)
    return dests, counts


def frame_record_bytes(nv: int) -> int:
    return 16 + 8 * nv


def parse_frames(buf: bytes, nv: int, max_records: int = 2 ** 62):
    """Parse binary records (int64 key, int64 ts, nv×float64) into columns.
    Returns (keys, tss, vals[n, nv], consumed_bytes)."""
    rec = frame_record_bytes(nv)
    n = min(len(buf) // rec, max_records)
    L = lib()
    if L is not None:
        keys = np.empty(n, np.int64)
        tss = np.empty(n, np.int64)
        vals = np.empty((n, nv), np.float64)
        raw = np.frombuffer(buf, np.uint8)
        got = L.wf_parse_frames(_ptr(raw), len(buf), nv, _ptr(keys),
                                _ptr(tss), _ptr(vals), n)
        assert got == n
        return keys, tss, vals, n * rec
    arr = np.frombuffer(buf[:n * rec], np.uint8).reshape(n, rec)
    keys = arr[:, 0:8].copy().view(np.int64).reshape(n)
    tss = arr[:, 8:16].copy().view(np.int64).reshape(n)
    vals = arr[:, 16:].copy().view(np.float64).reshape(n, nv)
    return keys, tss, vals, n * rec


def parse_csv(buf: bytes, nv: int, max_records: int = 2 ** 62):
    """Parse "key,ts,v0[,v1...]\\n" lines into columns.
    Returns (keys, tss, vals[n, nv], consumed_bytes)."""
    L = lib()
    if L is not None:
        cap = min(max_records, buf.count(b"\n") + 1)
        keys = np.empty(cap, np.int64)
        tss = np.empty(cap, np.int64)
        vals = np.empty((cap, nv), np.float64)
        consumed = np.zeros(1, np.int64)
        raw = np.frombuffer(buf, np.uint8)
        n = L.wf_parse_csv(_ptr(raw), len(buf), nv, _ptr(keys), _ptr(tss),
                           _ptr(vals), cap, _ptr(consumed))
        return keys[:n].copy(), tss[:n].copy(), vals[:n].copy(), \
            int(consumed[0])
    keys, tss, rows = [], [], []
    consumed = 0
    for line in buf.split(b"\n")[:-1]:
        end = consumed + len(line) + 1
        if len(keys) >= max_records:
            break
        consumed = end
        parts = line.split(b",")
        if len(parts) != 2 + nv:
            continue
        try:
            k, t = int(parts[0]), int(parts[1])
            vs = [float(x) for x in parts[2:]]
        except ValueError:
            continue
        keys.append(k)
        tss.append(t)
        rows.append(vs)
    return (np.array(keys, np.int64), np.array(tss, np.int64),
            np.array(rows, np.float64).reshape(len(keys), nv), consumed)



def min_watermark(channel_wms: np.ndarray, wm_none: int) -> int:
    """Min over channel maxima; wm_none if any channel is still unset."""
    channel_wms = np.ascontiguousarray(channel_wms, np.int64)
    L = lib()
    if L is not None:
        return int(L.wf_min_watermark(_ptr(channel_wms), len(channel_wms),
                                      wm_none))
    if (channel_wms == wm_none).any() or len(channel_wms) == 0:
        return wm_none
    return int(channel_wms.min())
