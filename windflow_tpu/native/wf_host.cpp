// windflow_tpu native host runtime.
//
// TPU-native equivalent of the reference's native data plane
// (/root/reference/wf: recycling.hpp / recycling_gpu.hpp free-list pools,
// ff::MPMC_Ptr_Queue lock-free queues, forward_emitter_gpu.hpp pinned
// staging, keyby_emitter.hpp hash routing): the pieces of the runtime that
// sit AROUND the XLA compute path and want to be native — bulk ingest
// parsing, key partitioning, and the watermark fold.  Exposed as a plain
// C ABI consumed via
// ctypes (windflow_tpu/native/__init__.py); no Python.h dependency so the
// library builds with any g++ and loads in any CPython.
//
// Build: `make -C native` -> native/libwfhost.so

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Hashing + keyby partitioning (reference keyby_emitter.hpp:216 hash%ndest).
// splitmix64: deterministic across processes, well-mixed for dense int keys.
// ---------------------------------------------------------------------------

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

uint64_t wf_hash64(int64_t key) { return splitmix64((uint64_t)key); }

// dest_out[i] = hash(keys[i]) % ndest; counts_out[d] = #tuples for dest d.
void wf_keyby_partition(const int64_t* keys, int64_t n, int32_t ndest,
                        int32_t* dest_out, int64_t* counts_out) {
  memset(counts_out, 0, sizeof(int64_t) * (size_t)ndest);
  for (int64_t i = 0; i < n; ++i) {
    int32_t d = (int32_t)(splitmix64((uint64_t)keys[i]) % (uint64_t)ndest);
    dest_out[i] = d;
    counts_out[d]++;
  }
}


// ---------------------------------------------------------------------------
// Bulk ingest: parse binary frames / CSV into columns (the native
// data-loader; feeds the staging emitter with zero per-tuple Python work).
// Binary record layout: int64 key, int64 ts, nv x float64 values (LE).
// ---------------------------------------------------------------------------

int64_t wf_frame_record_bytes(int32_t nv) { return 16 + 8 * (int64_t)nv; }

// Returns #records parsed (caps at max_records; ignores trailing partial
// record — the caller carries the remainder into the next chunk).
int64_t wf_parse_frames(const uint8_t* buf, int64_t nbytes, int32_t nv,
                        int64_t* keys, int64_t* tss, double* vals,
                        int64_t max_records) {
  const int64_t rec = wf_frame_record_bytes(nv);
  int64_t n = nbytes / rec;
  if (n > max_records) n = max_records;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = buf + i * rec;
    memcpy(&keys[i], p, 8);
    memcpy(&tss[i], p + 8, 8);
    memcpy(&vals[i * nv], p + 16, 8 * (size_t)nv);
  }
  return n;
}

// CSV lines "key,ts,v0[,v1...]\n".  Returns #records; stops at max_records
// or at the last complete line; *consumed_out = bytes consumed.
int64_t wf_parse_csv(const char* buf, int64_t nbytes, int32_t nv,
                     int64_t* keys, int64_t* tss, double* vals,
                     int64_t max_records, int64_t* consumed_out) {
  int64_t n = 0, pos = 0;
  std::vector<char> scratch(512);
  while (n < max_records) {
    // find end of line
    int64_t eol = pos;
    while (eol < nbytes && buf[eol] != '\n') eol++;
    if (eol >= nbytes) break;  // partial line: leave for next chunk
    // copy the line into a NUL-terminated scratch so strto* cannot scan
    // past the newline (a field like "5,50,\n6" must not steal digits from
    // the next line) or past the end of the buffer
    int64_t len = eol - pos;
    if (len + 1 > (int64_t)scratch.size()) scratch.resize((size_t)len + 1);
    char* line = scratch.data();
    memcpy(line, buf + pos, (size_t)len);
    line[len] = '\0';
    char* end;
    int64_t key = strtoll(line, &end, 10);
    // malformed (empty key or no separator): skip line
    if (end == line || *end != ',') { pos = eol + 1; continue; }
    const char* ts_start = end + 1;
    int64_t ts = strtoll(ts_start, &end, 10);
    bool ok = (end != ts_start);
    for (int32_t v = 0; ok && v < nv; ++v) {
      if (*end != ',') { ok = false; break; }
      const char* start = end + 1;
      vals[n * nv + v] = strtod(start, &end);
      if (end == start) { ok = false; break; }  // empty field
    }
    if (ok) {
      keys[n] = key;
      tss[n] = ts;
      n++;
    }
    pos = eol + 1;
  }
  *consumed_out = pos;
  return n;
}

// ---------------------------------------------------------------------------
// Watermark fold: min over per-channel maxima, ignoring unset channels
// (reference watermark_collector.hpp:63-76 inner loop).
// ---------------------------------------------------------------------------

int64_t wf_min_watermark(const int64_t* channel_wms, int32_t n,
                         int64_t wm_none) {
  int64_t m = wm_none;
  for (int32_t i = 0; i < n; ++i) {
    int64_t w = channel_wms[i];
    if (w == wm_none) return wm_none;  // some channel has no watermark yet
    if (m == wm_none || w < m) m = w;
  }
  return m;
}

}  // extern "C"
