// wf_kv: embedded log-structured key/value store for persistent operators.
//
// TPU-native stand-in for the RocksDB dependency of the reference's
// persistent operator suite (/root/reference/wf/persistent/db_handle.hpp:53-140):
// keyed operator state and spilled window fragments live here, surviving
// process restarts when the DB path is kept.  Design: single append-only data
// log per store + an in-memory hash index (key -> value offset/len), rebuilt
// by a sequential scan on open; deletes are tombstones; compaction rewrites
// the log keeping only live entries.  This favors the streaming write path
// (state write-back per input is the hot loop, p_map.hpp:178-211) over range
// scans, which the persistent operators never do by key order.
//
// Record layout (little-endian, no alignment):
//   [u32 klen][i64 vlen][key bytes][value bytes]     vlen == -1 => tombstone
//
// Thread-safety: a coarse mutex per store.  Replicas run on the host driver's
// cooperative scheduler, so contention is nil; the lock guards shared-DB use
// from auxiliary threads (monitoring, loaders).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Entry {
    int64_t val_off;   // file offset of the value bytes
    int64_t val_len;
};

struct WfKv {
    int fd = -1;
    std::string path;
    int64_t end = 0;         // append offset (log size)
    int64_t live = 0;        // bytes occupied by live records
    std::unordered_map<std::string, Entry> index;
    std::mutex mu;
};

constexpr int64_t kHeader = 12;  // u32 klen + i64 vlen
constexpr uint32_t kMaxKey = 1u << 20;  // writer cap == scanner sanity bound

int64_t record_size(int64_t klen, int64_t vlen) {
    return kHeader + klen + (vlen > 0 ? vlen : 0);
}

bool read_exact(int fd, void* buf, int64_t n, int64_t off) {
    int64_t got = 0;
    auto* p = static_cast<uint8_t*>(buf);
    while (got < n) {
        ssize_t r = pread(fd, p + got, (size_t)(n - got), (off_t)(off + got));
        if (r <= 0) return false;
        got += r;
    }
    return true;
}

bool write_exact(int fd, const void* buf, int64_t n, int64_t off) {
    int64_t put = 0;
    auto* p = static_cast<const uint8_t*>(buf);
    while (put < n) {
        ssize_t r = pwrite(fd, p + put, (size_t)(n - put), (off_t)(off + put));
        if (r < 0) return false;
        put += r;
    }
    return true;
}

// Scan the log rebuilding the index; returns the offset of the first
// malformed/truncated record (the recovery point).
int64_t scan(WfKv* kv) {
    struct stat st;
    if (fstat(kv->fd, &st) != 0) return 0;
    const int64_t size = st.st_size;
    int64_t off = 0;
    std::vector<char> key;
    while (off + kHeader <= size) {
        uint8_t hdr[kHeader];
        if (!read_exact(kv->fd, hdr, kHeader, off)) break;
        uint32_t klen;
        int64_t vlen;
        std::memcpy(&klen, hdr, 4);
        std::memcpy(&vlen, hdr + 4, 8);
        if (vlen < -1 || klen > kMaxKey) break;  // corrupt header
        const int64_t rec = record_size(klen, vlen);
        if (off + rec > size) break;  // truncated tail
        key.resize(klen);
        if (klen && !read_exact(kv->fd, key.data(), klen, off + kHeader)) break;
        std::string k(key.data(), klen);
        auto it = kv->index.find(k);
        if (it != kv->index.end()) {  // superseded: old record is now dead
            kv->live -= record_size(klen, it->second.val_len);
            kv->index.erase(it);
        }
        if (vlen >= 0) {
            kv->index.emplace(std::move(k), Entry{off + kHeader + klen, vlen});
            kv->live += rec;
        }
        off += rec;
    }
    return off;
}

bool append(WfKv* kv, const uint8_t* k, uint32_t klen, const uint8_t* v,
            int64_t vlen) {
    uint8_t hdr[kHeader];
    std::memcpy(hdr, &klen, 4);
    std::memcpy(hdr + 4, &vlen, 8);
    int64_t off = kv->end;
    if (!write_exact(kv->fd, hdr, kHeader, off)) return false;
    if (klen && !write_exact(kv->fd, k, klen, off + kHeader)) return false;
    if (vlen > 0 && !write_exact(kv->fd, v, vlen, off + kHeader + klen))
        return false;
    kv->end = off + record_size(klen, vlen);
    return true;
}

}  // namespace

extern "C" {

void* wf_kv_open(const char* path, int32_t create) {
    int flags = O_RDWR | (create ? O_CREAT : 0);
    int fd = open(path, flags, 0644);
    if (fd < 0) return nullptr;
    auto* kv = new WfKv;
    kv->fd = fd;
    kv->path = path;
    int64_t good = scan(kv);
    struct stat st;
    if (fstat(fd, &st) == 0 && good < st.st_size) {
        // Torn tail from a crash mid-append: drop it so new appends are clean.
        if (ftruncate(fd, (off_t)good) != 0) { /* keep going; appends rewrite */ }
    }
    kv->end = good;
    return kv;
}

int32_t wf_kv_put(void* h, const uint8_t* k, int32_t klen, const uint8_t* v,
                  int64_t vlen) {
    auto* kv = static_cast<WfKv*>(h);
    if ((uint32_t)klen > kMaxKey) return -1;  // scan() rejects larger keys
    std::lock_guard<std::mutex> g(kv->mu);
    int64_t off = kv->end;
    if (!append(kv, k, (uint32_t)klen, v, vlen)) return -1;
    std::string key(reinterpret_cast<const char*>(k), (size_t)klen);
    auto it = kv->index.find(key);
    if (it != kv->index.end()) {
        kv->live -= record_size(klen, it->second.val_len);
        it->second = Entry{off + kHeader + klen, vlen};
    } else {
        kv->index.emplace(std::move(key), Entry{off + kHeader + klen, vlen});
    }
    kv->live += record_size(klen, vlen);
    return 0;
}

// Returns the value length (copying min(vlen, cap) bytes into out), or -1 if
// the key is absent.  A result > cap means the caller's buffer was too small:
// retry with a buffer of the returned size.
int64_t wf_kv_get(void* h, const uint8_t* k, int32_t klen, uint8_t* out,
                  int64_t cap) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    auto it = kv->index.find(
        std::string(reinterpret_cast<const char*>(k), (size_t)klen));
    if (it == kv->index.end()) return -1;
    const Entry& e = it->second;
    int64_t n = e.val_len < cap ? e.val_len : cap;
    if (n > 0 && !read_exact(kv->fd, out, n, e.val_off)) return -1;
    return e.val_len;
}

int32_t wf_kv_del(void* h, const uint8_t* k, int32_t klen) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    std::string key(reinterpret_cast<const char*>(k), (size_t)klen);
    auto it = kv->index.find(key);
    if (it == kv->index.end()) return 0;
    if (!append(kv, k, (uint32_t)klen, nullptr, -1)) {
        // Tombstone write failed (e.g. ENOSPC): without it, the old record
        // would resurrect on reopen — keep the index entry consistent with
        // the log and report the failure instead.
        return -1;
    }
    kv->live -= record_size(klen, it->second.val_len);
    kv->index.erase(it);
    return 1;
}

int64_t wf_kv_count(void* h) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    return (int64_t)kv->index.size();
}

int64_t wf_kv_log_bytes(void* h) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    return kv->end;
}

int64_t wf_kv_live_bytes(void* h) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    return kv->live;
}

// Rewrite the log keeping only live records; shrinks the file and refreshes
// the index offsets.  Safe against crashes: the new log is built beside the
// old one and renamed over it only once fully written and synced.
int32_t wf_kv_compact(void* h) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    std::string tmp = kv->path + ".compact";
    int nfd = open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
    if (nfd < 0) return -1;
    int64_t off = 0;
    std::vector<uint8_t> val;
    std::unordered_map<std::string, Entry> nindex;
    nindex.reserve(kv->index.size());
    for (const auto& [key, e] : kv->index) {
        val.resize((size_t)e.val_len);
        if (e.val_len &&
            !read_exact(kv->fd, val.data(), e.val_len, e.val_off)) {
            close(nfd);
            unlink(tmp.c_str());
            return -1;
        }
        uint32_t klen = (uint32_t)key.size();
        uint8_t hdr[kHeader];
        std::memcpy(hdr, &klen, 4);
        std::memcpy(hdr + 4, &e.val_len, 8);
        bool ok = write_exact(nfd, hdr, kHeader, off) &&
                  write_exact(nfd, key.data(), klen, off + kHeader) &&
                  (e.val_len == 0 ||
                   write_exact(nfd, val.data(), e.val_len,
                               off + kHeader + klen));
        if (!ok) {
            close(nfd);
            unlink(tmp.c_str());
            return -1;
        }
        nindex.emplace(key, Entry{off + kHeader + klen, e.val_len});
        off += record_size(klen, e.val_len);
    }
    if (fsync(nfd) != 0 || rename(tmp.c_str(), kv->path.c_str()) != 0) {
        close(nfd);
        unlink(tmp.c_str());
        return -1;
    }
    close(kv->fd);
    kv->fd = nfd;
    kv->end = off;
    kv->live = off;
    kv->index = std::move(nindex);
    return 0;
}

int32_t wf_kv_flush(void* h) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    return fsync(kv->fd) == 0 ? 0 : -1;
}

void wf_kv_close(void* h, int32_t delete_db) {
    auto* kv = static_cast<WfKv*>(h);
    {
        std::lock_guard<std::mutex> g(kv->mu);
        close(kv->fd);
        if (delete_db) unlink(kv->path.c_str());
    }
    delete kv;
}

// -- key iteration (snapshot of current keys; used for EOS window flush) -----

struct WfKvIter {
    std::vector<std::string> keys;
    size_t pos = 0;
};

void* wf_kv_iter_new(void* h) {
    auto* kv = static_cast<WfKv*>(h);
    std::lock_guard<std::mutex> g(kv->mu);
    auto* it = new WfKvIter;
    it->keys.reserve(kv->index.size());
    for (const auto& [key, e] : kv->index) {
        (void)e;
        it->keys.push_back(key);
    }
    return it;
}

// Returns the key length (advancing only when it fits in kcap), or -1 when
// exhausted.  A result > kcap means retry with a larger buffer.
int32_t wf_kv_iter_next(void* hi, uint8_t* kout, int32_t kcap) {
    auto* it = static_cast<WfKvIter*>(hi);
    if (it->pos >= it->keys.size()) return -1;
    const std::string& k = it->keys[it->pos];
    if ((int64_t)k.size() > kcap) return (int32_t)k.size();
    std::memcpy(kout, k.data(), k.size());
    it->pos++;
    return (int32_t)k.size();
}

void wf_kv_iter_destroy(void* hi) { delete static_cast<WfKvIter*>(hi); }

}  // extern "C"
