"""DeviceSource: batches generated ON DEVICE by a jitted program.

The reference's GPU sources still materialize tuples in host memory and
copy them in (``Batch_GPU_t`` staging, ``batch_gpu_t.hpp:51-229``); a TPU
source has a cheaper option the reference lacks: run the generator itself
as an XLA program so the batch is BORN in HBM and the host link never
carries the hot path.  Uses:

* synthetic/benchmark feeds — the bench's ``e2e_device_source`` mode uses
  this to measure pure framework dispatch overhead, decoupled from
  host→device link bandwidth (VERDICT r4 item 3);
* replay of device-resident datasets (arrays already in HBM);
* load generators for soak tests.

Device-born batches never touch the wire plane (windflow_tpu/wire.py):
there is no host→device transfer to compress, which is exactly why the
bench's ``e2e_device_source`` leg anchors the staging-share
decomposition the wire round's ``staging_share`` number is read
against.  ``batch_fn`` still matters to the wire plane indirectly: the
preflight spec walk infers this source's record spec from it
(``analysis/preflight.propagate_specs``), so a DeviceSource feeding a
host stage that later re-stages to a device edge keeps that edge
spec-known (no WF606 downgrade).

Contract: ``batch_fn(i)`` is JAX-traceable, maps the int32 batch index to
a payload pytree whose leaves have leading dimension ``capacity``; it is
jitted once and executed per tick.  Timestamps: INGRESS stamps the whole
batch with one monotone host arrival stamp (broadcast on device); EVENT
requires ``ts_fn(i) -> int64[capacity]`` (traced, fused into the same
program) plus ``wm_fn(i) -> int`` giving the batch's watermark frontier
on the host — the host never reads device lanes back to learn time.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from windflow_tpu.basic import RoutingMode, TimePolicy, WindFlowError, \
    current_time_usecs
from windflow_tpu.batch import DeviceBatch
from windflow_tpu.monitoring.jit_registry import wf_jit
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.source import BaseSourceReplica, Source


class DeviceSourceReplica(BaseSourceReplica):
    def __init__(self, op: "DeviceSource", index: int) -> None:
        super().__init__(op, index)
        self._i = index              # replicas stride the batch index space
        self._jit = None

    def start(self) -> None:
        if self.time_policy == TimePolicy.EVENT \
                and (self.op.ts_fn is None or self.op.wm_fn is None):
            raise WindFlowError(
                f"device source '{self.op.name}': EVENT time policy needs "
                "both ts_fn (device lane) and wm_fn (host frontier)")
        if self.time_policy != TimePolicy.EVENT and self.op.ts_fn is not None:
            # event-time lanes under an INGRESS wall-clock watermark would
            # put every tuple eons behind the frontier — windows would
            # silently drop everything as late
            raise WindFlowError(
                f"device source '{self.op.name}': withTimestampFn requires "
                "the EVENT time policy (INGRESS stamps arrival time itself)")
        cap = self.op.capacity

        def program(i, base_ts):
            payload = self.op.batch_fn(i)
            ts = (self.op.ts_fn(i).astype(jnp.int64)
                  if self.op.ts_fn is not None
                  else jnp.full((cap,), base_ts, jnp.int64))
            return payload, ts, jnp.ones((cap,), bool)

        self._jit = wf_jit(program, op_name=self.op.name)

    def tick(self, max_items: int) -> bool:
        """One device batch per tick (``max_items`` is a host-tuple notion;
        a device source's natural quantum is its compiled batch)."""
        if self._exhausted:
            return False
        if self._i >= self.op.n_batches:
            self._exhausted = True
            self._terminate()
            return True
        if self.time_policy == TimePolicy.INGRESS:
            base = max(current_time_usecs(), self._last_ts + 1)
            wm = base
            # every lane carries the same arrival stamp, so the data-ts
            # extrema are host-known for free — device-born batches then
            # feed the same preemptive TB ring sizing as staged batches
            # (DeviceBatch.ts_min/ts_max, windows/ffat_tpu
            # _regrow_for_span) without any device sync
            ts_lo = ts_hi = base
        else:
            base = 0
            wm = int(self.op.wm_fn(self._i))
            if self.op.ts_bounds_fn is not None:
                lo, hi = self.op.ts_bounds_fn(self._i)
                ts_lo, ts_hi = int(lo), int(hi)
            else:
                ts_lo = ts_hi = None    # unknown: eviction backstop only
        payload, ts, valid = self._jit(jnp.int32(self._i), jnp.int64(base))
        self._last_ts = max(self._last_ts, wm)
        self._advance_wm(self._last_ts)
        self.stats.outputs_sent += self.op.capacity
        self.stats.device_programs_launched += 1
        # device-born batches join the flight recorder's trace lane at
        # birth ("emitted" — nothing was staged over the host link)
        self.emitter.emit_device_batch(
            DeviceBatch(payload, ts, valid, watermark=self.current_wm,
                        size=self.op.capacity, ts_min=ts_lo, ts_max=ts_hi,
                        trace=self.emitter._new_trace()))
        self._i += self.op.parallelism
        self._count_toward_punctuation(self.op.capacity)
        return True


class DeviceSource(Source):
    """Source whose batches are generated on device (see module doc).

    ``n_batches`` bounds the stream; replicas stride the index space
    (replica r generates batches r, r+parallelism, ...)."""

    replica_class = DeviceSourceReplica

    def __init__(self, batch_fn: Callable, capacity: int, n_batches: int,
                 name: str = "device_source", parallelism: int = 1,
                 ts_fn: Optional[Callable] = None,
                 wm_fn: Optional[Callable[[int], int]] = None,
                 ts_bounds_fn: Optional[Callable] = None) -> None:
        if capacity <= 0 or n_batches < 0:
            raise WindFlowError(
                "device source needs capacity > 0 and n_batches >= 0")
        Operator.__init__(self, name, parallelism, routing=RoutingMode.NONE,
                          output_batch_size=capacity, is_tpu=True)
        self.batch_fn = batch_fn
        self.capacity = capacity
        self.n_batches = n_batches
        self.ts_fn = ts_fn
        self.wm_fn = wm_fn
        #: optional HOST fn ``i -> (ts_min, ts_max)`` bounding the event-
        #: time lane of batch ``i``: attaches the data-ts extrema that let
        #: downstream TB window rings size themselves preemptively
        #: (batch.py DeviceBatch.ts_min/ts_max) — without it, device-born
        #: EVENT batches rely on the eviction-cadence backstop
        self.ts_bounds_fn = ts_bounds_fn
        self.ts_extractor = None
