"""Bulk IO: native-parsed ingestion sources (the framework's data loaders)."""

from windflow_tpu.io.device_source import DeviceSource
from windflow_tpu.io.frames import FrameSource
