"""FrameSource: bulk binary/CSV ingestion through the native parser.

The TPU-native answer to the reference's high-rate ingestion paths (Kafka
consumer poll loops, ``kafka_source.hpp:270-310``; and the test drivers that
generate tuples in tight C++ loops): instead of one Python object per tuple,
the source pulls **byte chunks** from the user, parses them to columns in C++
(``native/wf_host.cpp`` wf_parse_frames / wf_parse_csv), and hands whole
columns to the staging emitter — so a batch travels from bytes to TPU HBM
without any per-tuple Python work.  Falls back to numpy parsing when the
native library is unavailable.

Record wire format (``fmt="frames"``): little-endian ``int64 key, int64 ts,
nv × float64 values``.  CSV (``fmt="csv"``): ``key,ts,v0[,v1...]`` lines.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

import numpy as np

from windflow_tpu import native
from windflow_tpu.basic import RoutingMode, TimePolicy, WindFlowError, \
    current_time_usecs
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.source import BaseSourceReplica, Source


class FrameSourceReplica(BaseSourceReplica):
    def __init__(self, op: "FrameSource", index: int) -> None:
        super().__init__(op, index)
        self._chunks = None
        self._carry = b""

    def start(self) -> None:
        self._chunks = iter(self.op.chunks_fn(self.context))

    def tick(self, max_items: int) -> bool:
        if self._exhausted:
            return False
        try:
            chunk = next(self._chunks)
        except StopIteration:
            self._flush_carry()
            self._exhausted = True
            self._terminate()
            return True  # termination (EOS cascade) is progress
        self._ingest(self._carry + chunk)
        return True

    def _flush_carry(self) -> None:
        if self._carry:
            if self.op.fmt == "csv" and not self._carry.endswith(b"\n"):
                # a file without a trailing newline still ends in a complete
                # record; an unterminated binary frame is genuinely partial
                self._carry += b"\n"
            self._ingest(self._carry, final=True)

    def _ingest(self, buf: bytes, final: bool = False) -> None:
        nv = self.op.nv
        if self.op.fmt == "frames":
            keys, tss, vals, consumed = native.parse_frames(buf, nv)
        else:
            keys, tss, vals, consumed = native.parse_csv(buf, nv)
        self._carry = b"" if final else buf[consumed:]
        n = len(keys)
        if n == 0:
            return
        if self.time_policy == TimePolicy.INGRESS:
            # every record of the chunk arrived with the chunk: one arrival
            # stamp (monotone vs earlier chunks), not a synthetic +arange
            # ramp that would place timestamps in the wall-clock future
            base = max(current_time_usecs(), self._last_ts)
            tss = np.full(n, base, dtype=np.int64)
            row_wms = tss
        else:
            # per-row frontier: running max event ts (reference
            # Source_Shipper advances the watermark per tuple) — lets the
            # staging emitter stamp batches that split this chunk exactly
            row_wms = np.maximum(np.maximum.accumulate(tss),
                                 max(self._last_ts, 0))
        self._last_ts = max(self._last_ts, int(tss.max()))
        self._advance_wm(self._last_ts)
        self.stats.outputs_sent += n
        # int32 keys on device when they fit: every keyed device operator
        # interns int32 keys (KeyedDeviceStageEmitter._key32), so staging
        # the full int64 wire key usually doubles the lane's bytes for no
        # extra key space — but keys outside int32 (e.g. 64-bit hash ids)
        # keep their width so host-side consumers never see collisions
        keys = keys.astype(np.int64)
        if len(keys) and np.int32(keys.max() >> 31) == (keys.min() >> 31)                 and -(1 << 31) <= keys.min() and keys.max() < (1 << 31):
            keys = keys.astype(np.int32)
        cols = {"key": keys}
        vd = self.op.value_dtype
        for i, name in enumerate(self.op.fields):
            cols[name] = np.ascontiguousarray(vals[:, i].astype(vd,
                                                                copy=False))
        self.emitter.emit_columns(cols, tss, self.current_wm,
                                  row_wms=row_wms)
        self._count_toward_punctuation(n)


class FrameSource(Source):
    """Bulk source over a byte-chunk generator.

    ``chunks_fn`` (optionally taking a RuntimeContext) yields ``bytes``
    objects; records may span chunk boundaries (the remainder is carried).
    ``fields`` names the ``nv`` float64 value columns; records surface
    downstream as ``{"key": int, <field>: float, ...}``.

    TPU-first dtype policy: value columns are staged as **float32** by
    default even though the wire format is float64 — the TPU has no native
    f64 (XLA emulates it with 32-bit pairs at several times the cost) and
    f32 halves the staged bytes.  Pass ``value_dtype=np.float64`` for full
    wire precision; keys keep int64 whenever they don't fit int32."""

    replica_class = FrameSourceReplica

    def __init__(self, chunks_fn: Callable[..., Iterable[bytes]],
                 nv: int = 1, fields: Optional[List[str]] = None,
                 fmt: str = "frames", name: str = "frame_source",
                 parallelism: int = 1, output_batch_size: int = 0,
                 value_dtype=np.float32) -> None:
        if fmt not in ("frames", "csv"):
            raise WindFlowError(f"unknown frame format '{fmt}'")
        if fields is not None and len(fields) != nv:
            raise WindFlowError("fields must name all nv value columns")
        Operator.__init__(self, name, parallelism, routing=RoutingMode.NONE,
                          output_batch_size=output_batch_size)
        self.chunks_fn = adapt(chunks_fn, 0)
        self.nv = nv
        self.fields = fields or [f"v{i}" for i in range(nv)]
        self.fmt = fmt
        #: device dtype for value columns.  float32 by default — the wire
        #: format is float64, but the TPU has no native f64 (XLA emulates
        #: it with 32-bit pairs); pass np.float64 to keep full precision.
        self.value_dtype = np.dtype(value_dtype)
        self.ts_extractor = None
