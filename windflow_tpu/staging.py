"""Staging plane: host-buffer recycling pool + streaming packed batches.

WindFlow's L1 data plane gets its rate from two mechanisms this module
reproduces for the TPU (reference ``recycling.hpp`` ``ff::MPMC_Ptr_Queue``
batch recycling; ``batch_gpu_t.hpp`` per-batch CUDA streams overlapping
H2D copies with kernel execution):

* :class:`StagingPool` — fixed-capacity, size-keyed pool of host ``uint32``
  staging buffers reused across batches, so steady-state staging performs
  **zero numpy allocation** (the reference's recycling queue).  A released
  buffer carries a device-side *gate*: any array whose readiness implies
  the device has finished consuming the buffer.  Re-acquiring a buffer
  whose gate is still in flight blocks until the gate is ready — the
  recycling queue's blocking pop, which doubles as natural backpressure
  exactly like the reference's ``FullGPUMemoryException`` retry loop
  (``recycling_gpu.hpp:88-126``).  In steady state the gate is long ready
  (the pool runs several buffers deep) and acquire never syncs.

* :class:`PackedBatchBuilder` — streaming packer writing SoA chunk slices
  straight into a pooled buffer at their final packed offsets: all payload
  lanes, the timestamp lane, and the fill count ride ONE contiguous host
  buffer and ONE host→device transfer per batch (``batch.py`` unpacks it
  on device with a cached program).  No intermediate concatenate, no
  per-lane ``device_put`` — host↔device links are dominated by
  per-transfer latency, not bandwidth.

* Double-buffered prefetch lives in the run loop
  (``graph/pipegraph.py``, ``Config.stage_prefetch_depth``): with a
  sweep's device programs dispatched asynchronously, the driver packs
  batch N+1 on the host while batch N's XLA step runs — JAX async
  dispatch plays the role of the reference's 2-deep pinned double
  buffering (``forward_emitter_gpu.hpp:254-300``).

Buffer layout (shared with ``batch.py``'s cached unpack programs)::

    [lane0 words | lane1 words | ... | ts words (2/row) | n]

where a 4-byte lane contributes 1 word/row and an int64 lane 2 words/row
(little-endian lo/hi interleaved — the TPU X64-rewrite implements no
64-bit bitcast, so 64-bit lanes travel as arithmetic word pairs).
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque
from typing import Optional, Sequence

import numpy as np

from windflow_tpu.analysis import debug_concurrency as _dbg
from windflow_tpu.analysis.hotpath import hot_path

#: retained buffers per distinct buffer size (the recycling queue depth);
#: 4 covers the driver loop's double buffering with margin for the keyed
#: staging emitter's per-partition builders
DEFAULT_DEPTH = int(os.environ.get("WF_TPU_STAGING_POOL_DEPTH", "4"))
#: global cap on bytes RETAINED by the pool (buffers out on loan are the
#: caller's); beyond it releases drop their buffer (graceful degradation
#: to plain allocation, never a deadlock)
DEFAULT_MAX_BYTES = int(os.environ.get("WF_TPU_STAGING_POOL_BYTES",
                                       str(256 << 20)))


def lane_words(dt) -> int:
    """uint32 words per row for one packed lane."""
    return 2 if np.dtype(dt).itemsize == 8 else 1


def packable_dtype(dt) -> bool:
    """Lanes that can ride the packed buffer: any 4-byte dtype via a
    32-bit device bitcast, or int64/uint64 as arithmetic lo/hi pairs
    (float64 has no cheap device decode — TPU has no native f64)."""
    dt = np.dtype(dt)
    return (dt.itemsize == 4) or dt in (np.dtype(np.int64),
                                        np.dtype(np.uint64))


def size_class(nwords: int) -> int:
    """Pool size class of a data-dependent buffer size: round up to 1/8
    granularity of the enclosing power of two (256-word floor).  Wire-
    compressed staging buffers (windflow_tpu/wire.py) vary in size with
    the data, so the pool MUST key on the class, not the exact size —
    codec-choice churn across reseeds would otherwise mint a fresh slot
    per batch and thrash the pool (hit/miss counters in
    ``stats()["Staging_pool"]`` prove reuse either way).  Bounded waste:
    the step is 1/8 of the enclosing power of two, so padding stays
    under 25% of the transfer in the worst case (just past a power of
    two) and under 12.5% on average."""
    if nwords <= 256:
        return 256
    step = 1 << max(0, (nwords - 1).bit_length() - 3)
    return ((nwords + step - 1) // step) * step


class StagingPool:
    """Size-keyed recycling pool of host ``uint32`` staging buffers.

    Thread-safe (host worker-pool replicas may stage concurrently); the
    lock guards only deque bookkeeping, never a copy or a device sync.
    ``acquire`` never blocks on pool state — an empty slot allocates (a
    counted miss) — and only ever waits on a recycled buffer's gate.
    """

    #: lock discipline declaration enforced by tools/wf_lint.py (WF721):
    #: the slot dict and retained-byte counter mutate only under _lock
    __lock_guards__ = {"_lock": ("_slots", "_held_bytes")}

    def __init__(self, depth: int = DEFAULT_DEPTH,
                 max_bytes: int = DEFAULT_MAX_BYTES) -> None:
        self.depth = max(1, depth)
        self.max_bytes = max_bytes
        self._held_bytes = 0
        if _dbg.ENABLED:
            # race detector (analysis/debug_concurrency): the lock records
            # its owning thread and every mutation of _slots AND of the
            # slot deques it hands out asserts it is held — silent
            # unlocked writes become immediate diagnostics
            self._lock = _dbg.DebugLock("StagingPool._lock")
            self._slots = _dbg.LockCheckedDict(self._lock,
                                               "StagingPool._slots")
            self._new_slot = lambda: _dbg.LockCheckedDeque(
                self._lock, "StagingPool._slots slot deque")
        else:
            self._slots = {}        # nwords -> deque[(buf, gate)]
            self._lock = threading.Lock()
            self._new_slot = deque
        # counters (exposed via stats() and the PipeGraph monitoring dump)
        self.hits = 0
        self.misses = 0
        self.releases = 0
        self.drops = 0          # releases refused at capacity
        self.gate_waits = 0     # acquires that had to sync on a gate

    def acquire(self, nwords: int) -> np.ndarray:
        """A ``uint32[nwords]`` host buffer: recycled when one is pooled
        (waiting on its gate only if the device is still reading it),
        freshly allocated otherwise.  Contents are UNDEFINED — callers
        overwrite every word they transfer, zeroing only partial-batch
        tails (``PackedBatchBuilder.finish``)."""
        entry = None
        with self._lock:
            dq = self._slots.get(nwords)
            if dq:
                entry = dq.popleft()
                self._held_bytes -= nwords * 4
                self.hits += 1
            else:
                self.misses += 1
        if entry is None:
            return np.empty(nwords, np.uint32)
        buf, gate = entry
        if gate is not None:
            ready = True
            try:
                # A DELETED gate cannot be synced on (is_ready/
                # block_until_ready raise) — by construction it never
                # happens for the pool's own gates: batch.stage_packed
                # gates on the unpack program's PRIVATE scalar output,
                # which no consumer can reach with donate_argnums
                # (deletion at a donating consumer's async dispatch
                # enqueue would prove nothing about the H2D DMA still
                # reading `buf`).  Foreign gates that do arrive deleted
                # fall through as "ready" — there is nothing left to
                # wait on.
                if getattr(gate, "is_deleted", lambda: False)():
                    ready = True
                else:
                    ready = bool(gate.is_ready())
            except (AttributeError, RuntimeError, TypeError):
                # gate arrays are backend-supplied: non-jax gates lack
                # is_ready/is_deleted — treat as "not provably ready"
                # and sync below
                ready = False
            if not ready:
                self.gate_waits += 1
                import jax
                try:
                    jax.block_until_ready(gate)
                except RuntimeError:
                    # deleted between the check and the sync: nothing
                    # left to wait on
                    pass
        return buf

    def release(self, buf: np.ndarray, gate=None) -> None:
        """Return a buffer for reuse.  ``gate`` is a device array whose
        readiness implies the device has finished reading ``buf`` (for a
        packed batch: any output of the unpack program).  At capacity the
        buffer is dropped instead of pooled — allocation pressure, never
        blocking."""
        with self._lock:
            dq = self._slots.setdefault(buf.shape[0], self._new_slot())
            if len(dq) >= self.depth \
                    or self._held_bytes + buf.nbytes > self.max_bytes:
                self.drops += 1
                return
            dq.append((buf, gate))
            self._held_bytes += buf.nbytes
            self.releases += 1

    # -- telemetry -----------------------------------------------------------
    def stats(self) -> dict:
        """Counter snapshot for the monitoring stats layer
        (``PipeGraph.stats()["Staging_pool"]``)."""
        total = self.hits + self.misses
        with self._lock:
            held = self._held_bytes
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "releases": self.releases,
            "drops_at_capacity": self.drops,
            "gate_waits": self.gate_waits,
            "held_bytes": held,
            "depth": self.depth,
        }

    def reset_stats(self) -> None:
        self.hits = self.misses = self.releases = 0
        self.drops = self.gate_waits = 0

    def clear(self) -> None:
        """Drop every pooled buffer (tests; backend teardown)."""
        with self._lock:
            self._slots.clear()
            self._held_bytes = 0


class _DeviceBytes:
    """Staging-attributed device-byte accounting (monitoring
    ``stats()["Device"]["staging"]``): cumulative packed bytes shipped
    host→device and the batch count behind them, noted by
    ``batch.stage_packed`` at every fused transfer.  Since the wire
    round the WIRE bytes (actual transfer) and the LOGICAL bytes (what
    the decoded lanes occupy) are counted separately — equating them
    let compression silently inflate every bytes-derived ratio.  Plain
    int adds — concurrent pool-thread updates may lose a tick, the same
    telemetry tolerance as the graph's lock-free backpressure reads."""

    __slots__ = ("staged_bytes_total", "staged_batches_total",
                 "logical_bytes_total")

    def __init__(self) -> None:
        self.staged_bytes_total = 0     # wire bytes: actual transfers
        self.staged_batches_total = 0
        self.logical_bytes_total = 0    # decoded (pre-compression) bytes

    def note(self, nbytes: int, logical_nbytes: Optional[int] = None) -> None:
        self.staged_bytes_total += nbytes
        self.logical_bytes_total += (logical_nbytes if logical_nbytes
                                     is not None else nbytes)
        self.staged_batches_total += 1

    def reset(self) -> None:
        self.staged_bytes_total = 0
        self.staged_batches_total = 0
        self.logical_bytes_total = 0


#: process-wide staged-transfer accounting (shared like the default pool)
device_bytes = _DeviceBytes()


_default_pool: Optional[StagingPool] = None
_default_lock = threading.Lock()


def default_pool() -> StagingPool:
    """Process-wide staging pool shared by every graph's staging emitters
    (buffers are shape-keyed, so sharing across graphs only helps)."""
    global _default_pool
    if _default_pool is None:
        with _default_lock:
            if _default_pool is None:
                _default_pool = StagingPool()
    return _default_pool


def set_default_pool(pool: Optional[StagingPool]) -> None:
    """Swap the process-wide pool (tests; sizing experiments)."""
    global _default_pool
    _default_pool = pool


class PackedBatchBuilder:
    """Streams SoA rows into one pooled staging buffer.

    ``dtypes`` lists the payload lanes in order (each packable, see
    :func:`packable_dtype`); the int64 timestamp lane and the fill-count
    word are implicit.  ``append`` writes each chunk slice at its final
    packed offset — the zero-copy-beyond-one-memcpy streaming form of the
    reference's pinned-buffer fill loop (``forward_emitter_gpu.hpp``).
    """

    __slots__ = ("capacity", "dtypes", "_words", "_offsets", "total",
                 "buf", "n", "pool", "_lane_dtypes")

    def __init__(self, dtypes: Sequence, capacity: int,
                 pool: Optional[StagingPool] = None) -> None:
        self.pool = pool or default_pool()
        self.dtypes = tuple(np.dtype(d) for d in dtypes)
        if not all(packable_dtype(d) for d in self.dtypes):
            raise ValueError(f"unpackable lane dtypes {self.dtypes}")
        # payload dtypes + the implicit int64 ts lane, precomputed so the
        # @hot_path append builds nothing per call
        self._lane_dtypes = self.dtypes + (np.dtype(np.int64),)
        self._words = [lane_words(d) for d in self.dtypes] + [2]  # + ts
        self._offsets = []
        off = 0
        for w in self._words:
            self._offsets.append(off)
            off += w * capacity
        self.total = off + 1            # + fill-count word
        self.capacity = capacity
        self.buf = self.pool.acquire(self.total)
        self.n = 0

    @property
    def room(self) -> int:
        return self.capacity - self.n

    @hot_path
    def append(self, lanes: Sequence[np.ndarray], tss: np.ndarray) -> None:
        """Write ``len(tss)`` rows: ``lanes`` are 1-D payload columns in
        ``dtypes`` order, ``tss`` the int64 timestamps.  Slices of
        contiguous source columns view as uint32 without copying."""
        if _dbg.ENABLED:
            # a builder is single-consumer: one replica's emitter fills it
            # (possibly from different pool threads across sweeps, never
            # concurrently) — overlapping appends are a race.  The guard
            # is a context manager so a mid-append exception cannot leave
            # a stale entry behind.
            with _dbg.entry_guard(self, "PackedBatchBuilder.append"):
                return self._append_impl(lanes, tss)
        return self._append_impl(lanes, tss)

    @hot_path
    def _append_impl(self, lanes, tss) -> None:
        m = len(tss)
        for off, w, dt, lane in zip(self._offsets, self._words,
                                    self._lane_dtypes,
                                    itertools.chain(lanes, (tss,))):
            src = np.ascontiguousarray(lane, dt).view(np.uint32)
            lo = off + w * self.n
            self.buf[lo:lo + w * m] = src
        self.n += m

    @hot_path
    def finish(self) -> np.ndarray:
        """Zero each lane's unwritten tail (recycled buffers carry stale
        words; the old per-batch ``np.zeros`` padded with zeros, and
        downstream equality depends on it only for partial batches), stamp
        the fill count, and hand the buffer over.  The caller owns it
        until ``pool.release(buf, gate)``."""
        if _dbg.ENABLED:
            with _dbg.entry_guard(self, "PackedBatchBuilder.finish"):
                return self._finish_impl()
        return self._finish_impl()

    @hot_path
    def _finish_impl(self) -> np.ndarray:
        if self.n < self.capacity:
            for off, w in zip(self._offsets, self._words):
                self.buf[off + w * self.n:off + w * self.capacity] = 0
        self.buf[-1] = self.n
        return self.buf

    def abandon(self) -> None:
        """Return an unused buffer to the pool (no gate: nothing read it)."""
        self.pool.release(self.buf, None)
