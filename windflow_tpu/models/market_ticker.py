"""MarketTicker: per-symbol sliding high/low tracker (the classic
finance-feed window query; DSPBench's "stock analytics" family, used by
the reference's evaluation papers).

``Source(ticks) → FfatWindowsTPU(declared max) → Sink``: one device
window op computes BOTH the sliding high and the sliding low per symbol
in a single program, by lifting each tick to the two-leaf aggregate
``{"hi": price, "lo": -price}`` under a leafwise ``maximum`` combiner —
``min(x) == -max(-x)``, so one declared-"max" monoid covers both ends.
The declaration routes the step onto the scatter-combine fast path (no
grouping permutation, identity-filled flagless fold; see
``windows/ffat_kernels.make_ffat_step``) — the reference pays its
per-batch sort for the same query regardless of combiner
(``ffat_replica_gpu.hpp:751``).
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

import jax.numpy as jnp

import windflow_tpu as wf


def build(ticks: Iterable[dict],
          on_window: Optional[Callable] = None,
          *, win_len: int = 64, slide: int = 16, max_symbols: int = 64,
          batch: int = 1024) -> wf.PipeGraph:
    """Ticks are dicts ``{"sym": int, "price": float}`` (extra lanes ride
    along).  Each fired window emits ``{"sym", "wid", "high", "low"}``."""

    def emit(res, ctx=None):
        if res is not None and on_window is not None:
            on_window({"sym": int(res["key"]), "wid": int(res["wid"]),
                       "high": float(res["value"]["hi"]),
                       "low": -float(res["value"]["lo"])})

    src = (wf.Source_Builder(lambda: iter(ticks)).withName("ticks")
           .withOutputBatchSize(batch).build())
    hilo = (wf.Ffat_WindowsTPU_Builder(
                lambda t: {"hi": t["price"], "lo": -t["price"]},
                lambda a, b: {"hi": jnp.maximum(a["hi"], b["hi"]),
                              "lo": jnp.maximum(a["lo"], b["lo"])})
            .withName("hilo")
            .withCBWindows(win_len, slide)
            .withKeyBy(lambda t: t["sym"])
            .withMaxKeys(max_symbols)
            .withMonoidCombiner("max").build())
    sink = wf.Sink_Builder(emit).withName("quotes_out").build()

    g = wf.PipeGraph("market_ticker", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(hilo).add_sink(sink)
    return g


def run(ticks: Iterable[dict], **kwargs) -> List[dict]:
    """Run to completion; returns ``{"sym", "wid", "high", "low"}`` rows."""
    results: List[dict] = []
    build(ticks, on_window=results.append, **kwargs).run()
    return results
