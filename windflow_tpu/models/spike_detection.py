"""SpikeDetection: sensor-stream anomaly application (DSPBench suite, used
by the reference's evaluation papers).

``Source(readings) → keyed sliding-window average → Filter(spike) → Sink``:
per-sensor moving average over a count-based sliding window, flagging
readings that deviate more than ``threshold`` × average — exercises keyed
windows with incremental logic and a keyed filter chained on window results.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, List, Optional

import windflow_tpu as wf


@dataclasses.dataclass
class Reading:
    device: int
    value: float


@dataclasses.dataclass
class Spike:
    device: int
    window_id: int
    average: float


def build(readings: Iterable[Reading],
          on_spike: Optional[Callable[[Spike], None]] = None,
          win_len: int = 16, slide: int = 1,
          threshold: float = 1.25,
          window_parallelism: int = 2,
          detector_parallelism: int = 1) -> wf.PipeGraph:
    def moving_avg(r, acc):
        # incremental (tuple, accumulator) logic: track sum/count/last value
        if acc is None:
            acc = {"sum": 0.0, "n": 0, "last": 0.0}
        acc["sum"] += r.value
        acc["n"] += 1
        acc["last"] = r.value
        return acc

    def is_spike(res):
        avg = res.value["sum"] / res.value["n"]
        return abs(res.value["last"]) > threshold * abs(avg)

    def emit(res, ctx=None):
        if res is not None and on_spike is not None:
            on_spike(Spike(device=res.key, window_id=res.wid,
                           average=res.value["sum"] / res.value["n"]))

    src = (wf.Source_Builder(lambda: iter(readings))
           .withName("sensor_source").build())
    win = (wf.Keyed_Windows_Builder(moving_avg)
           .withName("moving_average")
           .withCBWindows(win_len, slide)
           .withKeyBy(lambda r: r.device)
           .withParallelism(window_parallelism).build())
    det = (wf.Filter_Builder(is_spike).withName("spike_detector")
           .withParallelism(detector_parallelism)
           .withKeyBy(lambda res: res.key).build())
    sink = wf.Sink_Builder(emit).withName("spike_sink").build()

    g = wf.PipeGraph("spike_detection", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(win).add(det).add_sink(sink)
    return g


def run(readings: Iterable[Reading], **kwargs) -> List[Spike]:
    spikes: List[Spike] = []
    g = build(readings, on_spike=spikes.append, **kwargs)
    g.run()
    return spikes
