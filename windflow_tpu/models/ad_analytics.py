"""AdAnalytics: the Yahoo-Streaming-Benchmark-shaped advertising pipeline.

``Source(events) → FilterTPU(view events) → MapTPU(project) →
FfatWindowsTPU(per-campaign TB count) → Sink`` — the canonical
filter/project/windowed-count workload the streaming community benchmarks
engines with (YSB), expressed device-first: the filter and projection fuse
into one XLA program via chaining, the ad→campaign join is a device gather
against a static campaign table (YSB's Redis join becomes an on-device
lookup), and the per-campaign counts come from time-based FFAT windows fired
on the watermark frontier.

Reference parity: the reference's evaluation apps are DSPBench-style
pipelines of exactly this shape (its GPU graph tests chain
Filter_GPU/Map_GPU into windows, ``tests/graph_tests_gpu``); this is the
TPU-native expression with a keyed time-window tail.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

import windflow_tpu as wf


def build(events: Iterable[dict],
          ad_to_campaign: List[int],
          on_count: Optional[Callable[[int, int, int], None]] = None, *,
          win_usec: int = 10_000_000, slide_usec: int = 10_000_000,
          batch: int = 4096,
          view_type: int = 1) -> wf.PipeGraph:
    """``events`` are dicts with int columns ``ad_id``, ``etype``, ``ts``
    (µs).  ``ad_to_campaign[ad]`` maps each ad to its campaign id; the table
    is closed over by the projection and becomes a device-resident constant
    gather (XLA keeps it on-chip — no per-tuple host lookup).

    ``on_count(campaign, window_id, n)`` receives each fired window count.
    """
    import jax.numpy as jnp

    table = jnp.asarray(ad_to_campaign, jnp.int32)
    n_campaigns = int(max(ad_to_campaign)) + 1 if len(ad_to_campaign) else 1

    src = (wf.Source_Builder(lambda: iter(events))
           .withName("ad_events")
           .withTimestampExtractor(lambda e: e["ts"])
           .withOutputBatchSize(batch).build())
    # filter + project chain into ONE fused XLA program per batch
    flt = (wf.FilterTPU_Builder(lambda e: e["etype"] == view_type)
           .withName("view_filter").build())
    prj = (wf.MapTPU_Builder(
            lambda e: {"campaign": table[e["ad_id"]], "one": 1})
           .withName("campaign_join").build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda e: e["one"],
                                      lambda a, b: a + b)
           .withName("campaign_counts")
           .withTBWindows(win_usec, slide_usec)
           .withKeyBy(lambda e: e["campaign"])
           .withMaxKeys(n_campaigns).build())

    def emit(r, ctx=None):
        if r is not None and on_count is not None:
            on_count(int(r["key"]), int(r["wid"]), int(r["value"]))

    sink = wf.Sink_Builder(emit).withName("count_sink").build()

    g = wf.PipeGraph("ad_analytics", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    pipe = g.add_source(src)
    pipe.add(flt)
    pipe.chain(prj)
    pipe.add(win).add_sink(sink)
    return g


def run(events: Iterable[dict], ad_to_campaign: List[int],
        **kwargs) -> Dict[Tuple[int, int], int]:
    counts: Dict[Tuple[int, int], int] = {}
    g = build(events, ad_to_campaign,
              on_count=lambda c, w, n: counts.__setitem__((c, w), n),
              **kwargs)
    g.run()
    return counts
