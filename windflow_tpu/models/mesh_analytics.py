"""MeshAnalytics: the multi-chip configuration of the flagship pipeline.

The same graph as ``ffat_analytics`` — ``Source → MapTPU ⊕ FilterTPU →
FfatWindowsTPU → Sink`` — executed over a ``jax.sharding.Mesh`` via
``Config(mesh=...)``: staged batches lay out data-sharded, the chained
map/filter runs with zero communication, and the keyed window state is
sharded along the mesh's key axis with one ``all_gather`` per batch over
ICI (``windflow_tpu.parallel.mesh``).  On a v5e pod slice this is the
8-chip scaling configuration from BASELINE.json; on the test backend it
runs on virtual CPU devices::

    XLA_FLAGS=--xla_force_host_platform_device_count=8
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, List, Optional

import windflow_tpu as wf
from windflow_tpu.basic import Config
from windflow_tpu.parallel import mesh as M


def build(records: Iterable[dict],
          on_window: Optional[Callable] = None, *,
          n_devices: Optional[int] = None,
          data_axis: int = 1,
          win_len: int = 64, slide: int = 16,
          max_keys: int = 64, batch: int = 1024) -> wf.PipeGraph:
    """``records`` are dicts with int field ``k`` and float field ``v``;
    ``max_keys`` must be divisible by the mesh's key-axis extent and
    ``batch`` by its data-axis extent.  ``on_window(key, wid, value)``
    receives each fired window."""
    mesh = M.make_mesh(n_devices=n_devices, data=data_axis)
    cfg = dataclasses.replace(Config(), mesh=mesh)

    src = (wf.Source_Builder(lambda: iter(records))
           .withName("records").withOutputBatchSize(batch).build())
    mp = (wf.MapTPU_Builder(lambda t: {"k": t["k"], "v": t["v"] * 1.5})
          .withName("scale").build())
    flt = (wf.FilterTPU_Builder(lambda t: t["v"] >= 0.0)
           .withName("clip").build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
           .withName("sharded_windows")
           .withCBWindows(win_len, slide)
           .withKeyBy(lambda t: t["k"]).withMaxKeys(max_keys).build())

    def emit(r, ctx=None):
        if r is not None and on_window is not None:
            on_window(int(r["key"]), int(r["wid"]), float(r["value"]))

    snk = wf.Sink_Builder(emit).withName("windows_out").build()

    g = wf.PipeGraph("mesh_analytics", wf.ExecutionMode.DEFAULT, config=cfg)
    pipe = g.add_source(src)
    pipe.add(mp)
    pipe.chain(flt)          # fuses into ONE sharded XLA program
    pipe.add(win).add_sink(snk)
    return g


def run(records: Iterable[dict], **kwargs) -> List[tuple]:
    out: List[tuple] = []
    g = build(records, on_window=lambda k, w, v: out.append((k, w, v)),
              **kwargs)
    g.run()
    return out
