"""Telemetry over binary frames: the zero-per-tuple-Python pipeline.

``FrameSource → MapTPU⊕FilterTPU (chained) → FfatWindowsTPU (TB) →
columnar Sink``: byte chunks parse to columns in C, all lanes of a batch
ride ONE packed host→device transfer, time-based sliding windows fire on
the watermark frontier with a configurable ring-overflow policy, and
results leave through the deferred single-transfer columnar egress — no
per-tuple Python object exists anywhere on the hot path.

This is the application shape for high-rate machine telemetry (metrics,
sensor frames): the wire format is the ``io.frames`` record layout
(``int64 key, int64 ts, float64 value``), e.g. produced by any columnar
exporter.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import windflow_tpu as wf
from windflow_tpu.io import FrameSource


def build(chunks: Callable[[], Iterable[bytes]],
          on_windows: Optional[Callable] = None,
          *, win_usec: int = 60_000_000, slide_usec: int = 5_000_000,
          max_keys: int = 1024, batch: int = 8192,
          lateness_usec: int = 1_000_000,
          overflow_policy: str = "drop",
          transform: Optional[Callable] = None,
          predicate: Optional[Callable] = None,
          lift: Optional[Callable] = None) -> wf.PipeGraph:
    """``chunks`` yields byte blobs in the frames wire format; ``on_windows``
    receives :class:`windflow_tpu.SinkColumns` (SoA numpy: ``key``, ``wid``,
    ``value`` columns + the timestamp lane) once per result batch.

    ``transform``/``predicate``/``lift`` customize the three stages; a
    custom ``transform`` must keep the ``key`` field, and the default
    ``predicate`` and ``lift`` read field ``v0`` — a transform that renames
    or drops ``v0`` must supply its own ``predicate`` and ``lift``."""
    transform = transform or (
        lambda t: {"key": t["key"], "v0": t["v0"]})
    predicate = predicate or (lambda t: t["v0"] == t["v0"])  # drop NaNs
    lift = lift or (lambda t: t["v0"])

    def emit(cols, ctx=None):
        if cols is not None and on_windows is not None:
            on_windows(cols)

    src = FrameSource(chunks, nv=1, fmt="frames", name="frames_in",
                      output_batch_size=batch)
    mp = wf.MapTPU_Builder(transform).withName("normalize").build()
    flt = wf.FilterTPU_Builder(predicate).withName("drop_nan").build()
    win = (wf.Ffat_WindowsTPU_Builder(lift, lambda a, b: a + b)
           .withName("tb_windows")
           .withTBWindows(win_usec, slide_usec)
           .withKeyBy(lambda t: t["key"])
           .withMaxKeys(max_keys)
           .withLateness(lateness_usec)
           .withOverflowPolicy(overflow_policy).build())
    sink = (wf.Sink_Builder(emit).withName("columns_out")
            .withColumnarSink().build())

    g = wf.PipeGraph("telemetry_frames", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    pipe = g.add_source(src)
    pipe.add(mp)
    pipe.chain(flt)        # Map+Filter fuse into one XLA program
    pipe.add(win).add_sink(sink)
    return g
