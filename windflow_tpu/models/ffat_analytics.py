"""FFAT analytics: the flagship TPU pipeline (the north-star benchmark
shape, BASELINE.md) packaged as a reusable application.

``Source → MapTPU → FilterTPU → FfatWindowsTPU → Sink``: staged columnar
batches, bf16-friendly elementwise transform and predicate fused on device,
and per-key sliding-window aggregation over the on-device FlatFAT pane tree
— every fired window of every key computed in one XLA program per batch.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

import windflow_tpu as wf


def build(records: Iterable[dict],
          on_window: Optional[Callable] = None,
          *, win_len: int = 1024, slide: int = 128, max_keys: int = 1024,
          batch: int = 4096,
          transform: Optional[Callable] = None,
          predicate: Optional[Callable] = None,
          lift: Optional[Callable] = None,
          comb: Optional[Callable] = None) -> wf.PipeGraph:
    """Records are dicts of scalars with an int ``k`` key field and a float
    ``v`` value field (arbitrary extra lanes ride along)."""
    transform = transform or (
        lambda t: {"k": t["k"], "v": t["v"] * 1.5 + 1.0})
    predicate = predicate or (lambda t: (t["k"] & 7) != 7)
    lift = lift or (lambda t: t["v"])
    comb = comb or (lambda a, b: a + b)

    def emit(res, ctx=None):
        if res is not None and on_window is not None:
            on_window(res)

    src = (wf.Source_Builder(lambda: iter(records)).withName("ingest")
           .withOutputBatchSize(batch).build())
    mp = wf.MapTPU_Builder(transform).withName("transform").build()
    flt = wf.FilterTPU_Builder(predicate).withName("select").build()
    ffat = (wf.Ffat_WindowsTPU_Builder(lift, comb)
            .withName("ffat")
            .withCBWindows(win_len, slide)
            .withKeyBy(lambda t: t["k"])
            .withMaxKeys(max_keys).build())
    sink = wf.Sink_Builder(emit).withName("windows_out").build()

    g = wf.PipeGraph("ffat_analytics", wf.ExecutionMode.DEFAULT)
    pipe = g.add_source(src)
    pipe.chain(mp)          # chained TPU stages fuse into one XLA program
    pipe.chain(flt)
    pipe.add(ffat).add_sink(sink)
    return g


def run(records: Iterable[dict], **kwargs) -> List[dict]:
    """Run to completion; returns window records
    ``{"key": int, "wid": int, "value": float}``."""
    results: List[dict] = []
    g = build(records, on_window=results.append, **kwargs)
    g.run()
    return results
