"""FraudDetection: per-card Markov-chain transaction scoring (DSPBench
suite, used by the reference's evaluation papers).

``Source(transactions) → StatefulMapTPU(transition score) →
FilterTPU(low probability) → Sink``: each card's previous transaction
type is keyed device state (a dense slot table updated on device every
batch — the TPU redesign of the reference's keyed ``Map_GPU`` state with
per-key spinlocks, ``map_gpu.hpp``); the score of a transaction is the
Markov transition probability from the previous type, looked up in a
closed-over device table inside the fused program.  Transactions whose
transition probability falls below ``threshold`` are flagged.

First-seen cards score 1.0 (no prior, never flagged) via the sentinel
``-1`` initial state.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, Sequence

import jax.numpy as jnp

import windflow_tpu as wf


def build(transactions: Iterable[dict],
          transition: Sequence[Sequence[float]],
          on_alert: Optional[Callable] = None,
          *, max_cards: int = 256, threshold: float = 0.05,
          batch: int = 1024) -> wf.PipeGraph:
    """Transactions are dicts ``{"card": int, "etype": int}`` with
    ``etype`` in ``[0, len(transition))``; ``transition[i][j]`` is the
    probability of type ``j`` following type ``i``."""
    table = jnp.asarray(transition, jnp.float32)

    def score(t, prev):
        # prev < 0: first transaction of this card — no prior, score 1.0
        p = jnp.where(prev < 0, jnp.float32(1.0),
                      table[jnp.clip(prev, 0), t["etype"]])
        out = {"card": t["card"], "etype": t["etype"], "score": p}
        return out, t["etype"].astype(jnp.int32)

    def emit(res, ctx=None):
        if res is not None and on_alert is not None:
            on_alert({"card": int(res["card"]),
                      "etype": int(res["etype"]),
                      "score": float(res["score"])})

    src = (wf.Source_Builder(lambda: iter(transactions))
           .withName("transactions").withOutputBatchSize(batch).build())
    scorer = (wf.MapTPU_Builder(score).withName("markov_score")
              .withInitialState(jnp.full((), -1, jnp.int32))
              .withKeyBy(lambda t: t["card"])
              .withNumKeySlots(max_cards).withDenseKeys().build())
    flag = (wf.FilterTPU_Builder(lambda t: t["score"] < threshold)
            .withName("flag").build())
    sink = wf.Sink_Builder(emit).withName("alerts").build()

    g = wf.PipeGraph("fraud_detection", wf.ExecutionMode.DEFAULT)
    pipe = g.add_source(src)
    pipe.add(scorer)
    pipe.chain(flag)       # score + flag fuse into one device program
    pipe.add_sink(sink)
    return g


def run(transactions: Iterable[dict],
        transition: Sequence[Sequence[float]], **kwargs) -> List[dict]:
    """Run to completion; returns flagged
    ``{"card", "etype", "score"}`` alerts."""
    alerts: List[dict] = []
    build(transactions, transition, on_alert=alerts.append,
          **kwargs).run()
    return alerts
