"""Example streaming applications built on windflow_tpu — the application
set the reference's evaluation papers benchmark (DSPBench-style WordCount,
SpikeDetection, MarketTicker, FraudDetection) plus the flagship TPU FFAT analytics
pipeline, the zero-per-tuple binary-telemetry pipeline, the
Yahoo-Streaming-Benchmark ad-analytics pipeline, and the multi-chip mesh
configuration."""

from windflow_tpu.models import (ad_analytics, ffat_analytics,
                                 fraud_detection, market_ticker,
                                 mesh_analytics, spike_detection,
                                 telemetry_frames, wordcount)
