"""Example streaming applications built on windflow_tpu (the reference ships
these as test/benchmark programs; see models/wordcount.py and
models/yahoo_bench.py)."""
