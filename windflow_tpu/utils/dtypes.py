"""Dtype policy for writes into carried device state.

One rule, shared by the stateful-operator scatters (``ops/tpu_stateful.py``)
and the FFAT continuation-cell merge (``windows/ffat_kernels.py``): the
state/table dtype is authoritative, and a user-fn update may be cast to it
when the cast cannot corrupt state —

* same kind (f64 → f32 narrowing, i64 → i32, …): allowed — deliberate
  narrowing to the declared state precision;
* standard promotion lands on the state dtype (i32 update into an f32
  table): allowed — identical to what ``state + update`` arithmetic does;
* anything else (float update into an int table, complex into float,
  signed into unsigned): a loud error — a silent truncating scatter would
  corrupt state with no diagnostic (and is an error in future JAX anyway).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from windflow_tpu.basic import WindFlowError


def cast_state_update(u, dtype, what: str = "stateful update"):
    """Cast update ``u`` to the state ``dtype`` under the policy above."""
    if u.dtype == dtype:
        return u
    if np.dtype(u.dtype).kind == np.dtype(dtype).kind:
        return u.astype(dtype)
    if jnp.promote_types(u.dtype, dtype) == np.dtype(dtype):
        return u.astype(dtype)
    raise WindFlowError(
        f"{what} dtype {u.dtype} does not match the state dtype {dtype} "
        "(the cast would corrupt state); make the function return the "
        "state's kind or widen the state prototype")
