"""TPU operators: the device compute path.

These replace the reference's CUDA operator set (``/root/reference/wf/map_gpu.hpp``,
``filter_gpu.hpp``, ``reduce_gpu.hpp``) with XLA programs:

* ``Map_GPU``'s grid-stride elementwise kernel (``map_gpu.hpp:60-76``) becomes
  ``jax.vmap`` of the user's per-item function over the batch — XLA tiles it
  onto the VPU/MXU and fuses adjacent elementwise work.
* ``Filter_GPU``'s predicate + compaction (``filter_gpu.hpp``) becomes a
  validity-mask update: compaction is deferred (mask-aware consumers) because
  XLA prefers static shapes; the mask costs one fused elementwise op instead
  of a gather.
* ``Reduce_GPU``'s ``sort_by_key`` + ``reduce_by_key`` pipeline
  (``reduce_gpu.hpp:227-283``) becomes an XLA sort + segmented
  ``associative_scan`` — the same algorithm Thrust runs, expressed so the
  compiler can fuse the user combiner into the scan.

Structural invariants kept from the reference (SURVEY.md §2.5): TPU operators
consume batches only, require an upstream output batch size > 0, and run in
DEFAULT execution mode only.

User functions must be JAX-traceable, operating on one record (a pytree of
scalars) with ``jnp`` ops.  They are traced once per batch shape: the staging
emitter pads every batch to a fixed capacity precisely so each operator
compiles a single program.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from windflow_tpu.basic import RoutingMode, WindFlowError, current_time_usecs
from windflow_tpu.batch import DeviceBatch
from windflow_tpu.monitoring import recorder as flightrec
from windflow_tpu.monitoring.jit_registry import wf_jit
from windflow_tpu.ops.base import Operator, Replica


def _payload_nbytes(tree) -> int:
    return sum(getattr(l, "nbytes", 0) for l in jax.tree.leaves(tree))


class _TPUReplica(Replica):
    """Shared device-batch plumbing for TPU operator replicas."""

    def _op_step(self, batch: DeviceBatch):
        """Hook for replicas whose operator step needs the replica index
        (per-replica state); default ops take the batch alone.  A fused
        all-stateless segment installs its chain program here
        (windflow_tpu/fusion FusedStatelessExec) — the unfused path pays
        exactly this one attribute check."""
        fx = self.op._fusion_exec
        if fx is not None:
            return fx.step(batch)
        return self.op._step(batch)

    def process_device_batch(self, batch: DeviceBatch) -> None:
        if batch.trace is not None:
            # profiler bridge: the sampled (1-in-N trace-lane) batch's
            # device dispatch is wrapped in a TraceAnnotation carrying the
            # flight-recorder trace id, so a jax.profiler capture
            # (PipeGraph.profile) and dump_trace()'s Chrome trace line up
            # span-for-span in one Perfetto session.  Untraced batches pay
            # exactly this one attribute check (budget asserted by
            # tests/test_device_metrics.py).
            with jax.profiler.TraceAnnotation(
                    f"op:{self.op.name} trace:{batch.trace[0]}"):
                out = self._op_step(batch)
        else:
            out = self._op_step(batch)
        self.stats.device_programs_launched += 1
        if self.ring is not None and batch.trace is not None:
            # `dispatched` stamps the ASYNC enqueue (the host is already
            # free); the device-side completion is only observable through
            # a real sync, so `device_done` blocks on the output for every
            # M-th traced batch (Config.trace_device_sync_every) — 1 in
            # (sample_every * M) batches pays the sync.
            self.ring.record(batch.trace[0], flightrec.DISPATCHED,
                             current_time_usecs())
            self._traced_seen += 1
            sync_every = self.config.trace_device_sync_every
            if out is not None and sync_every \
                    and self._traced_seen % sync_every == 0:
                jax.block_until_ready(out.valid)
                now = current_time_usecs()
                self.ring.record(batch.trace[0], flightrec.DEVICE_DONE,
                                 now)
                if self.latency is not None:
                    # window-freshness gauge (latency ledger): fire time
                    # minus window-close event time over the fired
                    # records of this already-synced batch — bound only
                    # on window replicas, and only the 1-in-
                    # (sample * sync) sampled batch reaches here
                    self.latency.note_window_fire(self.op.name, out.ts,
                                                  out.valid, now)
        if out is not None:
            if out.trace is None:
                # operator steps build fresh DeviceBatches; the trace lane
                # is host metadata, relayed here so one hook covers every
                # device operator (map/filter/reduce/stateful/windows)
                out.trace = batch.trace
            self.stats.outputs_sent += out.known_size or 0
            self.emitter.emit_device_batch(out)


class MapTPUReplica(_TPUReplica):
    pass


class MapTPU(Operator):
    """Stateless elementwise transform on device (reference stateless
    ``Map_GPU``, ``map_gpu.hpp:60-76,104-433``).

    ``fn`` maps one record pytree to one record pytree.  With
    ``batch_fn=True``, ``fn`` instead receives the whole SoA payload (leading
    dim = capacity) and the validity mask — the escape hatch for
    batch-granular math (the reference has no equivalent; CUDA kernels are
    always per-item)."""

    replica_class = MapTPUReplica

    def __init__(self, fn: Callable, name: str = "map_tpu",
                 parallelism: int = 1, batch_fn: bool = False,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing, is_tpu=True,
                         key_extractor=key_extractor)
        self.fn = fn
        self.batch_fn = batch_fn

        def step(payload, valid):
            if self.batch_fn:
                return self.fn(payload, valid)
            return jax.vmap(self.fn)(payload)

        self._jit_step = wf_jit(step, op_name=name)

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        out_payload = self._jit_step(batch.payload, batch.valid)
        # keys lane deliberately not forwarded: it is edge-scoped metadata
        # (valid only for the extractor of the edge that attached it), and a
        # map may rewrite the key field anyway.
        return DeviceBatch(out_payload, batch.ts, batch.valid,
                           watermark=batch.watermark, size=batch._size,
                           frontier=batch.frontier, ts_max=batch.ts_max,
                           ts_min=batch.ts_min)


class FilterTPUReplica(_TPUReplica):
    pass


class FilterTPU(Operator):
    """Device predicate filter (reference ``Filter_GPU``): survivors are
    expressed as a validity-mask intersection rather than a compaction —
    downstream operators and the TPU→host boundary are mask-aware, so the
    copy the reference pays on the GPU is avoided entirely."""

    replica_class = FilterTPUReplica

    def __init__(self, fn: Callable, name: str = "filter_tpu",
                 parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing, is_tpu=True,
                         key_extractor=key_extractor)
        self.fn = fn

        def step(payload, valid):
            keep = jax.vmap(self.fn)(payload)
            return valid & keep

        self._jit_step = wf_jit(step, op_name=name)

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        new_valid = self._jit_step(batch.payload, batch.valid)
        return DeviceBatch(batch.payload, batch.ts, new_valid,
                           watermark=batch.watermark, frontier=batch.frontier,
                           size=None,  # survivor count unknown until observed
                           ts_max=batch.ts_max, ts_min=batch.ts_min)


def _segmented_reduce(keys, payload, ts, valid, comb, capacity):
    """Sorted segmented reduce: the XLA expression of the reference's
    ``Extract_Keys_Kernel`` → ``thrust::sort_by_key`` → ``thrust::reduce_by_key``
    pipeline (``reduce_gpu.hpp:227-258``).

    Invalid lanes get a sentinel sort key so they sort behind every real
    segment; the sort lane is int64 so the sentinel lies OUTSIDE the int32
    key space (an actual key of INT32_MAX must not be mistaken for padding
    and dropped).  Returns (distinct_keys, combined_payload, seg_ts,
    out_valid) with the distinct-key results left-compacted to the front of
    the batch."""
    sentinel = jnp.int64(1) << 32
    skeys = jnp.where(valid, keys.astype(jnp.int64), sentinel)
    order = jnp.argsort(skeys)
    skeys = skeys[order]
    spayload = jax.tree.map(lambda a: a[order], payload)
    sts = ts[order]

    starts = jnp.concatenate([jnp.array([True]), skeys[1:] != skeys[:-1]])

    def op(a, b):
        # Segmented-scan monoid: if b opens a new segment, the running value
        # resets to b; otherwise it folds comb(a, b).
        fa, pa, ta = a
        fb, pb, tb = b
        combined = comb(pa, pb)
        p = jax.tree.map(
            lambda c, vb: jnp.where(_bshape(fb, c), vb, c), combined, pb)
        t = jnp.where(fb, tb, jnp.maximum(ta, tb))
        return (fa | fb, p, t)

    _, scanned_payload, scanned_ts = jax.lax.associative_scan(
        op, (starts, spayload, sts))

    # segment ends = positions where the next key differs
    ends = jnp.concatenate([skeys[:-1] != skeys[1:], jnp.array([True])])
    ends = ends & (skeys != sentinel)
    # compact segment results to the front
    dest = jnp.cumsum(ends) - 1
    n_out = ends.sum()
    scatter_idx = jnp.where(ends, dest, capacity - 1)

    def compact(a):
        out = jnp.zeros((capacity,) + a.shape[1:], a.dtype)
        out = out.at[scatter_idx].set(
            jnp.where(_bshape(ends, a), a, jnp.zeros_like(a)))
        return out

    out_payload = jax.tree.map(compact, scanned_payload)
    out_keys = compact(skeys)
    out_ts = compact(scanned_ts)
    out_valid = jnp.arange(capacity) < n_out
    return out_keys, out_payload, out_ts, out_valid


def _bshape(mask, ref):
    """Broadcast a [B] bool mask against a [B, ...] leaf."""
    return mask.reshape(mask.shape + (1,) * (ref.ndim - 1))


class ReduceTPUReplica(_TPUReplica):
    pass


class ReduceTPU(Operator):
    """Per-batch associative reduce on device (reference ``Reduce_GPU``,
    ``reduce_gpu.hpp:107-315``): keyed batches shrink to one combined record
    per distinct key; non-keyed batches to a single record.  ``comb`` must be
    associative (the reference requires the same for Thrust).  Cross-batch
    rolling aggregation is the job of windows, exactly as in the reference
    where ``Reduce_GPU`` is also per-batch.

    The key extractor of a keyed TPU operator must be JAX-traceable and
    return an integer: keys are extracted *inside* the compiled program
    (reference: ``Extract_Keys_Kernel`` runs on device too,
    ``reduce_gpu.hpp:227``), so the extraction fuses with the sort/scan and
    works identically whether the batch arrived from a host staging edge or a
    TPU→TPU edge."""

    replica_class = ReduceTPUReplica

    def __init__(self, comb: Callable[[Any, Any], Any],
                 name: str = "reduce_tpu", parallelism: int = 1,
                 key_extractor=None, max_keys: Optional[int] = None,
                 sum_like: bool = False,
                 monoid: Optional[str] = None) -> None:
        routing = RoutingMode.KEYBY if key_extractor is not None \
            else RoutingMode.FORWARD
        super().__init__(name, parallelism, routing=routing, is_tpu=True,
                         key_extractor=key_extractor)
        self.comb = comb
        # Bound of the dense key space [0, max_keys) for the dense
        # tables: required on the mesh (cross-chip partials), optional on
        # a single chip where an UNDECLARED reduce sorts arbitrary int32
        # keys.  A declared monoid ("sum" | "max" | "min"; legacy
        # sum_like=True means "sum") lets the cross-chip combine ride one
        # reduce collective (psum/pmax/pmin) instead of all_gather +
        # fold, and — together with max_keys — replaces the single-chip
        # sort/scan with one scatter-combine pass (_get_dense_step).
        self.max_keys = max_keys
        from windflow_tpu.windows.ffat_kernels import resolve_monoid
        try:
            self.monoid = resolve_monoid(sum_like, monoid)
        except ValueError as e:
            raise WindFlowError(str(e)) from None
        self._jit_steps = {}
        # dense-key variant (withMaxKeys): the cross-chip partial tables
        # are compiled for one batch capacity — build-time capacity check
        if max_keys is not None:
            self.fixed_capacity_label = "ReduceTPU[withMaxKeys]"
        # device scalar accumulating dense-table key drops — mesh path
        # and the single-chip declared-monoid path alike (tuples whose
        # key falls outside [0, max_keys) cannot live in the dense
        # tables); read lazily at stats time, never on the step path
        self._mesh_dropped = None
        # one-time drop warning for the single-chip dense path (ADVICE
        # r5): adding withMaxKeys + withMonoidCombiner for speed silently
        # switches semantics from the sorted path (keeps arbitrary int32
        # keys) to the dense-table contract (out-of-range keys dropped) —
        # surface the first observed drop loudly.  The cadence check reads
        # a device scalar enqueued 64 steps earlier (same lazy-read trick
        # as the FFAT regrow checkpoint), so the hot path never syncs.
        # RETIRED under key compaction (PR 11): the compacted step routes
        # out-of-range keys to the overflow/sorted lane instead of
        # dropping them, so this path only exists for the
        # WF_TPU_KEY_COMPACTION=0 kill switch.
        self._drop_warned = False
        self._drop_steps = 0
        self._pending_drop = None
        # device-side key compaction (parallel/compaction.py): the
        # accumulated hit/miss/candidate state threaded through the
        # compacted step as one donated operand; _compactor itself is
        # attached by the graph build (None = one check per batch)
        self._cstats = None

    def enable_compaction(self, comp) -> None:
        """Attach a KeyCompactor (graph build, Config.key_compaction):
        declared-monoid reduces over UNDECLARED int32 key spaces run the
        dense scatter-combine path through the remap table, with the
        cold tail on the sorted lane of the same program; declared
        ``withMaxKeys`` reduces reroute out-of-range keys to that lane
        instead of dropping them."""
        self._compactor = comp
        comp.register_device_stats(lambda: self._cstats)

    def _get_step(self, capacity: int, probe_batch=None):
        step = self._jit_steps.get(capacity)
        if step is None:
            comb = self.comb
            key_fn = self.key_extractor
            prelude = self._fused_prelude

            def step(keys, payload, ts, valid):
                if prelude is not None:
                    # whole-chain fusion (windflow_tpu/fusion): the
                    # stateless members run inside this same program.
                    # Any edge-attached keys describe the PRE-chain
                    # records — extraction must rerun on the chain's
                    # output, below, in-program.
                    payload, valid = prelude(payload, valid)
                    keys = None
                if keys is None:
                    if key_fn is not None:
                        keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
                    else:
                        # Non-keyed: one global segment (thrust::reduce path).
                        keys = jnp.zeros(capacity, dtype=jnp.int32)
                return _segmented_reduce(keys, payload, ts, valid, comb,
                                         capacity)

            # staged-fed fused chain: the sorted reduce's outputs are
            # capacity-shaped like its inputs, so donating the (provably
            # unshared — fusion/executor.input_donation_safe) batch
            # lanes lets XLA write them in place — provided the prelude
            # preserves each lane's spec (donation_aliases_cleanly on
            # the first batch's shapes); the dense path's [K] tables
            # alias nothing and stay non-donated
            donate = ()
            if self._fused_donate_inputs and probe_batch is not None:
                from windflow_tpu.fusion.executor import \
                    donation_aliases_cleanly
                if donation_aliases_cleanly(
                        lambda p, t, v: step(None, p, t, v),
                        probe_batch.payload, probe_batch.ts,
                        probe_batch.valid):
                    donate = (1, 2, 3)
            step = wf_jit(step, op_name=self._fused_name or self.name,
                          donate_argnums=donate)
            self._jit_steps[capacity] = step
        return step

    def _get_dense_step(self, capacity: int):
        """Single-chip declared-monoid fast path (requires ``withMaxKeys``
        + ``withMonoidCombiner``): ONE scatter-combine pass builds the
        dense ``[K]`` distinct-key table — no sort, no segmented scan —
        exactly the per-chip half of the mesh path
        (parallel/mesh._dense_keyed_partial) without the collective.  The
        reference pays ``thrust::sort_by_key`` + ``reduce_by_key`` for
        every combiner (``reduce_gpu.hpp:227-258``); a declared monoid
        makes the grouping unnecessary.  Out-of-range keys cannot live in
        the dense table: they are dropped and counted, the same
        ``withMaxKeys`` key-space contract the mesh path enforces
        (single-chip UNDECLARED reduces still sort arbitrary int32
        keys)."""
        step = self._jit_steps.get(("dense", capacity))
        if step is None:
            from windflow_tpu.kernels import resolve_pallas_for
            from windflow_tpu.windows.ffat_kernels import (_monoid_identity,
                                                           _monoid_scatter)
            # non-keyed: one global segment, K=1 (the mesh contract,
            # _get_sharded_step) — not a max_keys-lane batch with one row
            K = self.max_keys if self.key_extractor is not None else 1
            monoid = self.monoid
            key_fn = self.key_extractor
            prelude = self._fused_prelude
            # Pallas segmented reduce (windflow_tpu/kernels): the dense
            # slot tables build in one tiled masked-fold kernel traced
            # into this same program; leaves outside the kernel's
            # shape/dtype gates keep the lax scatter (per-leaf routing
            # — values identical either way)
            pallas = resolve_pallas_for(self)

            def step(keys, payload, ts, valid):
                if prelude is not None:
                    # fused chain: see _get_step — the prelude runs here
                    # and keys re-extract from its output
                    payload, valid = prelude(payload, valid)
                    keys = None
                if keys is None:
                    keys = jax.vmap(key_fn)(payload).astype(jnp.int32) \
                        if key_fn is not None \
                        else jnp.zeros(capacity, jnp.int32)
                in_range = (keys >= 0) & (keys < K)
                ok = valid & in_range
                n_drop = jnp.sum(valid & ~in_range, dtype=jnp.int64)
                row = jnp.where(ok, keys, K)

                def scat(leaf):
                    ident = _monoid_identity(monoid, leaf.dtype)
                    buf = jnp.full((K + 1,) + leaf.shape[1:], ident,
                                   leaf.dtype)
                    return _monoid_scatter(buf.at[row], monoid)(
                        jnp.where(_bshape(ok, leaf), leaf, ident))[:K]

                def lax_ts():
                    return jnp.full(K + 1, -1, jnp.int64).at[row].max(
                        jnp.where(ok, ts, jnp.int64(-1)))[:K]

                routed = None
                if pallas is not None:
                    from windflow_tpu import kernels as pk
                    routed = pk.routed_monoid_tables(
                        row, payload, monoid, K, pallas.interpret,
                        lax_leaf=scat, ts=ts, ts_init=-1,
                        lax_ts=lax_ts, want_count=True)
                if routed is not None:
                    table, ts_t, cnt = routed
                    has = cnt > 0
                else:
                    table = jax.tree.map(scat, payload)
                    ts_t = lax_ts()
                    has = jnp.zeros(K + 1, bool).at[row].set(True)[:K]
                return table, ts_t, has, n_drop

            step = wf_jit(step,
                          op_name=f"{self._fused_name or self.name}.dense")
            self._jit_steps[("dense", capacity)] = step
        return step

    def _get_compacted_step(self, capacity: int):
        """Compacted keyed reduce (parallel/compaction.py): remapped hot
        keys scatter-combine into the dense slot table, the cold tail
        runs the sorted lane, and the rank-merged output is bit-identical
        to the sorted path's — one program, zero extra dispatches.  Also
        the declared-``withMaxKeys`` variant (``bounded``): the identity
        remap plus the overflow lane that retires the PR 1 silent-drop
        path."""
        step = self._jit_steps.get(("compact", capacity))
        if step is None:
            from windflow_tpu.kernels import resolve_pallas_for
            from windflow_tpu.parallel import compaction
            bounded = self.max_keys is not None
            step = compaction.make_compacted_reduce(
                capacity,
                self.max_keys if bounded else self._compactor.slots,
                self.monoid, self.comb, self.key_extractor,
                self._fused_prelude, bounded,
                pallas=resolve_pallas_for(self))
            # the donated operand is the cstats state (last arg); the
            # remap tables are read-only operands shared across steps
            donate = (4,) if bounded else (6,)
            step = wf_jit(step,
                          op_name=f"{self._fused_name or self.name}"
                                  ".compact",
                          donate_argnums=donate)
            self._jit_steps[("compact", capacity)] = step
        return step

    def _get_sharded_step(self, capacity: int):
        step = self._jit_steps.get(("mesh", capacity))
        if step is None:
            from windflow_tpu.parallel.mesh import (
                make_sharded_reduce_arbitrary, make_sharded_reduce_step)
            K = self.max_keys if self.key_extractor is not None else 1
            if K is None:
                # Arbitrary int32 keys: hash-shard lanes to their owner
                # chip with one all_to_all, then per-chip sort/reduce — no
                # dense table bound, nothing dropped (reference
                # reduce_gpu.hpp:227-258 arbitrary-key path).  withMaxKeys
                # remains the faster dense/psum variant for bounded keys.
                step = make_sharded_reduce_arbitrary(
                    self.mesh, capacity, self.comb, self.key_extractor,
                    op_name=f"{self.name}.mesh",
                    # key compaction (parallel/compaction.py): the remap
                    # overrides the owner hash per slot — hot keys
                    # balanced over chips; built before the first batch,
                    # so the cache key needs no variant tag
                    remap=self._compactor is not None)
            else:
                # key-aligned ingest (mesh.mark_aligned_ingest): host
                # pre-placed lanes let each key shard build only its
                # own table rows — the cross-chip table collective
                # disappears (parallel/mesh.py)
                step = make_sharded_reduce_step(
                    self.mesh, capacity, K, self.comb, self.key_extractor,
                    monoid=self.monoid,
                    ingest=getattr(self, "_ingest_mode", None) or "data",
                    op_name=f"{self.name}.mesh")
            self._jit_steps[("mesh", capacity)] = step
        return step

    def key_space(self) -> Optional[int]:
        # keys-lane plumbing for the shard ledger: the dense-table
        # contract bounds the key space exactly where routing/state do
        return self.max_keys if self.key_extractor is not None else None

    def num_dropped_tuples(self) -> int:
        if self._mesh_dropped is None:
            return 0
        return int(self._mesh_dropped)  # one device sync, diagnostics only

    # -- durable state (windflow_tpu/durability) -----------------------------
    # ReduceTPU's dense tables are rebuilt per batch (per-batch reduce
    # semantics — cross-batch aggregation is the windows' job), so the
    # only state worth a checkpoint is the accumulated drop counter the
    # stats layer reports.
    def snapshot_state(self):
        blob = {"kind": "reduce_tpu"}
        if self._mesh_dropped is not None:
            blob["dropped"] = int(self._mesh_dropped)
        if self._compactor is not None:
            # the remap table is operator state: a replay must rebuild
            # the same key→slot assignment so hit/miss partitioning (and
            # with it every device counter) evolves identically
            blob["compactor"] = self._compactor.snapshot()
        return blob if len(blob) > 1 else None

    def restore_state(self, blob):
        if "dropped" in blob:
            self._mesh_dropped = jnp.asarray(blob["dropped"], jnp.int64)
        if blob.get("compactor") is not None \
                and self._compactor is not None:
            self._compactor.restore(blob["compactor"])

    def _maybe_warn_drops(self, n_drop: int) -> None:
        """One-time RuntimeWarning the first time the single-chip dense
        path (withMaxKeys + withMonoidCombiner) is SEEN dropping
        out-of-range keys; also noted in dump_stats, mirroring how the
        other silent-drop paths surface through the stats layer."""
        if self._drop_warned or n_drop <= 0 or self.mesh is not None:
            return
        self._drop_warned = True
        import warnings
        warnings.warn(
            f"ReduceTPU '{self.name}': withMaxKeys({self.max_keys}) + "
            "withMonoidCombiner uses the dense-table contract — "
            f"{n_drop} tuple(s) with out-of-range keys (outside "
            f"[0, {self.max_keys})) were dropped and counted in "
            "Out_of_range_keys_dropped; the undeclared sorted path keeps "
            "arbitrary int32 keys", RuntimeWarning, stacklevel=3)

    def dump_stats(self) -> dict:
        st = super().dump_stats()
        comp = self._compactor
        if comp is not None:
            summary = comp.summary()
            st["Key_compaction"] = summary
            if comp.bounded and summary["overflow_tuples"]:
                # compaction absorbed the PR 1 dense-path key drop: keys
                # outside [0, max_keys) were REROUTED to the sorted
                # overflow lane (kept, not dropped) and counted here
                st["Out_of_range_keys_rerouted"] = \
                    summary["overflow_tuples"]
        if self._mesh_dropped is not None:
            dropped = self.num_dropped_tuples()
            st["Out_of_range_keys_dropped"] = dropped
            self._maybe_warn_drops(dropped)
            if self._drop_warned:
                st["Out_of_range_keys_note"] = (
                    "dense-table contract (withMaxKeys + "
                    "withMonoidCombiner): keys outside [0, max_keys) are "
                    "dropped; the undeclared sorted path keeps arbitrary "
                    "int32 keys")
        return st

    def _check_comb_contract(self, payload) -> None:
        """The combiner must return the full record structure — one that
        drops, renames, or restructures fields (e.g. forgets a carried
        'ts' column) cannot fold records associatively.  Checked here, at
        the first batch, so every execution path (single-chip sort/scan,
        mesh dense tables, mesh arbitrary-key all_to_all) gets the clear
        message instead of an opaque pytree mismatch from inside a scan."""
        one = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), payload)
        out_struct = jax.eval_shape(self.comb, one, one)
        if jax.tree.structure(out_struct) != jax.tree.structure(one):
            if isinstance(one, dict) and isinstance(out_struct, dict) \
                    and sorted(one.keys()) != sorted(out_struct.keys()):
                want, got = sorted(one.keys()), sorted(out_struct.keys())
            else:  # same field names but nested shape differs: treedefs
                want = jax.tree.structure(one)
                got = jax.tree.structure(out_struct)
            raise WindFlowError(
                "ReduceTPU combiner must return the same record structure "
                f"as its inputs (records have {want}, combiner returned "
                f"{got}); carry every field through the combine")
        # Same structure is not enough: a leaf whose shape or dtype drifts
        # (a combiner summing over an axis, or promoting f32→f64) fails
        # later inside the scan with the same opaque mismatch.
        # tree_util spelling: jax.tree.flatten_with_path only exists on
        # jax >= 0.5 and this must run on the 0.4.x floor too
        in_leaves, _ = jax.tree_util.tree_flatten_with_path(one)
        out_leaves = jax.tree.leaves(out_struct)
        for (path, a), b in zip(in_leaves, out_leaves):
            if a.shape != b.shape or a.dtype != b.dtype:
                raise WindFlowError(
                    "ReduceTPU combiner must preserve each field's shape "
                    f"and dtype: field {jax.tree_util.keystr(path) or '.'} "
                    f"is {a.shape}/{a.dtype} in the records but the "
                    f"combiner returned {b.shape}/{b.dtype}")

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        if not self._jit_steps:
            payload = batch.payload
            if self._fused_prelude is not None:
                # fused chain: the combiner folds the chain's OUTPUT
                # records — contract-check against the post-prelude spec
                # (abstract eval, zero device work)
                from windflow_tpu.fusion.executor import prelude_out_spec
                payload = prelude_out_spec(self._fused_prelude,
                                           batch.payload, batch.valid)
            self._check_comb_contract(payload)
        comp = self._compactor
        if self.mesh is not None:
            # Sharded variant: dense per-chip partials combined over ICI;
            # output is a capacity-max_keys batch of distinct-key records.
            step = self._get_sharded_step(batch.capacity)
            if comp is not None and self.max_keys is None:
                # arbitrary-key mesh reduce with a remap: the owner hash
                # is overridden per slot (hot keys balanced over chips)
                comp.on_batch()
                tk, tsl = comp.tables()
                table, ts_out, has, n_drop = step(
                    batch.payload, batch.ts, batch.valid, tk, tsl)
            else:
                table, ts_out, has, n_drop = step(
                    batch.payload, batch.ts, batch.valid)
            self._mesh_dropped = n_drop if self._mesh_dropped is None \
                else self._mesh_dropped + n_drop
            return DeviceBatch(table, ts_out, has,
                               watermark=batch.watermark, size=None,
                               frontier=batch.frontier)
        if comp is not None and self.monoid is not None \
                and self.key_extractor is not None:
            # compacted path (parallel/compaction.py): dense slots for
            # the remapped hot keys + the sorted lane for the cold tail,
            # in ONE program whose output matches the sorted path
            # record-for-record
            from windflow_tpu.parallel import compaction
            comp.on_batch()
            if self._cstats is None:
                self._cstats = compaction.cstats_init()
            step = self._get_compacted_step(batch.capacity)
            if comp.bounded:
                out_payload, out_ts, out_valid, self._cstats = step(
                    batch.keys, batch.payload, batch.ts, batch.valid,
                    self._cstats)
            else:
                tk, tsl = comp.tables()
                out_payload, out_ts, out_valid, self._cstats = step(
                    batch.keys, batch.payload, batch.ts, batch.valid,
                    tk, tsl, self._cstats)
            return DeviceBatch(out_payload, out_ts, out_valid,
                               watermark=batch.watermark, size=None,
                               frontier=batch.frontier)
        if self.monoid is not None and self.max_keys is not None:
            # declared-monoid dense table: same output contract as the
            # mesh branch (capacity-max_keys batch of distinct-key
            # records in ascending key order — the order the sorted path
            # also emits)
            table, ts_out, has, n_drop = self._get_dense_step(
                batch.capacity)(batch.keys, batch.payload,
                                batch.ts, batch.valid)
            self._mesh_dropped = n_drop if self._mesh_dropped is None \
                else self._mesh_dropped + n_drop
            # lazy drop check on a 64-step cadence: inspects the counter
            # enqueued one cadence AGO (long executed — no sync stall)
            self._drop_steps += 1
            if not self._drop_warned and self._drop_steps % 64 == 0:
                prev = self._pending_drop
                self._pending_drop = self._mesh_dropped
                if prev is not None:
                    self._maybe_warn_drops(int(prev))
            return DeviceBatch(table, ts_out, has,
                               watermark=batch.watermark, size=None,
                               frontier=batch.frontier)
        out_keys, out_payload, out_ts, out_valid = \
            self._get_step(batch.capacity, batch)(batch.keys,
                                                  batch.payload,
                                                  batch.ts, batch.valid)
        return DeviceBatch(out_payload, out_ts, out_valid,
                           watermark=batch.watermark, size=None,
                           frontier=batch.frontier)
