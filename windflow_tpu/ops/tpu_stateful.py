"""Stateful keyed TPU operators: per-key mutable state on device.

Re-design of the reference's stateful GPU paths:

* ``Map_GPU`` stateful kernel — one CUDA worker per distinct key walks the
  batch's per-key index chain applying ``fn(tuple, state)`` in arrival order
  (``map_gpu.hpp:78-102``); state lives in a shared
  ``tbb::concurrent_unordered_map<key, wrapper_state_t>`` guarded by a
  spinlock that serializes stateful kernels across replicas
  (``map_gpu.hpp:114-115,278-295``).
* ``Filter_GPU`` stateful kernel — same walk, predicate + state update
  (``filter_gpu.hpp:119``).

TPU mapping (SURVEY.md §7 "hard parts": dense key-slot tables, host-managed
key→slot assignment):

1. **Key→slot interning on host.**  The state table is a dense pytree of
   ``[num_key_slots, ...]`` device arrays.  Per batch, the distinct keys are
   pulled to host (a tiny D2H — the reference does exactly this with
   ``dist_keys_cpu``, ``keyby_emitter_gpu.hpp:519-583``) and interned into
   dense slot ids by a Python dict, replacing the reference's device-pointer
   hash map with index arithmetic XLA can compile.
2. **Rank-wavefront in-order apply.**  The reference's "one worker per key
   walks its chain" becomes: stable-sort lanes by slot, compute each lane's
   *rank* (occurrence index within its key), then loop rank = 0..max_rank.
   Each wavefront step applies ``vmap(fn)`` to every lane at that rank —
   lanes at the same rank hold **distinct keys by construction**, so the
   state gather/scatter is conflict-free and fully parallel.  The loop depth
   is the max per-key multiplicity in the batch (typically ≪ capacity), the
   TPU analogue of the CUDA chain-walk's depth.
3. **Shared state, serialized.**  The table lives on the *operator*, not the
   replica; the host driver dispatches batches one at a time, so cross-replica
   state access is serialized by construction — the role of the reference's
   spinlock.

Stateful function signatures (the in-place C++ references become returns):

* map: ``fn(record, state) -> (new_record, new_state)``
* filter: ``fn(record, state) -> (keep_bool, new_state)``
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from windflow_tpu.basic import RoutingMode, WindFlowError
from windflow_tpu.batch import DeviceBatch
from windflow_tpu.monitoring.jit_registry import wf_jit
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.tpu import _TPUReplica, _bshape
from windflow_tpu.parallel.emitters import KeyInterner
from windflow_tpu.utils.dtypes import cast_state_update as _cast_update
from windflow_tpu.windows.grouping import auto_order, invert_perm

_KEY_SENTINEL = np.int32(2**31 - 1)


def _broadcast_state(proto, num_slots: int):
    """Materialize the [S, ...] state table from one per-key prototype."""
    def rep(x):
        a = jnp.asarray(x)
        return jnp.repeat(a[None], num_slots, axis=0)
    return jax.tree.map(rep, proto)


def _wavefront_body(fn: Callable, capacity: int,
                    num_slots: int, is_filter: bool):
    """Per-batch program body: rank-wavefront stateful apply over resolved
    dense slot ids (``slots``; lanes with slot >= num_slots are ignored)."""

    def body_fn(state, payload, valid, slots):
        # Stable sort by slot: arrival order is preserved within each key —
        # the ordering guarantee of the reference's per-key chain walk.
        sort_key = jnp.where(valid & (slots < num_slots), slots,
                             jnp.int32(num_slots))
        order = auto_order(sort_key, num_slots + 1)
        s_slots = sort_key[order]
        s_valid = valid[order]
        s_payload = jax.tree.map(lambda a: a[order], payload)

        # rank[i] = occurrence index of lane i within its key segment
        idx = jnp.arange(capacity, dtype=jnp.int32)
        starts = jnp.concatenate(
            [jnp.ones(1, bool), s_slots[1:] != s_slots[:-1]])
        seg_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(starts, idx, jnp.int32(0)))
        rank = idx - seg_start
        max_rank = jnp.max(jnp.where(s_valid, rank, jnp.int32(0)))

        gather_slots = jnp.clip(s_slots, 0, num_slots - 1)

        # Each lane is applied exactly once (at its own rank), so fn always
        # reads the ORIGINAL sorted payload; results accumulate into a
        # separate output carry — whose pytree structure may differ from the
        # input's (a stateful map may add/drop record fields, unlike the
        # reference's in-place C++ tuples).
        if is_filter:
            out0 = jnp.ones(capacity, bool)
        else:
            res_shape, _ = jax.eval_shape(
                jax.vmap(fn), s_payload,
                jax.tree.map(lambda a: a[gather_slots], state))
            out0 = jax.tree.map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype), res_shape)

        def body(carry):
            r, st, out = carry
            mask = (rank == r) & s_valid
            cur = jax.tree.map(lambda a: a[gather_slots], st)
            res, new_st = jax.vmap(fn)(s_payload, cur)
            if is_filter:
                out = jnp.where(mask, res, out)
            else:
                out = jax.tree.map(
                    lambda o, v: jnp.where(_bshape(mask, o), v, o), out, res)
            # Conflict-free scatter: within one rank all slots are distinct.
            # Masked-out lanes scatter to index num_slots → dropped (XLA
            # drops out-of-bounds scatter updates under jit).
            scat = jnp.where(mask, s_slots, jnp.int32(num_slots))
            st = jax.tree.map(
                lambda a, u: a.at[scat].set(_cast_update(u, a.dtype),
                                            mode="drop"),
                st, new_st)
            return r + 1, st, out

        _, state, s_out = jax.lax.while_loop(
            lambda c: c[0] <= max_rank, body, (jnp.int32(0), state, out0))

        inv = invert_perm(order)
        if is_filter:
            new_valid = valid & s_out[inv]
            return state, payload, new_valid
        out_payload = jax.tree.map(lambda a: a[inv], s_out)
        return state, out_payload, valid

    return body_fn


def _assoc_body(lift: Callable, comb: Callable, project: Callable,
                capacity: int, num_slots: int, is_filter: bool):
    """Log-depth alternative to the wavefront for *associative* state
    updates (``state' = comb(state, lift(record))``): a segmented inclusive
    scan folds each key's contributions in arrival order, so a single-hot-key
    batch costs the same as a uniform one — the wavefront's depth equals the
    max per-key multiplicity, which degrades to ``capacity`` sequential
    sweeps under skew (reference has no analogue: its per-key CUDA chain
    walk is inherently sequential, ``map_gpu.hpp:78-102``).

    ``project(record, state_incl)`` sees the state *including* the record's
    own contribution (rolling-reduce semantics, like the reference's CPU
    ``Reduce`` emitting the updated state per input, ``reduce.hpp:58-176``);
    for filters it returns the keep bool."""

    def body_fn(state, payload, valid, slots):
        sort_key = jnp.where(valid & (slots < num_slots), slots,
                             jnp.int32(num_slots))
        order = auto_order(sort_key, num_slots + 1)
        s_slots = sort_key[order]
        s_valid = valid[order]
        s_payload = jax.tree.map(lambda a: a[order], payload)

        lifts = jax.vmap(lift)(s_payload)
        starts = jnp.concatenate(
            [jnp.ones(1, bool), s_slots[1:] != s_slots[:-1]])

        # segmented inclusive scan of contributions (invalid lanes are all
        # in the trailing sentinel segment, so no flags needed)
        def op(a, b):
            sa, va = a
            sb, vb = b
            combined = comb(va, vb)
            v = jax.tree.map(
                lambda c, x: jnp.where(_bshape(sb, c), x, c), combined, vb)
            return sa | sb, v

        _, prefix = jax.lax.associative_scan(op, (starts, lifts))

        gather_slots = jnp.clip(s_slots, 0, num_slots - 1)
        init = jax.tree.map(lambda a: a[gather_slots], state)
        state_incl = comb(init, prefix)

        s_out = jax.vmap(project)(s_payload, state_incl)

        # persist each segment's final state (segment-end lanes of real
        # slots; the sentinel segment is dropped by the OOB scatter)
        ends = jnp.concatenate([s_slots[:-1] != s_slots[1:],
                                jnp.ones(1, bool)])
        scat = jnp.where(ends & (s_slots < num_slots), s_slots,
                         jnp.int32(num_slots))
        state = jax.tree.map(
            lambda a, u: a.at[scat].set(_cast_update(u, a.dtype),
                                        mode="drop"),
            state, state_incl)

        inv = invert_perm(order)
        if is_filter:
            return state, payload, valid & s_out[inv]
        out_payload = jax.tree.map(lambda a: a[inv], s_out)
        return state, out_payload, valid

    return body_fn


class _StatefulTPUBase(Operator):
    """Shared machinery: state table + interner on the operator (shared by
    all replicas — reference shares one tbb map across replicas too)."""

    _is_filter = False

    @property
    def fixed_capacity_label(self):
        # slot-table programs (and their intern padding) are compiled for
        # one batch capacity; mixed capacities would silently retrace per
        # batch or fail inside the scan — reject the merge at build
        return type(self).__name__

    def __init__(self, fn: Callable, initial_state: Any, name: str,
                 parallelism: int, key_extractor: Callable,
                 num_key_slots: int = 4096, dense_keys: bool = False,
                 assoc: Optional[tuple] = None) -> None:
        if key_extractor is None:
            raise WindFlowError(
                f"stateful TPU operator '{name}' requires a key extractor "
                "(reference: stateful Map_GPU/Filter_GPU are keyed-only)")
        super().__init__(name, parallelism, routing=RoutingMode.KEYBY,
                         is_tpu=True, key_extractor=key_extractor)
        self.fn = fn
        self.num_key_slots = num_key_slots
        #: dense_keys: the extractor already returns slot ids in
        #: [0, num_key_slots) — skip host interning entirely, so the step is
        #: one fully-async device program with no per-batch D2H sync
        #: (out-of-range keys are masked invalid, like FfatWindowsTPU)
        self.dense_keys = dense_keys
        #: assoc: (lift, comb, project) declares the state update
        #: associative — the log-depth segmented-scan body replaces the
        #: wavefront (skew-proof); ``fn`` is ignored when set
        self.assoc = assoc
        self._state = _broadcast_state(initial_state, num_key_slots)
        self._interner = KeyInterner()
        self._extract = None
        self._steps = {}   # per-capacity program cache
        # device-side key compaction (parallel/compaction.py): when the
        # graph attaches a compactor (host-staged edges only), slots
        # resolve IN-PROGRAM through the remap table and the per-batch
        # D2H intern sync disappears; _cstats is the donated hit/miss
        # state threaded through that program
        self._cstats = None

    def enable_compaction(self, comp) -> None:
        """Attach a pinned KeyCompactor (graph build): the device-resident
        interner.  Keys are admitted host-side at the staging boundary
        (every key has a slot before its batch ships), the step resolves
        slots with one in-program searchsorted, and the table raises on
        overflow exactly like ``withNumKeySlots`` interning."""
        self._compactor = comp
        comp.register_device_stats(lambda: self._cstats)

    def _adopt_compactor_mapping(self) -> None:
        """Fallback after compactor deactivation (a speculative host
        observation failed): fold the remap's key→slot dict into the
        interner — slots were assigned contiguously in admission order,
        so the intern path continues indexing the same state rows."""
        comp, self._compactor = self._compactor, None
        self._interner._ids.update(comp.export_mapping())

    # -- host-managed key→slot assignment -----------------------------------
    def _intern(self, uniq: np.ndarray) -> np.ndarray:
        interner = self._interner
        slots = np.empty(len(uniq), np.int32)
        for i, k in enumerate(uniq):
            slots[i] = interner.intern(int(k))
        if len(interner) > self.num_key_slots:
            raise WindFlowError(
                f"operator '{self.name}': distinct keys exceed "
                f"num_key_slots={self.num_key_slots}; raise it via "
                "withNumKeySlots")
        return slots

    def _body(self, capacity: int):
        return self._body_factory()(capacity, self.num_key_slots)

    def _body_factory(self):
        """(capacity, num_slots) -> per-batch body; the mesh layer calls it
        with the per-shard slot count."""
        if self.assoc is not None:
            lift, comb, project = self.assoc
            return lambda cap, S: _assoc_body(lift, comb, project, cap, S,
                                              self._is_filter)
        return lambda cap, S: _wavefront_body(self.fn, cap, S,
                                              self._is_filter)

    def _get_sharded_step(self, capacity: int):
        step = self._steps.get(("mesh", capacity))
        if step is None:
            from windflow_tpu.parallel.mesh import (make_sharded_stateful_step,
                                                    state_sharding)
            step = make_sharded_stateful_step(
                self.mesh, capacity, self.num_key_slots,
                self._body_factory(), self.key_extractor, self.dense_keys,
                self._is_filter,
                # key-aligned ingest (mesh.mark_aligned_ingest): lanes
                # arrive pre-placed on their slot-owner's column — no
                # data-axis all_gather, no psum lane merge
                ingest=getattr(self, "_ingest_mode", None) or "data",
                op_name=f"{self.name}.mesh")
            # shard the state table along the key axis on first use
            self._state = jax.device_put(self._state,
                                         state_sharding(self.mesh))
            self._steps[("mesh", capacity)] = step
        return step

    def _get_step(self, capacity: int):
        step = self._steps.get(capacity)
        if step is None:
            body = self._body(capacity)
            key_fn = self.key_extractor
            S = self.num_key_slots
            prelude = self._fused_prelude
            if prelude is not None and not self.dense_keys:
                # the fusion planner only selects dense-key tails
                # (fusion/executor._tail_supported): interning reads
                # distinct keys to host BEFORE the step, which a fused
                # program cannot serve mid-chain
                raise WindFlowError(
                    f"stateful operator '{self.name}': whole-chain "
                    "fusion requires withDenseKeys")
            if self.dense_keys:
                # slot = key, resolved inside the one compiled program: the
                # whole step is async device work, no host round-trip
                def step(state, payload, valid, keys):
                    if prelude is not None:
                        # fused chain: the stateless members run inside
                        # this program; edge-attached keys describe the
                        # PRE-chain records — re-extract from its output
                        payload, valid = prelude(payload, valid)
                        keys = None
                    if keys is None:
                        keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
                    ok = valid & (keys >= 0) & (keys < S)
                    return body(state, payload, ok, keys)
            else:
                def step(state, payload, valid, keys, uniq_keys, uniq_slots):
                    pos = jnp.clip(jnp.searchsorted(uniq_keys, keys),
                                   0, capacity - 1)
                    return body(state, payload, valid, uniq_slots[pos])
            step = wf_jit(step, op_name=self._fused_name or self.name,
                          donate_argnums=(0,))
            self._steps[capacity] = step
        return step

    def _get_compact_step(self, capacity: int):
        """Compacted slot resolution (parallel/compaction.py): the remap
        tables ride the program as read-only operands and the whole step
        stays one fully-async dispatch — no per-batch intern sync.  Miss
        lanes (possible only for keys the host admission never saw) are
        masked invalid and counted, the dense-key out-of-range
        contract."""
        step = self._steps.get(("compact", capacity))
        if step is None:
            from windflow_tpu.parallel import compaction
            body = self._body(capacity)
            key_fn = self.key_extractor

            def step(state, payload, valid, keys, tk, tsl, cst):
                if keys is None:
                    keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
                slots, hit = compaction.lookup_slots(tk, tsl, keys, valid)
                cst = compaction.cstats_update(cst, keys, hit,
                                               valid & ~hit)
                st, out, ov = body(state, payload, hit, slots)
                return st, out, ov, cst

            step = wf_jit(step, op_name=self._fused_name or self.name,
                          donate_argnums=(0, 6))
            self._steps[("compact", capacity)] = step
        return step

    def key_space(self):
        # keys-lane plumbing for the shard ledger: dense extractors are
        # bounded by the slot table; interned key spaces are unbounded
        # (the intern map assigns slots in arrival order, so slot ids
        # say nothing about the user's key distribution)
        return self.num_key_slots if self.dense_keys else None

    # -- durable state (windflow_tpu/durability) -----------------------------
    def snapshot_state(self):
        """The dense ``[num_key_slots, ...]`` state table plus the host
        key→slot intern map (the two halves of per-key device state: the
        values AND where each key lives).  The table exists from
        construction, so this snapshots even before the first batch —
        restore then simply re-seeds the same initial table."""
        return {
            "kind": "stateful_tpu",
            "state": jax.tree.map(np.asarray, self._state),
            "interner": dict(self._interner._ids),
            # compacted runs: the remap IS the key→slot half of per-key
            # state — restored so replays index the same table rows
            "compactor": (self._compactor.snapshot()
                          if self._compactor is not None else None),
        }

    def restore_state(self, blob):
        if self.mesh is not None:
            # multi-chip restore: the slot table lives key-sharded (slot
            # ranges per chip) — re-place the host blob in that layout;
            # the table's logical content is shard-shape independent, so
            # a rescale restore needs nothing but this placement
            from windflow_tpu.parallel.mesh import state_sharding
            sh = state_sharding(self.mesh)
            self._state = jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), sh),
                blob["state"])
        else:
            self._state = jax.tree.map(jnp.asarray, blob["state"])
        self._interner._ids = dict(blob["interner"])
        cblob = blob.get("compactor")
        if cblob is not None and self._compactor is not None:
            self._compactor.restore(cblob)
        elif cblob is not None:
            # checkpoint taken under key compaction, restored with the
            # plane OFF: the remap's key→slot dict is the key half of
            # per-key state — fold it into the host interner so the
            # restored table rows keep meaning the same keys (slots
            # were assigned contiguously, the intern contract)
            self._interner._ids.update(
                {int(k): int(v) for k, v in cblob["key_slot"].items()})
        elif self._compactor is not None and self._interner._ids:
            # checkpoint taken WITHOUT compaction, restored with the
            # plane ON: the restored interner owns the state rows — a
            # fresh remap would assign CONFLICTING slots, so the
            # operator keeps the host-interning path
            self._compactor.deactivate()
            self._compactor = None

    def _stateful_step(self, batch: DeviceBatch):
        cap = batch.capacity
        if self.mesh is not None:
            return self._sharded_stateful_step(batch)
        if self.dense_keys:
            # no interning: dispatch stays fully asynchronous
            return self._get_step(cap)(self._state, batch.payload,
                                       batch.valid, batch.keys)
        comp = self._compactor
        if comp is not None:
            if not comp.active:
                # a speculative host observation path died: fall back to
                # interning, keeping the slots already assigned
                self._adopt_compactor_mapping()
            else:
                from windflow_tpu.parallel import compaction
                comp.on_batch()
                if self._cstats is None:
                    self._cstats = compaction.cstats_init()
                tk, tsl = comp.tables()
                st, out, ov, self._cstats = self._get_compact_step(cap)(
                    self._state, batch.payload, batch.valid, batch.keys,
                    tk, tsl, self._cstats)
                return st, out, ov
        keys_dev, uniq_keys_dev, uniq_slots_dev = self._intern_batch(batch)
        return self._get_step(cap)(self._state, batch.payload, batch.valid,
                                   keys_dev, uniq_keys_dev, uniq_slots_dev)

    def _intern_batch(self, batch: DeviceBatch):
        """Shared intern/pad block for the single-chip and mesh paths: keys
        are extracted once (reusing a keyby edge's attached key lane); the
        device array feeds the step and its host copy drives interning
        (tiny D2H — parity with the reference's dist_keys_cpu copy at the
        keyby boundary)."""
        cap = batch.capacity
        if self._extract is None:
            key_fn = self.key_extractor

            def extract(payload):
                return jax.vmap(key_fn)(payload).astype(jnp.int32)

            self._extract = wf_jit(extract,
                                   op_name=f"{self.name}.key_extract")
        keys_dev = batch.keys if batch.keys is not None \
            else self._extract(batch.payload)
        keys_np = np.asarray(keys_dev)
        valid_np = np.asarray(batch.valid)
        uniq = np.unique(keys_np[valid_np])
        uniq_slots = self._intern(uniq)
        pad = cap - len(uniq)
        uniq_keys_dev = jnp.asarray(
            np.concatenate([uniq.astype(np.int32),
                            np.full(pad, _KEY_SENTINEL, np.int32)]))
        uniq_slots_dev = jnp.asarray(
            np.concatenate([uniq_slots,
                            np.full(pad, self.num_key_slots, np.int32)]))
        return keys_dev, uniq_keys_dev, uniq_slots_dev

    def dump_stats(self) -> dict:
        st = super().dump_stats()
        if self._compactor is not None:
            st["Key_compaction"] = self._compactor.summary()
        return st

    def _sharded_stateful_step(self, batch: DeviceBatch):
        """Mesh path: key-sharded state table, data-sharded batch, one
        psum lane merge (parallel/mesh.py make_sharded_stateful_step)."""
        cap = batch.capacity
        step = self._get_sharded_step(cap)
        if self.dense_keys:
            dummy = self._steps.get(("mesh_dummy", cap))
            if dummy is None:
                dummy = jnp.zeros(cap, jnp.int32)
                self._steps[("mesh_dummy", cap)] = dummy
            return step(self._state, batch.payload, batch.valid, dummy,
                        dummy)
        _, uniq_keys_dev, uniq_slots_dev = self._intern_batch(batch)
        return step(self._state, batch.payload, batch.valid, uniq_keys_dev,
                    uniq_slots_dev)


class StatefulMapTPUReplica(_TPUReplica):
    pass


class StatefulMapTPU(_StatefulTPUBase):
    """Keyed stateful map on device (reference stateful ``Map_GPU``,
    ``map_gpu.hpp:78-102,104-433``): ``fn(record, state) -> (record, state)``
    applied to each key's tuples in arrival order."""

    replica_class = StatefulMapTPUReplica
    _is_filter = False

    def __init__(self, fn, initial_state, name: str = "map_tpu",
                 parallelism: int = 1, key_extractor=None,
                 num_key_slots: int = 4096, dense_keys: bool = False,
                 assoc=None) -> None:
        super().__init__(fn, initial_state, name, parallelism, key_extractor,
                         num_key_slots, dense_keys=dense_keys, assoc=assoc)

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        self._state, out_payload, valid = self._stateful_step(batch)
        # fused chains may filter inside the program: the input count no
        # longer bounds the survivors, so the size is observed lazily
        size = None if self._fused_prelude is not None else batch._size
        return DeviceBatch(out_payload, batch.ts, valid,
                           watermark=batch.watermark, size=size,
                           frontier=batch.frontier)


class StatefulFilterTPUReplica(_TPUReplica):
    pass


class StatefulFilterTPU(_StatefulTPUBase):
    """Keyed stateful filter on device (reference stateful ``Filter_GPU``,
    ``filter_gpu.hpp:119``): ``fn(record, state) -> (keep, state)``; dropped
    tuples leave the validity mask, state updates still apply in order."""

    replica_class = StatefulFilterTPUReplica
    _is_filter = True

    def __init__(self, fn, initial_state, name: str = "filter_tpu",
                 parallelism: int = 1, key_extractor=None,
                 num_key_slots: int = 4096, dense_keys: bool = False,
                 assoc=None) -> None:
        super().__init__(fn, initial_state, name, parallelism, key_extractor,
                         num_key_slots, dense_keys=dense_keys, assoc=assoc)

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        self._state, out_payload, valid = self._stateful_step(batch)
        return DeviceBatch(out_payload, batch.ts, valid,
                           watermark=batch.watermark, size=None,
                           frontier=batch.frontier)
