"""Operator and replica base classes.

Re-design of the reference's ``Basic_Operator`` / ``Basic_Replica``
(``/root/reference/wf/basic_operator.hpp:54-235,246-381``).  The structural
difference is the execution vehicle: a reference replica is a FastFlow node
with its own OS thread (``svc()`` called by the runtime); here a replica is a
plain object whose ``drain()`` is called by the host driver's cooperative
scheduler (graph/pipegraph.py).  On TPU the heavy lifting happens inside
compiled XLA programs, so dedicating host threads per replica buys nothing —
one dispatch loop keeps the chip fed (SURVEY.md §7 design stance).

End-of-stream follows the reference protocol (``eosnotify`` cascade,
``basic_operator.hpp:180-189``): an EOS punctuation per input channel; when
all channels have delivered EOS, the replica flushes operator state, flushes
its emitter, forwards EOS, and terminates.
"""

from __future__ import annotations

import copy
import threading
from collections import deque
from typing import Any, Callable, List, Optional

from windflow_tpu.analysis import debug_concurrency as _dbg
from windflow_tpu.basic import (ExecutionMode, RoutingMode, TimePolicy,
                                WindFlowError, current_time_usecs,
                                default_config)
from windflow_tpu.batch import DeviceBatch, HostBatch, Punctuation, WM_MAX, WM_NONE
from windflow_tpu.context import RuntimeContext
from windflow_tpu.monitoring import recorder as flightrec
from windflow_tpu.monitoring.stats import StatsRecord
from windflow_tpu.parallel.collectors import Collector
from windflow_tpu.parallel.emitters import Emitter


class Replica:
    """One logical replica of an operator (reference ``Basic_Replica``)."""

    #: replicas whose user function may mutate its input copy shared
    #: (multicast) tuples before processing (reference ``copyOnWrite``,
    #: ``map.hpp:57-215``)
    copy_on_shared = False

    #: lock discipline declaration enforced by tools/wf_lint.py (WF721):
    #: the in-transit device-batch counter mutates only under its lock
    #: (deliberately lock-free READS live in PipeGraph._backpressured —
    #: the discipline covers this class's own accesses)
    __lock_guards__ = {"_inflight_lock": ("inflight_device",)}

    def __init__(self, op: "Operator", index: int) -> None:
        self.op = op
        self.index = index
        self.context = RuntimeContext(op.parallelism, index, op.name)
        self.inbox: deque = deque()
        #: outstanding device batches in this inbox — the per-operator
        #: in-transit count the host driver throttles against (reference
        #: ``inTransit_counter``, ``recycling_gpu.hpp:88-126``).  Guarded
        #: by a lock: with the host worker pool several producer replicas
        #: may stage batches into this inbox concurrently (deque appends
        #: are atomic; the int += is not).
        self.inflight_device = 0
        self._inflight_lock = threading.Lock()
        self.collector: Optional[Collector] = None  # wired by the graph
        self.emitter: Optional[Emitter] = None      # wired by the graph
        self.config = default_config                # PipeGraph overrides
        self.num_channels = 0
        self._eos_channels = set()
        self.done = False
        self.current_wm = WM_NONE
        self._hooked_wm = WM_NONE   # last watermark passed to on_watermark
        self.stats = StatsRecord(operator_name=op.name, replica_index=index,
                                 is_tpu=op.is_tpu)
        #: flight-recorder span ring (monitoring/recorder.py), bound by
        #: PipeGraph._build when Config.flight_recorder is on; None leaves
        #: a single `is not None` check as the hot path's whole cost
        self.ring = None
        self._traced_seen = 0   # traced batches seen (device_done cadence)
        #: latency ledger (monitoring/latency_ledger.py), bound by
        #: PipeGraph._build on WINDOW replicas only when
        #: Config.latency_ledger is on; None leaves one `is not None`
        #: check at the sampled-sync site as the whole cost
        self.latency = None
        self.mode = ExecutionMode.DEFAULT
        self.time_policy = TimePolicy.INGRESS
        #: origin id of the input currently being processed (HostBatch.ids);
        #: one-to-one/one-to-many relays pass it to their emits so
        #: DETERMINISTIC ordering can break timestamp ties
        #: config-independently (reference Single_t id)
        self.cur_tid = None

    # -- wiring -------------------------------------------------------------
    def add_channel(self) -> int:
        ch = self.num_channels
        self.num_channels += 1
        return ch

    # -- runtime ------------------------------------------------------------
    def receive(self, channel: int, msg) -> None:
        self.inbox.append((channel, msg))
        if isinstance(msg, DeviceBatch):
            with self._inflight_lock:
                self.inflight_device += 1

    def drain(self, limit: int = 0) -> bool:
        """Process pending inbox messages (at most ``limit`` when > 0; the
        driver bounds per-sweep work so sibling replicas interleave fairly,
        approximating the reference's thread-parallel arrival order).
        Returns True if any progress was made."""
        if _dbg.ENABLED:
            # single-consumer contract: the driver/pool schedules at most
            # one drain per replica at a time (the sweep barrier); a
            # second thread draining concurrently is a scheduler race
            with _dbg.entry_guard(self, "Replica.drain"):
                return self._drain_impl(limit)
        return self._drain_impl(limit)

    def _drain_impl(self, limit: int) -> bool:
        progressed = False
        n = 0
        while self.inbox:
            if limit and n >= limit:
                break
            n += 1
            channel, msg = self.inbox.popleft()
            if isinstance(msg, DeviceBatch):
                with self._inflight_lock:
                    self.inflight_device -= 1
            progressed = True
            if isinstance(msg, Punctuation) and msg.is_eos:
                self._handle_channel_eos(channel)
                continue
            for ready in self.collector.on_message(channel, msg):
                self._dispatch(ready)
        return progressed

    def _handle_channel_eos(self, channel: int) -> None:
        if channel in self._eos_channels:
            return
        self._eos_channels.add(channel)
        for ready in self.collector.on_channel_eos(channel):
            self._dispatch(ready)
        if len(self._eos_channels) == self.num_channels:
            self._terminate()

    def _terminate(self) -> None:
        if self.done:
            return
        self.on_eos()
        if self.emitter is not None:
            self.emitter.flush(self.current_wm)
            self.emitter.propagate_punctuation(WM_MAX)
        cf = self.op.closing_func
        if cf is not None:
            # per-replica shutdown callback (reference closing_func run in
            # svc_end with the replica's RuntimeContext, map.hpp:79-81);
            # adapt() swallows the context for non-riched closers
            from windflow_tpu.meta import adapt
            adapt(cf, 0)(self.context)
        self.done = True
        self.stats.is_terminated = True

    def _dispatch(self, msg) -> None:
        if _dbg.ENABLED:
            # the stats sample bracket (start_sample enters a debug guard,
            # end_sample exits it) spans this whole method; an operator
            # raising mid-processing must not leave a stale guard entry
            # that would false-positive a later, unrelated access
            try:
                return self._dispatch_impl(msg)
            finally:
                _dbg.exit_(self.stats)
        return self._dispatch_impl(msg)

    def _dispatch_impl(self, msg) -> None:
        if isinstance(msg, Punctuation):
            self._advance_wm(msg.watermark)
            self._maybe_hook_wm()
            if self.emitter is not None:
                self.emitter.propagate_punctuation(self.current_wm)
            return
        # flight recorder (monitoring/recorder.py): span events for the
        # 1-in-N traced batch; untraced batches cost one attribute check
        tr = msg.trace if self.ring is not None else None
        if tr is not None:
            self.ring.record(tr[0], flightrec.COLLECTED,
                             current_time_usecs())
        self.stats.start_sample()
        if isinstance(msg, DeviceBatch):
            self._advance_wm(msg.watermark)
            self.stats.inputs_received += msg.known_size or 0
            self.process_device_batch(msg)
        else:
            assert isinstance(msg, HostBatch)
            self._advance_wm(msg.watermark)
            self.stats.inputs_received += len(msg)
            # Copy-on-write: a multicast batch is shared by sibling replicas;
            # an in-place-capable operator must mutate a private copy
            # (reference ``copyOnWrite``, ``map.hpp:57-215``).
            cow = msg.shared and self.copy_on_shared
            for item, ts, tid in zip(msg.items, msg.tss,
                                     msg.ids_or_nones()):
                if cow:
                    item = copy.deepcopy(item)
                self.cur_tid = tid
                self.context._set_context(ts, msg.watermark)
                self.process_single(item, ts, msg.watermark)
            self.cur_tid = None
        self._maybe_hook_wm()
        self.stats.end_sample()
        if tr is not None and self.op.is_terminal:
            # staged→sunk span closes at sink RECEIPT (a deferred columnar
            # sink converts later; its extra defer rides the bench's own
            # delivery-latency measurement, not this histogram)
            now = current_time_usecs()
            self.ring.record(tr[0], flightrec.SUNK, now)
            self.stats.e2e_hist.add(now - tr[1])

    def _maybe_hook_wm(self) -> None:
        # only invoke the (potentially O(open windows)) hook on a real advance
        if self.current_wm != self._hooked_wm:
            self._hooked_wm = self.current_wm
            self.on_watermark(self.current_wm)

    def _advance_wm(self, wm: int) -> None:
        if wm != WM_NONE and wm > self.current_wm:
            self.current_wm = wm

    # -- operator logic (overridden by concrete replicas) --------------------
    def process_single(self, item: Any, ts: int, wm: int) -> None:
        raise WindFlowError(
            f"operator '{self.op.name}' cannot consume host tuples")

    def process_device_batch(self, batch: DeviceBatch) -> None:
        raise WindFlowError(
            f"operator '{self.op.name}' cannot consume device batches; "
            "insert a host stage or mark the upstream edge for staging")

    def on_eos(self) -> None:
        """Flush hook: window firing, sink finalization, etc."""

    def on_watermark(self, wm: int) -> None:
        """Watermark-advance hook (fires time windows past the frontier)."""


class Operator:
    """Descriptor for one operator in the graph (reference
    ``Basic_Operator``): name, parallelism, input routing mode, output batch
    size, and whether its compute runs on TPU."""

    #: subclasses set this to their replica class
    replica_class = Replica
    #: terminal operators (sinks) have no emitter / downstream consumer
    is_terminal = False
    #: stable topological index assigned by PipeGraph._build; origin-id
    #: prefix for source stamping
    ordinal = 0
    #: per-replica shutdown callback, set by withClosingFunction (reference
    #: ``closing_func``: every operator builder accepts one); invoked at
    #: replica termination with the replica's RuntimeContext (arity 1) or
    #: no arguments (arity 0)
    closing_func = None
    #: host operators whose replicas may be drained concurrently by the
    #: host worker pool (Config.host_worker_threads); operators with
    #: cross-replica shared mutable state (e.g. a shared persistent DB
    #: handle) clear this to stay on the driver thread
    host_pool_safe = True
    #: non-None for device operators whose compiled state layout is tied to
    #: ONE batch capacity (FfatWindowsTPU pane state, stateful slot tables,
    #: dense-key mesh reduce tables): PipeGraph rejects merged upstream
    #: paths delivering unequal capacities at BUILD time (parity:
    #: ``multipipe.hpp:441-444`` rejects bad GPU predecessors at build).
    #: The value is the label used in the error message.
    fixed_capacity_label = None
    #: whole-chain fusion (windflow_tpu/fusion): non-None on the MEMBER
    #: operators of a fused segment — the name of the fused hop their
    #: execution folded into.  Member replicas are inert (wired with no
    #: channels, marked done at build); stats are attributed from the
    #: fused hop (fusion/executor.attribute_member_stats).
    _fused_into = None
    #: fused-segment HOST hooks: the stateless members' combined record
    #: transform, inlined at program-build time by stateful tails
    #: (ffat_tpu._build_step, ReduceTPU._get_step/_get_dense_step,
    #: tpu_stateful._get_step); the fused program's registry name; and
    #: whether the graph proved the input batch buffers unshared so the
    #: program may take them with donate_argnums
    #: (fusion/executor.input_donation_safe).
    _fused_prelude = None
    _fused_name = None
    _fused_donate_inputs = False
    #: all-stateless fused segments have no tail program to extend: the
    #: host op carries a FusedStatelessExec instead, dispatched through
    #: _TPUReplica._op_step (one attribute check per batch).
    _fusion_exec = None
    #: device-side key compaction (parallel/compaction.py): non-None on
    #: keyed consumers the graph build attached a KeyCompactor to —
    #: their step resolves arbitrary int32 keys to dense slots through
    #: the device-resident remap table.  None (Config.key_compaction
    #: off, or a non-qualifying consumer) leaves exactly one
    #: `is not None` check on the step path.
    _compactor = None

    def __init__(self, name: str, parallelism: int,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 output_batch_size: int = 0,
                 is_tpu: bool = False,
                 key_extractor: Optional[Callable] = None) -> None:
        if parallelism < 1:
            raise WindFlowError(
                f"operator '{name}' must have parallelism >= 1")
        self.name = name
        self.parallelism = parallelism
        self.routing = routing
        self.output_batch_size = output_batch_size
        self.is_tpu = is_tpu
        self.key_extractor = key_extractor
        self.replicas: List[Replica] = []
        #: jax Mesh for multi-chip execution; set by PipeGraph._build from
        #: Config.mesh.  Mesh-aware operators compile sharded programs when
        #: this is not None (parallel/mesh.py).
        self.mesh = None

    @property
    def is_keyed(self) -> bool:
        return self.routing == RoutingMode.KEYBY

    def build_replicas(self, mode: ExecutionMode,
                       time_policy: TimePolicy) -> List[Replica]:
        if self.is_tpu and mode != ExecutionMode.DEFAULT:
            # Parity: reference builders reject GPU operators outside DEFAULT
            # mode (SURVEY.md §2.5 structural invariants).
            raise WindFlowError(
                f"TPU operator '{self.name}' requires DEFAULT execution mode")
        self.replicas = [self.replica_class(self, i)
                        for i in range(self.parallelism)]
        for r in self.replicas:
            r.mode = mode
            r.time_policy = time_policy
        return self.replicas

    #: True on operators holding cross-batch state the durability plane
    #: cannot snapshot yet (host window engines, persistent-DB suites):
    #: a checkpoint of a graph containing one restores everything else
    #: and the pre-flight checker surfaces the gap as WF603
    checkpoint_opaque = False

    def snapshot_state(self) -> Optional[dict]:
        """Durable-state hook (windflow_tpu/durability): one picklable
        blob capturing ALL cross-batch state this operator owns (its
        replicas' included), taken at the quiesced checkpoint barrier.
        ``None`` means stateless — nothing written, nothing restored.
        Device arrays must come back as host numpy (the plane's only
        device sync, at checkpoint cadence)."""
        return None

    def restore_state(self, blob: dict) -> None:
        """Inverse of :meth:`snapshot_state`, applied to a freshly built
        (never-stepped) operator before the first source tick."""
        raise WindFlowError(
            f"operator '{self.name}' ({type(self).__name__}) cannot "
            "restore checkpoint state it never snapshots")

    def key_space(self) -> Optional[int]:
        """Declared dense key-space bound of a keyed operator (the
        ``withMaxKeys`` / dense ``withNumKeySlots`` contract), or None
        for arbitrary/interned key spaces.  The shard ledger
        (monitoring/shard_ledger.py) keys off this: a bounded space gets
        an EXACT per-key histogram (and, on a mesh, per-key-shard load
        from the ranges each chip owns); unbounded spaces fall back to
        the count-min sketch."""
        return None

    def num_dropped_tuples(self) -> int:
        """Tuples this operator dropped beyond collector-level drops (e.g.
        out-of-range keys on the mesh reduce, late tuples on TB windows);
        folded into PipeGraph.get_num_dropped_tuples."""
        return 0

    def dump_stats(self) -> dict:
        st = {
            "Operator_name": self.name,
            "Operator_type": type(self).__name__,
            "Parallelism": self.parallelism,
            "Replicas": [r.stats.to_json() for r in self.replicas],
        }
        if self._fused_into is not None:
            # whole-chain fusion: this operator's execution folded into
            # one fused program (the replica counters above are
            # attributed from that hop, not dispatched here)
            st["Fused_into"] = self._fused_into
        return st
