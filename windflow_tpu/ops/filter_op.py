"""Host Filter operator (reference ``/root/reference/wf/filter.hpp:57,245``):
drops tuples failing the predicate."""

from __future__ import annotations

from typing import Any, Callable

from windflow_tpu.basic import RoutingMode
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class FilterReplica(Replica):
    copy_on_shared = True  # user predicates may mutate the record

    def __init__(self, op: "Filter", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, 1)

    def process_single(self, item, ts, wm):
        if self._fn(item, self.context):
            self.stats.outputs_sent += 1
            self.emitter.emit(item, ts, wm, tid=self.cur_tid)


class Filter(Operator):
    replica_class = FilterReplica

    def __init__(self, fn: Callable[[Any], bool], name: str = "filter",
                 parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 output_batch_size: int = 0, key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.fn = fn
