"""Host Map operator (reference ``/root/reference/wf/map.hpp:57-215``).

Supports the reference's two functional styles: transforming (``fn(t) -> out``)
and in-place (``fn`` returns ``None``, mutating its argument), each optionally
"riched" with a RuntimeContext trailing parameter.
"""

from __future__ import annotations

from typing import Any, Callable

from windflow_tpu.basic import RoutingMode
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class MapReplica(Replica):
    copy_on_shared = True  # the in-place variant mutates its input

    def __init__(self, op: "Map", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, 1)

    def process_single(self, item, ts, wm):
        out = self._fn(item, self.context)
        if out is None:  # in-place variant: the (mutated) input moves on
            out = item
        self.stats.outputs_sent += 1
        self.emitter.emit(out, ts, wm, tid=self.cur_tid)


class Map(Operator):
    replica_class = MapReplica

    def __init__(self, fn: Callable[[Any], Any], name: str = "map",
                 parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 output_batch_size: int = 0, key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.fn = fn
