"""Host Reduce operator (reference ``/root/reference/wf/reduce.hpp:58-176``):
per-key rolling state, emitting the updated state for every input.  State for
unseen keys starts from ``initial_state`` (the reference default-constructs
``state_t``; here a value is shallow-copied or a zero-arg factory called).
Non-keyed Reduce folds everything into one state under the empty key
(reference ``empty_key_t``)."""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional

from windflow_tpu.basic import EMPTY_KEY, RoutingMode
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class ReduceReplica(Replica):
    def __init__(self, op: "Reduce", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, 2)
        self._states = {}

    def _new_state(self):
        init = self.op.initial_state
        return init() if callable(init) else copy.copy(init)

    def process_single(self, item, ts, wm):
        key = (self.op.key_extractor(item)
               if self.op.key_extractor is not None else EMPTY_KEY)
        state = self._states.get(key)
        if state is None:
            state = self._new_state()
        out = self._fn(item, state, self.context)
        if out is None:  # in-place mutation variant
            out = state
        self._states[key] = out
        self.stats.outputs_sent += 1
        self.emitter.emit(copy.copy(out), ts, wm,
                          tid=self.cur_tid)


class Reduce(Operator):
    replica_class = ReduceReplica

    # -- durable state (windflow_tpu/durability) -----------------------------
    def snapshot_state(self):
        """Per-replica rolling per-key state dicts (user state objects —
        must be picklable, same contract as the persistent suite's
        serializer defaults)."""
        if not self.replicas:
            return None
        return {"kind": "reduce_host",
                "replicas": [dict(r._states) for r in self.replicas]}

    def restore_state(self, blob):
        for rep, st in zip(self.replicas, blob["replicas"]):
            rep._states = dict(st)

    def __init__(self, fn: Callable[[Any, Any], Any], initial_state: Any,
                 name: str = "reduce", parallelism: int = 1,
                 key_extractor: Optional[Callable] = None,
                 output_batch_size: int = 0) -> None:
        routing = RoutingMode.KEYBY if key_extractor is not None \
            else RoutingMode.FORWARD
        if key_extractor is None and parallelism > 1:
            from windflow_tpu.basic import WindFlowError
            raise WindFlowError(
                "non-keyed Reduce requires parallelism == 1 (reference: "
                "keyless operators with state cannot be replicated)")
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.fn = fn
        self.initial_state = initial_state
