"""Host FlatMap operator (reference ``/root/reference/wf/flatmap.hpp:58,215``):
the user function emits 0..N outputs per input through a Shipper (reference
``shipper.hpp:58``).  Outputs inherit the input's timestamp, as in the
reference."""

from __future__ import annotations

from typing import Any, Callable

from windflow_tpu.basic import RoutingMode
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class Shipper:
    """Hands the user function a push interface (reference ``Shipper``)."""

    __slots__ = ("_replica", "_ts", "_wm", "pushed", "_exp")

    def __init__(self, replica: "FlatMapReplica") -> None:
        self._replica = replica
        self._ts = 0
        self._wm = 0
        self.pushed = 0
        self._exp = 0   # expansion index within the current input

    def push(self, item: Any) -> None:
        self.pushed += 1
        self._replica.stats.outputs_sent += 1
        # origin id = input id + expansion index: the k-th output of one
        # input orders after the (k-1)-th, config-independently (the
        # reference's flatmap outputs keep their input's id + FIFO order)
        tid = self._replica.cur_tid
        if tid is not None:
            tid = tid + (self._exp,)
            self._exp += 1
        self._replica.emitter.emit(item, self._ts, self._wm, tid=tid)


class FlatMapReplica(Replica):
    copy_on_shared = True  # user fn may mutate the record before shipping

    def __init__(self, op: "FlatMap", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, 2)
        self._shipper = Shipper(self)

    def process_single(self, item, ts, wm):
        self._shipper._ts = ts
        self._shipper._wm = wm
        self._shipper._exp = 0
        self._fn(item, self._shipper, self.context)


class FlatMap(Operator):
    replica_class = FlatMapReplica

    def __init__(self, fn: Callable[[Any, Shipper], None],
                 name: str = "flatmap", parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 output_batch_size: int = 0, key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.fn = fn
