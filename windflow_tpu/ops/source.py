"""Source operator (reference ``/root/reference/wf/source.hpp:55-309`` and the
``Source_Shipper`` at ``source_shipper.hpp:59-``).

The reference runs the user's generation function on a dedicated thread which
pushes tuples through a ``Source_Shipper`` (timestamp + watermark assignment).
Here a source replica is *pulled* by the host driver: the user supplies a
generator function returning an iterable, and each scheduler tick pulls a
bounded chunk so the pipeline stays balanced without threads.  Timestamping
follows the reference policies: INGRESS assigns arrival time, EVENT uses a
user timestamp extractor; watermarks are the monotone max of assigned
timestamps (``source_shipper.hpp`` behavior).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from windflow_tpu.basic import RoutingMode, TimePolicy, WindFlowError, \
    current_time_usecs
from windflow_tpu.batch import WM_NONE
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class SourceReplica(Replica):
    def __init__(self, op: "Source", index: int) -> None:
        super().__init__(op, index)
        self._iter = None
        self._last_ts = WM_NONE
        self._exhausted = False
        # A source has no input channels; the driver calls tick().

    def start(self) -> None:
        gen = adapt(self.op.gen_fn, 0)
        iterable = gen(self.context)
        if iterable is None:
            raise WindFlowError(
                f"source '{self.op.name}' generator returned None")
        self._iter = iter(iterable)

    def tick(self, max_items: int) -> bool:
        """Pull up to ``max_items`` tuples; returns False once exhausted."""
        if self._exhausted:
            return False
        assert self._iter is not None, "source not started"
        produced = 0
        while produced < max_items:
            try:
                item = next(self._iter)
            except StopIteration:
                self._exhausted = True
                self._terminate()
                return False
            ts = self._assign_ts(item)
            self._advance_wm(ts)
            self.stats.outputs_sent += 1
            self.emitter.emit(item, ts, self.current_wm)
            produced += 1
        return True

    def _assign_ts(self, item: Any) -> int:
        if self.time_policy == TimePolicy.EVENT:
            if self.op.ts_extractor is None:
                raise WindFlowError(
                    f"source '{self.op.name}': EVENT time policy requires a "
                    "timestamp extractor (with_timestamp_extractor)")
            ts = int(self.op.ts_extractor(item))
        else:
            ts = current_time_usecs()
            # Keep timestamps monotone per replica even if the clock stalls
            # within a microsecond.
            if ts <= self._last_ts:
                ts = self._last_ts + 1
        self._last_ts = max(self._last_ts, ts)
        return ts

    @property
    def exhausted(self) -> bool:
        return self._exhausted


class Source(Operator):
    replica_class = SourceReplica

    def __init__(self, gen_fn: Callable[..., Iterable], name: str = "source",
                 parallelism: int = 1, output_batch_size: int = 0,
                 ts_extractor: Optional[Callable[[Any], int]] = None) -> None:
        super().__init__(name, parallelism, routing=RoutingMode.NONE,
                         output_batch_size=output_batch_size)
        self.gen_fn = gen_fn
        self.ts_extractor = ts_extractor
