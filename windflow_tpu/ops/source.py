"""Source operator (reference ``/root/reference/wf/source.hpp:55-309`` and the
``Source_Shipper`` at ``source_shipper.hpp:59-``).

The reference runs the user's generation function on a dedicated thread which
pushes tuples through a ``Source_Shipper`` (timestamp + watermark assignment).
Here a source replica is *pulled* by the host driver: the user supplies a
generator function returning an iterable, and each scheduler tick pulls a
bounded chunk so the pipeline stays balanced without threads.  Timestamping
follows the reference policies: INGRESS assigns arrival time, EVENT uses a
user timestamp extractor; watermarks are the monotone max of assigned
timestamps (``source_shipper.hpp`` behavior).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from windflow_tpu.basic import RoutingMode, TimePolicy, WindFlowError, \
    current_time_usecs
from windflow_tpu.batch import WM_NONE
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class BaseSourceReplica(Replica):
    """Shared source-replica mechanics: monotone timestamps and the
    punctuation cadence (reference: emitters multicast watermark punctuations
    every WF_DEFAULT_WM_INTERVAL_USEC / WM_AMOUNT inputs, basic.hpp:189-206,
    forward_emitter.hpp:226-262)."""

    def __init__(self, op: Operator, index: int) -> None:
        super().__init__(op, index)
        self._tid_seq = 0          # origin-id sequence (HostBatch.ids)
        self._last_ts = WM_NONE
        self._exhausted = False
        self._since_punct = 0
        self._last_punct_usec = current_time_usecs()

    @property
    def exhausted(self) -> bool:
        return self._exhausted

    def maybe_punctuate(self, now_usec: Optional[int] = None) -> None:
        """Emit a watermark punctuation if the cadence interval elapsed — the
        mechanism that keeps time windows firing on a live-but-idle stream
        (reference ``forward_emitter.hpp:226-262``).  Called by the driver
        every sweep."""
        if self._exhausted:
            return
        now = now_usec if now_usec is not None else current_time_usecs()
        if now - self._last_punct_usec >= self.config.punctuation_interval_usec:
            self.punctuate(now)

    def punctuate(self, now_usec: Optional[int] = None) -> None:
        now = now_usec if now_usec is not None else current_time_usecs()
        if self.time_policy == TimePolicy.INGRESS:
            # Ingress watermarks may ride the wall clock: every future tuple
            # is stamped >= now, so `now` is a valid frontier even mid-idle.
            self._advance_wm(now)
            # keep future tuple timestamps ahead of the advertised frontier
            self._last_ts = max(self._last_ts, now)
        # EVENT time: the frontier is the max event timestamp seen; idle
        # cannot advance it (no oracle for future event times).
        if self.current_wm == WM_NONE:
            return
        self._since_punct = 0
        self._last_punct_usec = now
        self.emitter.propagate_punctuation(self.current_wm)

    def _count_toward_punctuation(self, n: int) -> None:
        amount = self.config.punctuation_amount
        if amount <= 0:
            return  # count trigger disabled (interval cadence still runs)
        self._since_punct += n
        if self._since_punct >= amount:
            self.punctuate()


class SourceReplica(BaseSourceReplica):
    def __init__(self, op: "Source", index: int) -> None:
        super().__init__(op, index)
        self._iter = None
        # A source has no input channels; the driver calls tick().

    def start(self) -> None:
        gen = adapt(self.op.gen_fn, 0)
        iterable = gen(self.context)
        if iterable is None:
            raise WindFlowError(
                f"source '{self.op.name}' generator returned None")
        self._iter = iter(iterable)

    def tick(self, max_items: int) -> bool:
        """Pull up to ``max_items`` tuples; returns True if any progress was
        made (tuples emitted, an idle yield consumed, or the stream
        terminated this call)."""
        if self._exhausted:
            return False
        assert self._iter is not None, "source not started"
        produced = 0
        while produced < max_items:
            try:
                item = next(self._iter)
            except StopIteration:
                self._exhausted = True
                self._terminate()
                return True
            if item is None:
                # Idle yield: the source is live but has nothing right now
                # (e.g. waiting on an external feed).  Give the scheduler the
                # sweep back; punctuation cadence keeps watermarks moving
                # (reference: Source_Shipper emits periodic watermarks on a
                # live-but-idle stream, forward_emitter.hpp:226-262).
                return True
            ts = self._assign_ts(item)
            self._advance_wm(ts)
            self.stats.outputs_sent += 1
            self._tid_seq += 1
            self.emitter.emit(item, ts, self.current_wm,
                              tid=(self.op.ordinal, self.index,
                                   self._tid_seq))
            produced += 1
            self._count_toward_punctuation(1)
        return produced > 0

    def _assign_ts(self, item: Any) -> int:
        if self.time_policy == TimePolicy.EVENT:
            if self.op.ts_extractor is None:
                raise WindFlowError(
                    f"source '{self.op.name}': EVENT time policy requires a "
                    "timestamp extractor (with_timestamp_extractor)")
            ts = int(self.op.ts_extractor(item))
        else:
            ts = current_time_usecs()
            # Keep timestamps monotone per replica even if the clock stalls
            # within a microsecond.
            if ts <= self._last_ts:
                ts = self._last_ts + 1
        self._last_ts = max(self._last_ts, ts)
        return ts


class Source(Operator):
    replica_class = SourceReplica

    def __init__(self, gen_fn: Callable[..., Iterable], name: str = "source",
                 parallelism: int = 1, output_batch_size: int = 0,
                 ts_extractor: Optional[Callable[[Any], int]] = None,
                 record_spec: Optional[Any] = None) -> None:
        super().__init__(name, parallelism, routing=RoutingMode.NONE,
                         output_batch_size=output_batch_size)
        self.gen_fn = gen_fn
        self.ts_extractor = ts_extractor
        #: abstract record declaration for the pre-flight checker
        #: (analysis/preflight.py): an example record, or a pytree of
        #: jax.ShapeDtypeStruct.  Purely static — never fed to gen_fn;
        #: None leaves downstream kernel checks skipped.
        self.record_spec = record_spec
