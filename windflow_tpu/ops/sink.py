"""Sink operator (reference ``/root/reference/wf/sink.hpp:56-``): terminal
consumer.  The user function receives each tuple, and ``None`` once at
end-of-stream (the reference passes an empty ``std::optional`` at EOS)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from windflow_tpu.basic import RoutingMode
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


class SinkReplica(Replica):
    def __init__(self, op: "Sink", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, 1)

    def process_single(self, item, ts, wm):
        self._fn(item, self.context)

    def process_device_batch(self, batch):
        # A sink fed directly by a TPU operator pulls the batch to host
        # (reference GPU→CPU boundary) and consumes per tuple.
        from windflow_tpu.batch import device_to_host
        hb = device_to_host(batch)
        self.stats.d2h_bytes += sum(
            getattr(l, "nbytes", 0) for l in _leaves(batch.payload))
        for item, ts in zip(hb.items, hb.tss):
            self.context._set_context(ts, batch.watermark)
            self._fn(item, self.context)

    def on_eos(self):
        self._fn(None, self.context)


def _leaves(tree):
    import jax
    return jax.tree.leaves(tree)


class Sink(Operator):
    replica_class = SinkReplica
    is_terminal = True

    def __init__(self, fn: Callable[[Optional[Any]], None], name: str = "sink",
                 parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing,
                         key_extractor=key_extractor)
        self.fn = fn
