"""Sink operator (reference ``/root/reference/wf/sink.hpp:56-``): terminal
consumer.  The user function receives each tuple, and ``None`` once at
end-of-stream (the reference passes an empty ``std::optional`` at EOS).

Columnar mode (``withColumnarSink``): on TPU→Sink edges the user function
instead receives one :class:`SinkColumns` per device batch — the payload as
SoA numpy columns plus the timestamp lane — skipping per-record Python
object construction entirely (the egress twin of the columnar ingest path,
``windflow_tpu/io``; reference GPU→CPU bulk D2H,
``keyby_emitter_gpu.hpp:594-638``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from windflow_tpu.basic import RoutingMode
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


@dataclasses.dataclass
class SinkColumns:
    """One device batch delivered columnar: ``cols`` mirrors the payload
    pytree with ``[n]``-leading numpy arrays; ``tss`` is int64 ``[n]``."""

    cols: Any
    tss: Any
    watermark: int

    def __len__(self) -> int:
        return len(self.tss)


class SinkReplica(Replica):
    def __init__(self, op: "Sink", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.fn, 1)
        self._pending = []          # deferred device batches (columnar)

    def process_single(self, item, ts, wm):
        self._fn(item, self.context)

    def process_device_batch(self, batch):
        # A sink fed directly by a TPU operator pulls the batch to host
        # (reference GPU→CPU boundary): columnar sinks get the SoA lanes in
        # one bulk copy, record sinks get per-tuple dicts.  The egress copy
        # moves the timestamp and validity lanes too, so the D2H counter
        # uses the shared whole-batch definition (batch.transfer_nbytes).
        from windflow_tpu.batch import transfer_nbytes
        self.stats.d2h_bytes += transfer_nbytes(batch)
        if self.op.columnar:
            # Deferred conversion: hold the last ``defer`` batches and pull
            # the oldest — JAX dispatch is asynchronous, so the device→host
            # transfer of batch i overlaps the compute of batches i+1.. and
            # the per-transfer link latency leaves the critical path (the
            # reference hides D2H behind per-batch CUDA streams the same
            # way).  EOS drains the queue.
            self._pending.append(batch)
            if len(self._pending) > self.op.columnar_defer:
                # drain the whole queue in ONE device->host transfer
                pend, self._pending = self._pending, []
                self._deliver_columns(pend)
            return
        from windflow_tpu.batch import device_to_host
        hb = device_to_host(batch)
        for item, ts in zip(hb.items, hb.tss):
            self.context._set_context(ts, batch.watermark)
            self._fn(item, self.context)

    def _deliver_columns(self, batches):
        from windflow_tpu.batch import device_to_columns_multi
        for b, (cols, tss) in zip(batches,
                                  device_to_columns_multi(batches)):
            if len(tss):
                self.context._set_context(int(tss[-1]), b.watermark)
                self._fn(SinkColumns(cols, tss, b.watermark), self.context)

    def on_eos(self):
        if self._pending:
            self._deliver_columns(self._pending)
            self._pending = []
        self._fn(None, self.context)


class Sink(Operator):
    replica_class = SinkReplica
    is_terminal = True

    def __init__(self, fn: Callable[[Optional[Any]], None], name: str = "sink",
                 parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None, columnar: bool = False,
                 columnar_defer: int = 2) -> None:
        super().__init__(name, parallelism, routing=routing,
                         key_extractor=key_extractor)
        self.fn = fn
        #: columnar sinks receive SinkColumns per device batch instead of
        #: per-record dicts (host-batch edges still deliver records)
        self.columnar = columnar
        #: batches held before conversion (transfer/compute overlap); the
        #: user callback trails the stream by up to this many batches
        self.columnar_defer = max(0, columnar_defer)
