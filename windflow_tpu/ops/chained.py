"""Operator chaining (fusion).

The reference fuses same-parallelism FORWARD operators into one thread
(``/root/reference/wf/multipipe.hpp:553-569`` via ``combine_with_laststage``) to
save queue hops.  Here fusion has two forms, both cheaper than thread fusion:

* Host operators compose into one :class:`ChainedHost` replica — a closure
  pipeline with zero intermediate batching.
* TPU operators compose into one :class:`ChainedTPU` whose stages trace into a
  **single XLA program**, so map/filter chains fuse into one pass over HBM —
  the TPU analogue the reference cannot express (each CUDA op is a separate
  kernel launch even when chained).
"""

from __future__ import annotations

from typing import Callable, List, Tuple

from windflow_tpu.basic import WindFlowError
from windflow_tpu.batch import DeviceBatch
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica
from windflow_tpu.ops.filter_op import Filter
from windflow_tpu.ops.flatmap_op import FlatMap
from windflow_tpu.ops.map_op import Map
from windflow_tpu.ops.tpu import FilterTPU, MapTPU, _TPUReplica


# ---------------------------------------------------------------------------
# Host-side fusion
# ---------------------------------------------------------------------------

def _host_specs(op) -> List[Tuple[str, Callable]]:
    if isinstance(op, ChainedHost):
        return op.specs
    if isinstance(op, Map):
        return [("map", adapt(op.fn, 1))]
    if isinstance(op, Filter):
        return [("filter", adapt(op.fn, 1))]
    if isinstance(op, FlatMap):
        return [("flatmap", adapt(op.fn, 2))]
    raise WindFlowError(f"cannot chain operator type {type(op).__name__}")


class _ChainShipper:
    __slots__ = ("call", "ts", "wm", "ctx")

    def __init__(self):
        self.call = None
        self.ts = 0
        self.wm = 0
        self.ctx = None

    def push(self, item):
        self.call(item, self.ts, self.wm, self.ctx)


class ChainedHostReplica(Replica):
    copy_on_shared = True  # fused map/filter stages may mutate in place

    def __init__(self, op: "ChainedHost", index: int) -> None:
        super().__init__(op, index)
        self._exp = 0

        def tail(item, ts, wm, ctx):
            self.stats.outputs_sent += 1
            # append the per-input output index: a fused flatmap emits
            # several outputs per input and each needs a distinct origin
            # id (same contract as flatmap_op.Shipper)
            tid = self.cur_tid
            if tid is not None:
                tid = tid + (self._exp,)
                self._exp += 1
            self.emitter.emit(item, ts, wm, tid=tid)

        call = tail
        for kind, fn in reversed(op.specs):
            call = self._make_stage(kind, fn, call)
        self._head = call

    def _make_stage(self, kind, fn, nxt):
        if kind == "map":
            def stage(item, ts, wm, ctx):
                out = fn(item, ctx)
                nxt(out if out is not None else item, ts, wm, ctx)
        elif kind == "filter":
            def stage(item, ts, wm, ctx):
                if fn(item, ctx):
                    nxt(item, ts, wm, ctx)
        else:  # flatmap
            shipper = _ChainShipper()
            shipper.call = nxt

            def stage(item, ts, wm, ctx):
                shipper.ts = ts
                shipper.wm = wm
                shipper.ctx = ctx
                fn(item, shipper, ctx)
        return stage

    def process_single(self, item, ts, wm):
        self._exp = 0
        self._head(item, ts, wm, self.context)


class ChainedHost(Operator):
    replica_class = ChainedHostReplica

    def __init__(self, specs, name, parallelism, routing, output_batch_size,
                 key_extractor):
        super().__init__(name, parallelism, routing=routing,
                         output_batch_size=output_batch_size,
                         key_extractor=key_extractor)
        self.specs = specs


# ---------------------------------------------------------------------------
# TPU-side fusion: one XLA program for the whole chain
# ---------------------------------------------------------------------------

def _tpu_specs(op):
    if isinstance(op, ChainedTPU):
        return op.specs
    if isinstance(op, MapTPU):
        if op.batch_fn:
            return [("batch_map", op.fn)]
        return [("map", op.fn)]
    if isinstance(op, FilterTPU):
        return [("filter", op.fn)]
    raise WindFlowError(f"cannot chain TPU operator type {type(op).__name__}")


class ChainedTPUReplica(_TPUReplica):
    pass


class ChainedTPU(Operator):
    replica_class = ChainedTPUReplica

    def __init__(self, specs, name, parallelism, routing, key_extractor):
        super().__init__(name, parallelism, routing=routing, is_tpu=True,
                         key_extractor=key_extractor)
        self.specs = specs
        # The step machinery IS the fusion executor's chain program
        # (windflow_tpu/fusion FusedStatelessExec): a ChainedTPU is the
        # one-op fused segment, so pairwise chain() and whole-chain
        # fusion share a single implementation of the spec loop,
        # downstream key extraction (the keys lane the old step silently
        # dropped), and two-phase input donation.  Lazy import: the
        # executor reads specs back through _tpu_specs below.
        from windflow_tpu.fusion.executor import FusedStatelessExec
        self._chain = FusedStatelessExec(name, [self])

    def set_downstream_key_extractor(self, key_fn) -> None:
        """Forward the keys lane through the chain: the downstream KEYBY
        consumer's extractor runs inside this program on the chain's
        OUTPUT records — exactly what the consumer's own in-program
        extraction would compute — and rides the output batch's keys
        lane, so neither the keyby emitter nor a stateful consumer's
        ``.key_extract`` program pays a second dispatch.  Called by
        ``PipeGraph._build`` when this op feeds exactly one device KEYBY
        consumer."""
        self._chain.set_downstream_key_extractor(key_fn)

    def enable_input_donation(self) -> None:
        """Donate the payload/valid input buffers to the chain program
        (the sweep-ledger donation-miss fix): every staged batch's lanes
        are fresh, unshared arrays, so XLA may write outputs in place
        instead of copying whole buffers.  Only ``PipeGraph._build``
        calls this, after proving the inputs unshared — device keyby /
        broadcast / split edges alias one payload across destinations
        and stay copy-on-write.  The aliasing half is checked against
        the first batch's concrete specs (donation_aliases_cleanly)."""
        self._chain.enable_input_donation()

    def _step(self, batch: DeviceBatch) -> DeviceBatch:
        return self._chain.step(batch)


def tpu_chainable(op: Operator) -> bool:
    """True when :func:`fuse` can provably fold ``op`` into a single-XLA-
    program :class:`ChainedTPU` stage TODAY (the pairwise fusion
    ``MultiPipe.chain`` applies).  The fusion advisor
    (windflow_tpu/analysis/fusion.py) generalizes from this predicate:
    chains of ``tpu_chainable`` ops are "provable now", while window /
    reduce / stateful tails need the whole-chain-fusion refactor the
    advisor's plan is sized for."""
    return isinstance(op, (MapTPU, FilterTPU, ChainedTPU))


def fuse(a: Operator, b: Operator) -> Operator:
    """Fuse two chainable operators into one stage."""
    name = f"{a.name}|{b.name}"
    if a.is_tpu:
        fused = ChainedTPU(_tpu_specs(a) + _tpu_specs(b), name,
                           a.parallelism, a.routing, a.key_extractor)
    else:
        fused = ChainedHost(_host_specs(a) + _host_specs(b), name,
                            a.parallelism, a.routing, b.output_batch_size,
                            a.key_extractor)
    closers = [f for f in (a.closing_func, b.closing_func) if f is not None]
    if closers:
        # the fused replica terminates once; run every constituent's closer
        from windflow_tpu.meta import adapt
        adapted = [adapt(f, 0) for f in closers]

        def closing(ctx):
            for f in adapted:
                f(ctx)
        fused.closing_func = closing
    return fused
